"""Compressed-page pass-through: classify, slice and host-decode raw Parquet pages.

The JPEG path proved the shape (ISSUE 2 → docs/device_decode.rst): split the
codec, ship the *compressed* representation over the host↔device link, finish
on the accelerator. This module generalizes that template to Parquet's own
page compression (ROADMAP item 3, grounded in "CODAG: Characterizing and
Optimizing Decompression Algorithms for GPUs", PAPERS.md — decompression is
bandwidth-bound and belongs on the accelerator):

- :func:`walk_pages` parses the thrift-compact page headers inside one raw
  column-chunk byte span (the spans FooterCache already stores) and classifies
  every page: dictionary/data page, codec, encoding, value count.
- :func:`classify_chunk` decides **eligibility** from the footer alone:
  fixed-width primitive columns (INT32/INT64/FLOAT/DOUBLE), flat
  (no nesting/repetition), provably null-free (statistics ``null_count == 0``
  or ``max_definition_level == 0``), codec snappy or uncompressed, encodings
  PLAIN / RLE_DICTIONARY. Everything else degrades **per column** to the
  classic pyarrow host-inflate path (``cause=pagedec_ineligible``).
- :class:`PassthroughColumn` carries the raw compressed pages of eligible
  columns through the existing delivery path (worker → wire → batcher) as an
  opaque columnar value with **page-granular** zero-copy row slicing — the
  loader's batch cutting selects covering pages plus a (skip, take) window
  instead of decoding on the host.
- The **numpy reference decoder** (:func:`decode_chunk_numpy` and friends) is
  the correctness twin of the device kernels
  (:mod:`petastorm_tpu.ops.pagedec_kernels`) and the CPU/host fallback —
  bit-identical to pyarrow's own column decode (pinned by tests the way the
  PR 5 jpeg_decoder fix was). Snappy inflation itself delegates to
  ``pyarrow.Codec`` (the exact library pyarrow's reader uses); the page /
  definition-level / RLE-dictionary layer — which pyarrow does not expose —
  is reimplemented here in vectorized numpy.

Corruption contract (ISSUE 14 satellite): a truncated or bit-flipped page
raises :class:`~petastorm_tpu.errors.PagedecCorruptError`
(``cause=pagedec_corrupt``) — a PERMANENT error (never burned as transient
retries) that the PR 7 poison policy quarantines; every decoder bounds-checks
offsets/lengths before touching memory, so corrupt input can never read out
of bounds.
"""
from __future__ import annotations

import struct
import threading

import numpy as np

from petastorm_tpu.errors import PagedecCorruptError
from petastorm_tpu.obs.metrics import default_registry

# Parquet page types (format/PageType)
PAGE_DATA = 0
PAGE_INDEX = 1
PAGE_DICT = 2
PAGE_DATA_V2 = 3

# Parquet encodings (format/Encoding)
ENC_PLAIN = 0
ENC_PLAIN_DICT = 2
ENC_RLE = 3
ENC_BIT_PACKED = 4
ENC_RLE_DICT = 8

#: physical types with a fixed byte width the device kernels reconstruct
_FIXED_WIDTH_TYPES = {
    "INT32": np.dtype("<i4"),
    "INT64": np.dtype("<i8"),
    "FLOAT": np.dtype("<f4"),
    "DOUBLE": np.dtype("<f8"),
}

#: codecs the pass-through ships raw (zstd is *classified* by the walker but
#: stays ineligible until a zstd device kernel lands — shipping bytes the
#: device cannot inflate would just move the host decode downstream)
_PASSTHROUGH_CODECS = ("UNCOMPRESSED", "SNAPPY")
_KNOWN_CODECS = ("UNCOMPRESSED", "SNAPPY", "ZSTD")


# -- thrift compact page-header parsing ------------------------------------------------
#
# Page headers are thrift-compact structs inline in the data stream (NOT in
# the footer pyarrow parses for us). The subset below covers every field the
# classifier needs and skips the rest structurally — statistics blobs, future
# fields — so new writer versions degrade to "ineligible", never to a crash.

def _varint(buf, pos, end):
    out = 0
    shift = 0
    while True:
        if pos >= end or shift > 63:
            raise PagedecCorruptError("truncated varint in page header")
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not (b & 0x80):
            return out, pos
        shift += 7


def _zigzag(v):
    return (v >> 1) ^ -(v & 1)


def _parse_compact_struct(buf, pos, end, depth=0):
    """One thrift-compact struct → ``({field_id: value}, next_pos)``; nested
    structs parse into dicts, lists into Python lists. Bounds-checked: any
    walk past ``end`` raises :class:`PagedecCorruptError`."""
    if depth > 8:
        raise PagedecCorruptError("page header nests deeper than thrift allows")
    fields = {}
    last = 0
    while True:
        if pos >= end:
            raise PagedecCorruptError("truncated page header (no STOP field)")
        b = buf[pos]
        pos += 1
        if b == 0:
            return fields, pos
        delta = b >> 4
        t = b & 0x0F
        if delta:
            fid = last + delta
        else:
            v, pos = _varint(buf, pos, end)
            fid = _zigzag(v)
        last = fid
        if t in (1, 2):                      # BOOLEAN_TRUE / BOOLEAN_FALSE
            fields[fid] = (t == 1)
        elif t == 3:                         # BYTE
            if pos >= end:
                raise PagedecCorruptError("truncated byte field")
            fields[fid] = buf[pos]
            pos += 1
        elif t in (4, 5, 6):                 # I16 / I32 / I64
            v, pos = _varint(buf, pos, end)
            fields[fid] = _zigzag(v)
        elif t == 7:                         # DOUBLE
            if pos + 8 > end:
                raise PagedecCorruptError("truncated double field")
            fields[fid] = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif t == 8:                         # BINARY / STRING
            n, pos = _varint(buf, pos, end)
            if n < 0 or pos + n > end:
                raise PagedecCorruptError("binary field runs past the chunk")
            fields[fid] = bytes(buf[pos:pos + n])
            pos += n
        elif t in (9, 10):                   # LIST / SET
            if pos >= end:
                raise PagedecCorruptError("truncated list header")
            hdr = buf[pos]
            pos += 1
            n = hdr >> 4
            et = hdr & 0x0F
            if n == 15:
                n, pos = _varint(buf, pos, end)
            if n > 1 << 20:
                raise PagedecCorruptError("implausible list length %d" % n)
            vals = []
            for _ in range(n):
                if et == 12:
                    v, pos = _parse_compact_struct(buf, pos, end, depth + 1)
                elif et in (4, 5, 6):
                    v, pos = _varint(buf, pos, end)
                    v = _zigzag(v)
                elif et == 8:
                    ln, pos = _varint(buf, pos, end)
                    if ln < 0 or pos + ln > end:
                        raise PagedecCorruptError(
                            "list element runs past the chunk")
                    v = bytes(buf[pos:pos + ln])
                    pos += ln
                elif et == 3:
                    if pos >= end:
                        raise PagedecCorruptError("truncated list byte")
                    v = buf[pos]
                    pos += 1
                else:
                    raise PagedecCorruptError(
                        "unsupported thrift list element type %d" % et)
                vals.append(v)
            fields[fid] = vals
        elif t == 12:                        # STRUCT
            fields[fid], pos = _parse_compact_struct(buf, pos, end, depth + 1)
        else:
            raise PagedecCorruptError("unsupported thrift field type %d" % t)


class PageInfo:
    """One classified page inside a column chunk (offsets chunk-relative)."""

    __slots__ = ("kind", "encoding", "def_encoding", "num_values",
                 "header_offset", "payload_offset", "comp_size", "uncomp_size")

    def __init__(self, kind, encoding, def_encoding, num_values,
                 header_offset, payload_offset, comp_size, uncomp_size):
        self.kind = kind
        self.encoding = encoding
        self.def_encoding = def_encoding
        self.num_values = num_values
        self.header_offset = header_offset
        self.payload_offset = payload_offset
        self.comp_size = comp_size
        self.uncomp_size = uncomp_size

    def __repr__(self):
        return ("PageInfo(kind=%d, enc=%s, n=%d, hdr@%d, payload@%d+%d->%d)"
                % (self.kind, self.encoding, self.num_values,
                   self.header_offset, self.payload_offset, self.comp_size,
                   self.uncomp_size))


def walk_pages(chunk, expected_values=None):
    """Parse every page header in one raw column-chunk byte span.

    Returns ``(dict_page_or_None, [data PageInfo, ...])``. Raises
    :class:`PagedecCorruptError` on malformed headers, payloads running past
    the chunk, or a data-page value total that disagrees with
    ``expected_values`` (the footer's row count) — the never-read-out-of-
    bounds gate runs here, before any payload is touched."""
    buf = memoryview(chunk)
    end = len(buf)
    pos = 0
    dict_page = None
    data_pages = []
    total = 0
    while pos < end:
        hdr, payload_pos = _parse_compact_struct(buf, pos, end)
        kind = hdr.get(1)
        uncomp = hdr.get(2)
        comp = hdr.get(3)
        if kind is None or uncomp is None or comp is None \
                or comp < 0 or uncomp < 0:
            raise PagedecCorruptError("page header missing type/size fields")
        if payload_pos + comp > end:
            raise PagedecCorruptError(
                "page payload (%d bytes at %d) runs past the %d-byte chunk"
                % (comp, payload_pos, end))
        if kind == PAGE_DICT:
            dph = hdr.get(7) or {}
            page = PageInfo(kind, dph.get(2, ENC_PLAIN), None,
                            int(dph.get(1, 0)), pos, payload_pos, comp, uncomp)
            if dict_page is not None:
                raise PagedecCorruptError("second dictionary page in one chunk")
            dict_page = page
        elif kind == PAGE_DATA:
            dph = hdr.get(5) or {}
            n = dph.get(1)
            if n is None or n < 0:
                raise PagedecCorruptError("data page header missing num_values")
            page = PageInfo(kind, dph.get(2, ENC_PLAIN), dph.get(3, ENC_RLE),
                            int(n), pos, payload_pos, comp, uncomp)
            data_pages.append(page)
            total += page.num_values
        elif kind == PAGE_DATA_V2:
            dph = hdr.get(8) or {}
            n = dph.get(1)
            if n is None or n < 0:
                raise PagedecCorruptError("v2 data page header missing num_values")
            # classified (the caller's eligibility check rejects v2 for now —
            # its levels live OUTSIDE the compressed block) but walked safely
            page = PageInfo(kind, dph.get(4, ENC_PLAIN), ENC_RLE, int(n),
                            pos, payload_pos, comp, uncomp)
            data_pages.append(page)
            total += page.num_values
        else:
            # index pages etc.: skip structurally
            pass
        pos = payload_pos + comp
    if expected_values is not None and total != expected_values:
        raise PagedecCorruptError(
            "chunk pages carry %d values, footer says %d" % (total,
                                                             expected_values))
    return dict_page, data_pages


# -- eligibility -----------------------------------------------------------------------

def chunk_byte_range(col):
    """``(start, length)`` byte span of one column chunk — dictionary page
    (when present) through the end of the data pages. The ONE definition of
    a chunk's raw span, shared by the local reader, the remote planner, and
    the page-index bookkeeping (three drifting copies would read different
    ranges for the same chunk)."""
    start = col.data_page_offset
    if col.dictionary_page_offset is not None:
        start = min(start, col.dictionary_page_offset)
    return start, col.total_compressed_size


class Eligibility:
    """A column chunk's pass-through verdict with the human-readable reason."""

    __slots__ = ("eligible", "reason", "dtype", "codec", "max_def")

    def __init__(self, eligible, reason, dtype=None, codec=None, max_def=0):
        self.eligible = eligible
        self.reason = reason
        self.dtype = dtype
        self.codec = codec
        self.max_def = max_def

    def __bool__(self):
        return self.eligible


_codec_ineligible_counters = {}
_codec_ineligible_lock = threading.Lock()


def _count_codec_ineligible(codec):
    """One column read locked out of the pass-through by a classified-but-
    kernel-less codec: warn once per codec
    (``cause=pagedec_codec_ineligible{codec=...}``) and count every
    occurrence, so operators can size the win of landing that kernel."""
    label = codec.lower()
    counter = _codec_ineligible_counters.get(label)
    if counter is None:
        with _codec_ineligible_lock:
            counter = _codec_ineligible_counters.get(label)
            if counter is None:
                counter = default_registry().counter(
                    "ptpu_pagedec_codec_ineligible_columns_total",
                    help="column reads whose codec the classifier knows but "
                         "has no device kernel for (full classic read)",
                    codec=label)
                _codec_ineligible_counters[label] = counter
    counter.inc()
    from petastorm_tpu.obs.log import degradation

    degradation(
        "pagedec_codec_ineligible{codec=%s}" % label,
        "pagedec: %s chunks are classified but have no device inflate "
        "kernel yet — these columns take the full classic host read "
        "(ptpu_pagedec_codec_ineligible_columns_total{codec=%s} counts how "
        "much of the store is locked out)", codec, label)


def classify_chunk(metadata, rg, col_idx):
    """Footer-only eligibility of row group ``rg``'s ``col_idx``-th column.

    This is the cheap first gate (no chunk bytes needed): physical type,
    nesting, codec, and provable null-freedom. The walker's per-page check
    (:func:`classify_pages`) runs after the raw bytes arrive."""
    col = metadata.row_group(rg).column(col_idx)
    sch = metadata.schema.column(col_idx)
    if "." in col.path_in_schema or sch.max_repetition_level > 0:
        return Eligibility(False, "nested or repeated column")
    dtype = _FIXED_WIDTH_TYPES.get(col.physical_type)
    if dtype is None:
        return Eligibility(False,
                           "non-fixed-width physical type %s" % col.physical_type)
    codec = col.compression
    if codec not in _PASSTHROUGH_CODECS:
        if codec in _KNOWN_CODECS:
            # zstd (ISSUE 19 satellite): the walker classifies these chunks
            # fine, but without a device inflate kernel they silently take
            # the full classic read — surface how much of the store is
            # locked out until the kernel lands
            _count_codec_ineligible(codec)
            reason = "codec %s classified but no device kernel yet" % codec
        else:
            reason = "unsupported codec %s" % codec
        return Eligibility(False, reason, dtype=dtype, codec=codec)
    max_def = sch.max_definition_level
    if max_def > 1:
        return Eligibility(False, "definition depth %d (nested optionality)"
                           % max_def, dtype=dtype, codec=codec)
    if max_def == 1:
        st = col.statistics
        if st is None or st.null_count is None or st.null_count != 0:
            return Eligibility(False, "null-freedom not provable from "
                               "statistics", dtype=dtype, codec=codec,
                               max_def=max_def)
    return Eligibility(True, "eligible", dtype=dtype, codec=codec,
                       max_def=max_def)


def classify_pages(dict_page, data_pages):
    """Second gate, after the walk: every page's encoding must be one the
    inflate stage (device kernels AND numpy twin) reconstructs. Returns
    ``(ok, reason)``."""
    if not data_pages:
        return False, "chunk has no data pages"
    if dict_page is not None and dict_page.encoding not in (ENC_PLAIN,
                                                            ENC_PLAIN_DICT):
        return False, "dictionary page encoding %d" % dict_page.encoding
    for page in data_pages:
        if page.kind == PAGE_DATA_V2:
            return False, "v2 data pages (uncompressed levels) not supported"
        if page.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
            if dict_page is None:
                return False, "dictionary-encoded page without a dictionary"
        elif page.encoding != ENC_PLAIN:
            return False, "data page encoding %d" % page.encoding
        if page.def_encoding not in (None, ENC_RLE):
            return False, "definition-level encoding %d" % page.def_encoding
    return True, "eligible"


# -- pass-through column ---------------------------------------------------------------

class PassthroughChunk:
    """The raw compressed pages of ONE eligible column chunk (immutable).

    ``buf`` is the chunk's full byte span exactly as stored; page offsets
    index into it. ``decode()``/``decode_window()`` are the numpy
    reference/CPU-fallback decode (bit-identical to pyarrow); the device
    kernels consume the same layout via
    :mod:`petastorm_tpu.ops.pagedec_kernels`. Decodes are PAGE-GRANULAR: a
    window decodes only its covering pages, so cutting one row group into
    many batches stays linear (boundary pages decode at most twice). Only
    the decoded *dictionary* is memoized (``_dict_cache``, bounded by the
    writer's dictionary-page limit and excluded from pickling) — memoizing
    whole decoded chunks would pin raw-sized arrays inside long-lived
    holders like the memcache."""

    __slots__ = ("buf", "codec", "dtype_str", "max_def", "dict_page",
                 "pages", "num_rows", "raw_nbytes", "_dict_cache")

    def __init__(self, buf, codec, dtype, max_def, dict_page, pages):
        self.buf = bytes(buf)
        self.codec = codec
        self.dtype_str = np.dtype(dtype).str
        self.max_def = int(max_def)
        self.dict_page = dict_page
        self.pages = tuple(pages)
        self.num_rows = sum(p.num_values for p in pages)
        #: what the classic path would have delivered for this chunk — the
        #: bytes the pass-through saves on the wire + PCIe
        self.raw_nbytes = self.num_rows * np.dtype(dtype).itemsize
        self._dict_cache = None

    def __getstate__(self):
        return {name: getattr(self, name) for name in self.__slots__
                if name != "_dict_cache"}

    def __setstate__(self, state):
        for name, value in state.items():
            setattr(self, name, value)
        self._dict_cache = None

    @property
    def dtype(self):
        return np.dtype(self.dtype_str)

    @property
    def nbytes(self):
        return len(self.buf)

    def page_starts(self):
        """Row offset of each data page's first value (cumulative counts)."""
        starts = [0]
        for p in self.pages:
            starts.append(starts[-1] + p.num_values)
        return starts

    def covering_pages(self, skip, take):
        """``(first_page, last_page_exclusive, row_base)`` of the pages a
        (skip, take) window touches; ``row_base`` is the first page's row
        offset within the chunk."""
        starts = self.page_starts()
        p0 = 0
        while p0 + 1 < len(self.pages) and starts[p0 + 1] <= skip:
            p0 += 1
        p1 = p0
        while p1 < len(self.pages) and starts[p1] < skip + take:
            p1 += 1
        return p0, p1, starts[p0]

    def dict_values(self):
        """The decoded dictionary page (memoized — small and re-used by
        every window of this chunk), or ``None``."""
        if self.dict_page is not None and self._dict_cache is None:
            self._dict_cache = decode_dict_values(self)
        return self._dict_cache

    def decode_window(self, skip, take):
        """Rows ``[skip, skip+take)`` via the numpy reference decode of the
        COVERING pages only."""
        if take <= 0:
            return np.empty((0,), dtype=self.dtype)
        p0, p1, base = self.covering_pages(skip, take)
        dict_values = self.dict_values()
        parts = [decode_data_page_numpy(self, page, dict_values)
                 for page in self.pages[p0:p1]]
        full = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return full[skip - base:skip - base + take]

    def decode(self):
        """Full-chunk numpy reference decode."""
        return self.decode_window(0, self.num_rows).copy()


class PassthroughColumn:
    """An opaque columnar value of raw compressed pages with page-granular
    row slicing — what rides the worker → wire → batcher → loader path in
    place of the decoded ndarray.

    ``parts`` is a list of ``(chunk, skip, take)`` windows: batch cuts slice
    by adjusting windows (zero-copy on the underlying buffers; the covering
    pages are selected at inflate time), and cross-row-group concatenation
    just chains windows. ``materialize()`` is the host fallback decode."""

    __slots__ = ("parts", "dtype_str")

    def __init__(self, parts):
        if not parts:
            raise ValueError("PassthroughColumn needs at least one window")
        self.parts = list(parts)
        self.dtype_str = parts[0][0].dtype_str

    @classmethod
    def from_chunk(cls, chunk):
        return cls([(chunk, 0, chunk.num_rows)])

    @property
    def dtype(self):
        return np.dtype(self.dtype_str)

    @property
    def is_passthrough(self):
        return True

    def __len__(self):
        return sum(take for _c, _s, take in self.parts)

    @property
    def shape(self):
        return (len(self),)

    @property
    def nbytes(self):
        """Compressed payload bytes held (budget accounting: memcache etc.)."""
        return sum(c.nbytes for c, _s, _t in self.parts)

    @property
    def raw_nbytes(self):
        """What the decoded rows of this window will occupy."""
        return len(self) * self.dtype.itemsize

    @property
    def shipped_nbytes(self):
        """Bytes that actually cross the wire/PCIe for this window: the
        compressed payload of the COVERING pages plus each window's small
        page-table overhead (the ≤60%-of-raw number the bench asserts)."""
        total = 0
        for chunk, skip, take in self.parts:
            starts = chunk.page_starts()
            if chunk.dict_page is not None:
                total += chunk.dict_page.comp_size
            for i, page in enumerate(chunk.pages):
                if starts[i + 1] <= skip or starts[i] >= skip + take:
                    continue
                total += page.comp_size + 16  # ~page-table row
        return total

    def __getitem__(self, key):
        if not isinstance(key, slice):
            raise TypeError(
                "PassthroughColumn supports slice windows only (materialize() "
                "for element access)")
        start, stop, step = key.indices(len(self))
        if step != 1:
            raise ValueError("PassthroughColumn slices must be contiguous")
        return self.slice(start, stop - start)

    def slice(self, offset, length):
        """A new column over rows ``[offset, offset+length)`` — window
        arithmetic only, no decode, no copy."""
        if offset < 0 or length < 0 or offset + length > len(self):
            raise IndexError("slice [%d, %d) outside %d rows"
                             % (offset, offset + length, len(self)))
        out = []
        pos = 0
        for chunk, skip, take in self.parts:
            lo = max(offset, pos)
            hi = min(offset + length, pos + take)
            if hi > lo:
                out.append((chunk, skip + (lo - pos), hi - lo))
            pos += take
        if not out:
            out = [(self.parts[0][0], 0, 0)]
        return PassthroughColumn(out)

    @classmethod
    def concat(cls, columns):
        parts = []
        for col in columns:
            parts.extend(col.parts)
        return cls(parts)

    def detach(self):
        """Buffers are owned ``bytes`` (never slab views): nothing to copy."""
        return self

    def materialize(self):
        """Host-side decode of this window via the numpy reference twin
        (page-granular: only the covering pages of each window decode)."""
        outs = []
        for chunk, skip, take in self.parts:
            if take == 0:
                continue
            outs.append(chunk.decode_window(skip, take))
        if not outs:
            return np.empty((0,), dtype=self.dtype)
        return outs[0].copy() if len(outs) == 1 else np.concatenate(outs)

    def __reduce__(self):
        return (_rebuild_column, (self.parts,))

    def __repr__(self):
        return ("PassthroughColumn(rows=%d, windows=%d, dtype=%s, "
                "compressed=%dB, raw=%dB)"
                % (len(self), len(self.parts), self.dtype_str, self.nbytes,
                   self.raw_nbytes))


def _rebuild_column(parts):
    return PassthroughColumn(parts)


def is_passthrough(value):
    return getattr(value, "is_passthrough", False) is True


def materialize_columns(columns, cause=None):
    """Replace every pass-through value in a columnar dict with its decoded
    ndarray (host reference decode). ``cause`` names the degradation to count
    (warn-once) when anything was actually materialized — the seams where
    host inflate is a *fallback*, not the design (shuffling buffers, plain
    Reader consumers are the designed host path and pass ``cause=None``)."""
    out = None
    names = []
    for name, value in columns.items():
        if is_passthrough(value):
            if out is None:
                out = dict(columns)
            out[name] = value.materialize()
            names.append(name)
    if out is not None and cause is not None:
        from petastorm_tpu.obs.log import degradation

        degradation(cause, "pass-through column(s) %s inflated on host; "
                    "the device inflate stage was bypassed at this seam",
                    sorted(names))
    return columns if out is None else out


# -- numpy reference decoders ----------------------------------------------------------

def _decompress_page(codec, payload, uncomp_size):
    """One page payload → raw bytes, via the same codec library pyarrow's own
    reader uses. Corruption (bad stream, wrong length) classifies as
    :class:`PagedecCorruptError`."""
    if codec == "UNCOMPRESSED":
        if len(payload) != uncomp_size:
            raise PagedecCorruptError(
                "uncompressed page is %d bytes, header says %d"
                % (len(payload), uncomp_size))
        return bytes(payload)
    if uncomp_size > 1 << 30:
        raise PagedecCorruptError(
            "implausible uncompressed page size %d" % uncomp_size)
    import pyarrow as pa

    try:
        raw = bytes(pa.Codec(codec.lower()).decompress(
            bytes(payload), uncomp_size))
    except Exception as e:  # noqa: BLE001 — any codec failure IS corruption here
        raise PagedecCorruptError(
            "%s page failed to inflate (%s)" % (codec, e)) from e
    if len(raw) != uncomp_size:
        raise PagedecCorruptError(
            "%s page inflated to %d bytes, header says %d"
            % (codec, len(raw), uncomp_size))
    return raw


def rle_bp_decode(buf, bit_width, count):
    """Parquet RLE/bit-packed hybrid → ``count`` int64 values.

    Vectorized numpy: the run table is scanned sequentially (runs ≪ values),
    RLE runs fill slices, bit-packed groups unpack via a bit-matrix gather —
    the same two-phase shape the device kernel uses (CODAG: sequential scan,
    parallel expansion). Bounds-checked throughout."""
    if bit_width < 0 or bit_width > 32:
        raise PagedecCorruptError("RLE bit width %d out of range" % bit_width)
    out = np.zeros(count, dtype=np.int64)
    if count == 0:
        return out
    if bit_width == 0:
        return out
    data = memoryview(buf)
    end = len(data)
    pos = 0
    filled = 0
    byte_width = (bit_width + 7) // 8
    while filled < count:
        if pos >= end:
            raise PagedecCorruptError(
                "RLE stream exhausted at %d of %d values" % (filled, count))
        header, pos = _varint(data, pos, end)
        if header & 1:
            # bit-packed run: (header >> 1) groups of 8 values
            groups = header >> 1
            n = groups * 8
            nbytes = groups * bit_width
            if pos + nbytes > end:
                raise PagedecCorruptError("bit-packed run past stream end")
            packed = np.frombuffer(data, dtype=np.uint8, count=nbytes,
                                   offset=pos)
            pos += nbytes
            bits = np.unpackbits(packed, bitorder="little")
            vals = bits.reshape(n, bit_width).astype(np.int64)
            weights = (1 << np.arange(bit_width, dtype=np.int64))
            vals = vals @ weights
            take = min(n, count - filled)
            # trailing values in the final group are padding, legal per spec
            out[filled:filled + take] = vals[:take]
            filled += take
        else:
            run = header >> 1
            if run <= 0:
                raise PagedecCorruptError("zero-length RLE run")
            if pos + byte_width > end:
                raise PagedecCorruptError("RLE run value past stream end")
            value = int.from_bytes(bytes(data[pos:pos + byte_width]), "little")
            pos += byte_width
            take = min(run, count - filled)
            out[filled:filled + take] = value
            filled += take
    return out


def _decode_def_levels(raw, num_values, max_def):
    """The v1 data page's definition-level block: returns the byte offset of
    the values section. Null-free is an *eligibility invariant* — a zero
    definition level here means the footer statistics lied, which is
    corruption, not a fallback."""
    if max_def == 0:
        return 0
    if len(raw) < 4:
        raise PagedecCorruptError("page too short for definition-level block")
    block_len = struct.unpack_from("<I", raw, 0)[0]
    if 4 + block_len > len(raw):
        raise PagedecCorruptError("definition-level block past page end")
    levels = rle_bp_decode(raw[4:4 + block_len], 1, num_values)
    if not (levels == 1).all():
        raise PagedecCorruptError(
            "null value in a chunk whose statistics claimed null_count=0")
    return 4 + block_len


def decode_dict_values(chunk):
    """The dictionary page's PLAIN values as a typed numpy array (or None)."""
    page = chunk.dict_page
    if page is None:
        return None
    payload = chunk.buf[page.payload_offset:page.payload_offset + page.comp_size]
    raw = _decompress_page(chunk.codec, payload, page.uncomp_size)
    dtype = chunk.dtype
    if len(raw) < page.num_values * dtype.itemsize:
        raise PagedecCorruptError("dictionary page shorter than its %d values"
                                  % page.num_values)
    return np.frombuffer(raw, dtype=dtype, count=page.num_values)


def decode_data_page_numpy(chunk, page, dict_values):
    """One v1 data page → typed numpy values (the reference decode)."""
    payload = chunk.buf[page.payload_offset:page.payload_offset + page.comp_size]
    raw = _decompress_page(chunk.codec, payload, page.uncomp_size)
    off = _decode_def_levels(raw, page.num_values, chunk.max_def)
    values = raw[off:]
    dtype = chunk.dtype
    if page.encoding == ENC_PLAIN:
        need = page.num_values * dtype.itemsize
        if len(values) < need:
            raise PagedecCorruptError(
                "PLAIN page holds %d bytes, needs %d" % (len(values), need))
        return np.frombuffer(values, dtype=dtype, count=page.num_values)
    if page.encoding in (ENC_PLAIN_DICT, ENC_RLE_DICT):
        if dict_values is None:
            raise PagedecCorruptError("dictionary-encoded page without a "
                                      "dictionary")
        if len(values) < 1:
            raise PagedecCorruptError("dictionary page body empty")
        bit_width = values[0]
        idx = rle_bp_decode(values[1:], bit_width, page.num_values)
        if idx.size and (idx.max(initial=0) >= len(dict_values)
                         or idx.min(initial=0) < 0):
            raise PagedecCorruptError(
                "dictionary index out of range (max %d, dictionary %d)"
                % (int(idx.max(initial=0)), len(dict_values)))
        return dict_values[idx]
    raise PagedecCorruptError("unsupported data page encoding %d"
                              % page.encoding)


def decode_chunk_numpy(chunk):
    """Full column-chunk reference decode: every data page, concatenated.
    Bit-identical to pyarrow's decode of the same chunk (pinned in
    tests/test_pagedec.py, incl. the seeded fuzz corpora)."""
    dict_values = decode_dict_values(chunk)
    parts = [decode_data_page_numpy(chunk, page, dict_values)
             for page in chunk.pages]
    if not parts:
        return np.empty((0,), dtype=chunk.dtype)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


# -- chunk construction (the worker's entry point) -------------------------------------

def build_chunk(raw, eligibility, expected_values=None, require_saving=True):
    """Walk + page-classify one raw chunk span into a :class:`PassthroughChunk`.

    Returns ``(chunk_or_None, reason)`` — ``None`` means the *pages* turned
    out ineligible (footer said yes, stream said no: e.g. a mid-column
    dictionary-overflow fallback to an unsupported encoding). Corruption
    raises; ineligibility degrades.

    ``require_saving``: a chunk whose compressed span is not smaller than its
    decoded rows (incompressible float noise dictionary-encoded into a
    *bigger* stream — measured on random f32) is pointless to pass through:
    shipping it raw-decoded costs fewer link bytes. Such chunks degrade with
    reason ``no byte saving`` (CODAG only wins when the compressed
    representation is the smaller one)."""
    dict_page, data_pages = walk_pages(raw, expected_values)
    ok, reason = classify_pages(dict_page, data_pages)
    if not ok:
        return None, reason
    chunk = PassthroughChunk(raw, eligibility.codec, eligibility.dtype,
                             eligibility.max_def, dict_page, data_pages)
    if require_saving and chunk.nbytes >= chunk.raw_nbytes:
        return None, ("no byte saving (compressed %d >= raw %d)"
                      % (chunk.nbytes, chunk.raw_nbytes))
    return chunk, reason


# -- page-index cache (the remote planner's page-granular split points) ----------------

class PageIndexCache:
    """Process-wide memo of walked page boundaries keyed by
    ``(path, row_group, column)`` — Parquet keeps page offsets inline in the
    data (not in the footer), so the remote range planner can only split a
    big chunk fetch *at page boundaries* once a previous walk has seen them.
    First read of a chunk fetches it at request-size granularity; re-reads
    split page-granular. Bounded count LRU (gets refresh recency — hot
    re-read chunks must not be evicted by insertion age).

    Walked boundaries are also published through the host-shared cache arena
    (ISSUE 17, key ``("pi", path, rg, column)``): a process that never walked
    a chunk still splits its FIRST fetch page-granular when any peer on the
    host has — the walk result is tiny (a tuple of ints), so a local miss
    maps the pickled memo and admits it locally."""

    def __init__(self, max_entries=4096):
        from collections import OrderedDict

        self._lock = threading.Lock()
        self._entries = OrderedDict()
        self._max = int(max_entries)

    @staticmethod
    def _arena():
        from petastorm_tpu.io import arena as arena_mod

        return arena_mod.process_arena()

    def put(self, path, rg, column, chunk_offset, page_offsets):
        key = (path, rg, column)
        entry = (int(chunk_offset), tuple(page_offsets))
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
            elif len(self._entries) >= self._max:
                self._entries.popitem(last=False)
            self._entries[key] = entry
        arena_obj = self._arena()
        if arena_obj is not None:
            import pickle

            arena_obj.put_bytes(("pi",) + key,
                                pickle.dumps(entry, protocol=2))

    def get(self, path, rg, column):
        key = (path, rg, column)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is not None:
            return entry
        arena_obj = self._arena()
        if arena_obj is None:
            return None
        blob = arena_obj.get_bytes(("pi",) + key)
        if blob is None:
            return None
        import pickle

        try:
            entry = pickle.loads(blob)
            chunk_offset, page_offsets = entry
        except Exception:  # noqa: BLE001 — torn/foreign memo: treat as a miss
            return None
        with self._lock:  # admit locally: later gets skip the arena map
            if key not in self._entries and len(self._entries) >= self._max:
                self._entries.popitem(last=False)
            self._entries[key] = (int(chunk_offset), tuple(page_offsets))
            return self._entries[key]

    def clear(self):
        with self._lock:
            self._entries.clear()


_page_index_lock = threading.Lock()
_page_index = None


def shared_page_index():
    global _page_index
    with _page_index_lock:
        if _page_index is None:
            _page_index = PageIndexCache()
        return _page_index


# -- metrics ---------------------------------------------------------------------------

_default_counters = None


def pagedec_counters(registry=None):
    """The ``ptpu_pagedec_*`` family. The default-registry handle dict is
    memoized (module global): both hot callers — the worker's per-read
    fallback path and the loader's per-batch inflate stage — would otherwise
    pay six locked get-or-create lookups per call. Counter handles hold
    locks, so they are resolved here rather than cached on picklable
    objects."""
    global _default_counters
    if registry is None or registry is default_registry():
        if _default_counters is None:
            _default_counters = _build_counters(default_registry())
        return _default_counters
    return _build_counters(registry)


def _build_counters(reg):
    return {
        "pages": reg.counter(
            "ptpu_pagedec_pages_total",
            help="compressed pages shipped through the pass-through path"),
        "bytes_compressed": reg.counter(
            "ptpu_pagedec_bytes_compressed_total",
            help="compressed page bytes handed to the device-bound transfer"),
        "bytes_saved": reg.counter(
            "ptpu_pagedec_bytes_saved_h2d_total",
            help="raw-minus-compressed bytes the pass-through kept off the "
                 "host->device link"),
        "fallback_columns": reg.counter(
            "ptpu_pagedec_fallback_columns_total",
            help="column reads that degraded to the classic host-inflate path"),
        "host_inflate_columns": reg.counter(
            "ptpu_pagedec_host_inflate_columns_total",
            help="pass-through columns the loader inflated on HOST (CPU "
                 "backend, sharded delivery, or a kernel bail): the "
                 "compressed carry covered the wire only — the H2D leg "
                 "shipped the decoded array"),
        "inflate_seconds": reg.histogram(
            "ptpu_pagedec_inflate_seconds",
            help="device/host inflate stage latency per batch"),
    }
