"""Pinned-host staging pool for the transfer thread's ``device_put`` (ISSUE 6).

``jax.device_put`` from an arbitrary numpy array stages the H2D transfer from
pageable memory: the runtime either pins pages on the fly or bounces through
an internal staging buffer — per batch, on the hot transfer thread. This pool
keeps a small ring of page-locked (``mlock``) host slabs; the transfer thread
copies each batch's device-bound columns into a leased slab ONCE (the copy
the census charges to ``h2d_stage``) and launches ``device_put`` from
page-locked memory, so the DMA engine reads directly with no runtime-side
bounce. The slab's :class:`petastorm_tpu.io.lease.Lease` returns it to the
ring; the loader releases it after the transfer completes
(``jax.block_until_ready``), so a slab is never rewritten under an in-flight
DMA.

Degradations (never failures):

- ``mlock`` refused (``RLIMIT_MEMLOCK``, platform): slabs stay pageable but
  pooled — the allocator churn still disappears
  (``ptpu_degradations_total{cause="staging_unpinned"}``, warn-once).
- batch larger than a slab, or the ring starved: that batch stages the old
  way, straight from its own buffers (``staging_oversized`` — watch it grow
  and raise ``slab_bytes``).

The pool is only correct on backends whose ``device_put`` COPIES host memory
(TPU/GPU H2D — the target). The CPU backend zero-copy-aliases aligned numpy
arrays (see :func:`device_put_aliases_host`), which would hand consumers
arrays aliasing a recycled slab; the loader probes once and refuses/degrades
there.
"""
from __future__ import annotations

import ctypes
import mmap
import queue
import threading

import numpy as np

from petastorm_tpu.io.lease import Lease, count_copy
from petastorm_tpu.obs.log import degradation

#: per-array offsets inside a staging slab are rounded up to this (page-ish
#: alignment keeps each column's DMA descriptor friendly)
_STAGE_ALIGN = 256

_alias_probe_lock = threading.Lock()
_alias_probe = None


def device_put_aliases_host():
    """True when this process's default jax backend ALIASES host numpy memory
    in ``device_put`` (the CPU backend's zero-copy path) instead of copying.
    Probed once: transfer a small array, mutate the source, read the device
    value back. On aliasing backends staged-slab reuse (and slab-lease release
    after transfer) would corrupt delivered batches, so callers must hand
    ``device_put`` owned buffers there."""
    global _alias_probe
    if _alias_probe is None:
        with _alias_probe_lock:
            if _alias_probe is None:
                try:
                    import jax

                    probe = np.arange(64, dtype=np.float32)
                    dev = jax.device_put(probe)
                    # device_put is async: a copying backend may not have read
                    # the source yet — mutating it now would race the H2D copy
                    # and misclassify the backend as aliasing
                    jax.block_until_ready(dev)
                    probe[0] = -1.0
                    _alias_probe = bool(np.asarray(dev)[0] == -1.0)
                except Exception:  # noqa: BLE001 — no jax / no device: nothing
                    _alias_probe = False  # will ever alias
    return _alias_probe


def _try_mlock(buf, nbytes):
    """Page-lock ``buf`` via libc ``mlock``; False (with a warn-once
    degradation) when the platform or RLIMIT_MEMLOCK refuses."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        if libc.mlock(ctypes.c_void_p(addr), ctypes.c_size_t(nbytes)) == 0:
            return True
        err = ctypes.get_errno()
    except Exception as e:  # noqa: BLE001 — exotic libc/platform
        err = e
    degradation(
        "staging_unpinned",
        "mlock of a %d-byte staging slab refused (%s); H2D staging slabs are "
        "pooled but PAGEABLE — raise RLIMIT_MEMLOCK to pin them", nbytes, err)
    return False


class PinnedStagingPool:
    """Ring of page-locked host slabs the transfer thread stages device-bound
    batches into before ``device_put``.

    ``stage(arrays)`` returns ``(staged_arrays, lease)``: the staged dict maps
    the same keys to ndarray views INTO one slab (read-only — nothing may
    write a slab under DMA), and the lease returns the slab to the ring on
    release. Returns ``(arrays, None)`` unchanged when the batch cannot stage
    (oversized / ring starved) — callers need no special path.
    """

    def __init__(self, slab_bytes, num_slabs=3, acquire_timeout_s=2.0):
        if slab_bytes <= 0 or num_slabs <= 0:
            raise ValueError("slab_bytes and num_slabs must be positive")
        self.slab_bytes = int(slab_bytes)
        self._timeout = acquire_timeout_s
        self._slabs = []
        self._closed = False
        self.pinned = True
        for _ in range(num_slabs):
            buf = mmap.mmap(-1, self.slab_bytes)  # anonymous, page-aligned
            self._slabs.append(buf)
            if self.pinned and not _try_mlock(buf, self.slab_bytes):
                self.pinned = False  # degradation logged once; slabs stay pooled
        self._free = queue.Queue()
        for i in range(num_slabs):
            self._free.put(i)

    def __len__(self):
        return len(self._slabs)

    def stage(self, arrays):
        """Copy every ndarray in ``arrays`` into one leased slab; returns
        ``(staged_views, lease)`` or ``(arrays, None)`` on fallback."""
        items = [(k, v) for k, v in arrays.items() if isinstance(v, np.ndarray)]
        end = 0
        spans = []
        for _k, v in items:
            start = -(-end // _STAGE_ALIGN) * _STAGE_ALIGN
            end = start + v.nbytes
            spans.append(start)
        if end > self.slab_bytes:
            degradation(
                "staging_oversized",
                "batch of %d device-bound bytes exceeds the %d-byte staging "
                "slab; transferring from pageable memory (raise slab_bytes)",
                end, self.slab_bytes)
            return arrays, None
        if self._closed:
            return arrays, None
        try:
            slab_id = self._free.get(timeout=self._timeout)
        except queue.Empty:
            degradation(
                "staging_starved",
                "no free H2D staging slab within %.1fs (a transfer is not "
                "completing, or the ring is undersized); transferring from "
                "pageable memory", self._timeout)
            return arrays, None
        buf = memoryview(self._slabs[slab_id])
        staged = dict(arrays)
        total = 0
        for (name, v), start in zip(items, spans):
            flat = np.frombuffer(buf, dtype=np.uint8, count=v.nbytes,
                                 offset=start)
            dst = flat.view(v.dtype).reshape(v.shape)
            np.copyto(dst, v)
            dst.flags.writeable = False  # nothing may write a slab under DMA
            staged[name] = dst
            total += v.nbytes
        count_copy("h2d_stage", total)
        lease = Lease(release_cb=lambda: self._release(slab_id),
                      kind="staging_slab")
        return staged, lease

    def _release(self, slab_id):
        if not self._closed:
            self._free.put(slab_id)

    def close(self):
        """Unlock + unmap the slabs (idempotent). Outstanding views keep their
        mapping alive until they die (``BufferError`` guard, like the shm
        ring's close)."""
        self._closed = True
        slabs, self._slabs = self._slabs, []
        for buf in slabs:
            try:
                buf.close()
            except BufferError:
                pass  # exported staged views still alive: frees with them
            except Exception:  # noqa: BLE001 — exit path
                pass  # graftlint: disable=GL-O002 (exit path: munmap best-effort)
