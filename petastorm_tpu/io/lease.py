"""Buffer-lease ownership contract for the zero-copy batch path (ISSUE 6).

Every hop of the read path used to defend itself with a private memcpy: the
default wires copied read-only reconstructions writable, ``MemCache`` deep-
copied on both hit and admit, and the loader copied every slab view out before
buffering ("Zerrow: True Zero-Copy Arrow Pipelines in Bauplan", PAPERS.md,
names the cure: one buffer-ownership contract so a row group is materialized
once and only sliced/viewed afterward). This module is that contract:

- :class:`Lease` — a refcounted handle over read-only buffers owned by someone
  else (a slab ring, the memcache store, a pinned staging pool). ``retain()``
  adds a holder, ``release()`` drops one; the owner's reclaim callback fires
  exactly once, when the LAST holder releases. ``revoke()`` lets the owner
  invalidate outstanding views (executor rebuild on ``Reader.reset()``):
  lease-aware accessors then raise :class:`~petastorm_tpu.errors.LeaseRevoked`
  instead of returning garbage.
- :class:`LeasedBatch` — a columnar batch dict riding one or more leases.
  Column access checks revocation; ``writable()`` is the copy-on-write
  escalation (copy ONE column, count the bytes, only when a consumer actually
  writes).
- The **copy census** — ``count_copy(site, nbytes)`` at every remaining copy
  site, exported as ``ptpu_copy_bytes_total{site=...}`` counters on the PR-3
  default registry. ``petastorm-tpu-bench copies`` reads the census deltas to
  report bytes-copied-per-delivered-batch per path.

Discipline rules (enforced at runtime here, statically by graftlint GL-L001):
release exactly once per retain; never touch buffers after your release; a
dropped lease self-releases at GC (``__del__``) so an abandoned batch cannot
wedge a ring — but the drop is counted as ``ptpu_lease_leaked_total`` because
it makes slab return nondeterministic.
"""
from __future__ import annotations

import threading

import numpy as np

from petastorm_tpu.errors import LeaseError, LeaseRevoked

#: reserved key under which a batch's lease rides inside a tagged columnar
#: payload dict crossing a wire — the Reader pops it before exposing the batch
#: (generalizes the PR-2 ``__shm_lease__`` key to any lease-backed transport)
LEASE_KEY = "__lease__"


class _LeaseMetrics:
    """Process-wide ``ptpu_lease_*`` counters (built on first lease; the
    registry import stays off the module import path)."""

    __slots__ = ("acquired", "released", "retained", "cow", "revoked", "leaked",
                 "active")

    def __init__(self):
        from petastorm_tpu.obs.metrics import default_registry

        reg = default_registry()
        self.acquired = reg.counter(
            "ptpu_lease_acquired_total", help="leases created over borrowed buffers")
        self.released = reg.counter(
            "ptpu_lease_released_total",
            help="leases fully released (owner reclaim callback fired)")
        self.retained = reg.counter(
            "ptpu_lease_retained_total", help="additional holders added via retain()")
        self.cow = reg.counter(
            "ptpu_lease_cow_total",
            help="copy-on-write escalations (a consumer actually wrote)")
        self.revoked = reg.counter(
            "ptpu_lease_revoked_total",
            help="leases invalidated by their buffer owner (reset/teardown)")
        self.leaked = reg.counter(
            "ptpu_lease_leaked_total",
            help="leases reclaimed by GC instead of an explicit release")
        self.active = reg.gauge(
            "ptpu_lease_active", help="leases currently alive (refcount > 0)")


_metrics_lock = threading.Lock()
_metrics = None


def _lease_metrics():
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                _metrics = _LeaseMetrics()
    return _metrics


class Lease:
    """One refcounted claim over read-only buffers owned by someone else.

    The constructor is the acquire (refcount 1). ``release_cb`` is the owner's
    reclaim hook — return a slab to its ring, unpin a staging slot — and fires
    exactly once, when the count reaches zero. Thread-safe: batches cross the
    loader's producer/transfer/consumer threads and each may hold a retain.
    """

    __slots__ = ("_release_cb", "_refs", "_lock", "_revoked", "kind",
                 "__weakref__")

    def __init__(self, release_cb=None, kind="lease"):
        self._release_cb = release_cb
        self._refs = 1
        self._lock = threading.Lock()
        self._revoked = False
        self.kind = kind
        m = _lease_metrics()
        m.acquired.inc()
        m.active.inc()

    # -- refcount protocol --------------------------------------------------------------

    def retain(self):
        """Add a holder; returns ``self`` so call sites read
        ``batch_leases.append(lease.retain())``."""
        with self._lock:
            if self._refs <= 0:
                raise LeaseError(
                    "retain() on a fully-released %s lease: its buffers are "
                    "already back with their owner" % self.kind)
            self._refs += 1
        _lease_metrics().retained.inc()
        return self

    def release(self):
        """Drop one holder; the owner's reclaim callback runs at zero. Releasing
        past zero raises :class:`~petastorm_tpu.errors.LeaseError` (never
        silently double-frees a buffer into two consumers)."""
        with self._lock:
            if self._refs <= 0:
                raise LeaseError(
                    "release() on an already-released %s lease (double "
                    "release)" % self.kind)
            self._refs -= 1
            final = self._refs == 0
        if final:
            self._reclaim()

    def _reclaim(self):
        cb, self._release_cb = self._release_cb, None
        m = _lease_metrics()
        m.released.inc()
        m.active.dec()
        if cb is not None:
            cb()

    # -- revocation ---------------------------------------------------------------------

    def revoke(self):
        """Owner-side invalidation: outstanding views must no longer be read
        (the backing memory is being recycled). Holders keep their refcounts —
        their ``release()`` calls stay balanced — but :meth:`check` and every
        :class:`LeasedBatch` accessor raise from now on."""
        with self._lock:
            if self._revoked:
                return
            self._revoked = True
        _lease_metrics().revoked.inc()

    @property
    def revoked(self):
        return self._revoked

    @property
    def alive(self):
        return self._refs > 0

    def check(self):
        """Raise :class:`~petastorm_tpu.errors.LeaseRevoked` when the buffers
        behind this lease were invalidated by their owner."""
        if self._revoked:
            raise LeaseRevoked(
                "%s lease was revoked by its buffer owner (e.g. Reader.reset() "
                "rebuilt the executor backing this batch); the views are no "
                "longer valid" % self.kind)

    # -- GC safety net ------------------------------------------------------------------

    def __del__(self):
        try:
            with self._lock:
                refs, self._refs = self._refs, 0
            if refs > 0:
                # abandoned holder(s): reclaim so the owner's pool cannot wedge,
                # but count it — GC-timed buffer return is a caller bug
                _lease_metrics().leaked.inc()
                self._reclaim()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass  # graftlint: disable=GL-O002 (GC/exit path: metrics may be torn down)

    def __repr__(self):
        return "<Lease kind=%s refs=%d%s>" % (
            self.kind, self._refs, " REVOKED" if self._revoked else "")


class LeasedBatch(dict):
    """A columnar batch (``{name: ndarray}``) riding the lease(s) that own its
    buffers. Behaves as a plain dict for the hot paths; key access additionally
    checks revocation, and :meth:`writable` is the CoW escalation.

    ``leases`` holds the retained handles this batch owns; :meth:`release`
    drops them all exactly once (idempotent at the batch level so consumer
    teardown paths stay simple — the per-lease discipline is still enforced).
    """

    __slots__ = ("leases",)

    def __init__(self, columns=(), leases=()):
        super().__init__(columns)
        self.leases = tuple(leases)

    def _check(self):
        for lease in self.leases:
            lease.check()

    def __getitem__(self, key):
        self._check()
        return super().__getitem__(key)

    # every accessor that can hand out buffer views checks revocation too —
    # a consumer iterating ``batch.items()`` after Reader.reset() must get
    # LeaseRevoked, not views into a recycled slab
    def get(self, key, default=None):
        self._check()
        return super().get(key, default)

    def items(self):
        self._check()
        return super().items()

    def values(self):
        self._check()
        return super().values()

    def writable(self, name):
        """Copy-on-write escalation for ONE column: replaces the read-only view
        with an owned writable copy (counted in the copy census) and returns
        it. The lease keeps protecting the remaining view columns."""
        arr = self[name]
        if isinstance(arr, np.ndarray) and not arr.flags.writeable:
            _lease_metrics().cow.inc()
            arr = arr.copy()
            count_copy("lease_cow", arr.nbytes)
            super().__setitem__(name, arr)
        return arr

    def release(self):
        """Release every lease this batch retained (exactly once per batch)."""
        leases, self.leases = self.leases, ()
        for lease in leases:
            lease.release()


def attach_leases(batch, leases):
    """Wrap ``batch`` (a plain columnar dict) as a :class:`LeasedBatch` holding
    ``leases``; a no-op returning ``batch`` unchanged when there are none."""
    if not leases:
        return batch
    if isinstance(batch, LeasedBatch):
        batch.leases = tuple(batch.leases) + tuple(leases)
        return batch
    return LeasedBatch(batch, leases)


def take_leases(batch):
    """Detach and return a batch's leases (``()`` for plain dicts): ownership
    moves to the caller, which must release them when the batch completes."""
    if isinstance(batch, LeasedBatch):
        leases, batch.leases = batch.leases, ()
        return leases
    return ()


def readonly_view(value):
    """Recursively rebuild ``value`` with every ndarray replaced by a READ-ONLY
    zero-copy view (fresh containers, shared buffers): the shape served by
    lease-backed stores. Object-dtype arrays get fresh outer arrays whose
    ndarray ELEMENTS are read-only views too (the outer pointers are copied —
    bytes negligible — so element reassignment stays consumer-local)."""
    if isinstance(value, np.ndarray):
        if value.dtype == object:
            out = np.empty(value.shape, dtype=object)
            out_flat, in_flat = out.reshape(-1), value.reshape(-1)
            for i in range(in_flat.size):
                out_flat[i] = readonly_view(in_flat[i])
            return out
        view = value.view()
        view.flags.writeable = False
        return view
    if isinstance(value, dict):
        return {k: readonly_view(v) for k, v in value.items()}
    if isinstance(value, list):
        return [readonly_view(v) for v in value]
    if isinstance(value, tuple):
        return tuple(readonly_view(v) for v in value)
    return value


# --------------------------------------------------------------------------------------
# Copy census: ptpu_copy_bytes_total{site=...}
# --------------------------------------------------------------------------------------

#: the known copy sites (docs/performance.md "Copy census"): new sites register
#: lazily, this is documentation + a typo tripwire for the bench assertions
KNOWN_SITES = (
    "wire_writable",    # default-wire writable-contract copy (serializers)
    "wire_owned",       # shm pickle payload backed by owned buffers (serializers)
    "memcache_hit",     # legacy writable-hit deep copy (memcache writable mode)
    "memcache_admit",   # legacy miss-path defensive copy (memcache writable mode)
    "memcache_cow",     # explicit writable escalation on a leased entry
    "lease_cow",        # LeasedBatch.writable() escalation
    "loader_detach",    # loader copy-out of view columns (shuffle / host-only)
    "loader_concat",    # batcher cross-chunk concatenation
    "loader_pad",       # last_batch='pad' index gather
    "h2d_stage",        # pinned staging copy before device_put
    "h2d_owned_copy",   # owned copy before an aliasing (CPU) device_put
    "arena_admit",      # the ONE copy into the shared cache arena (io/arena.py)
)

_census_lock = threading.Lock()
_census = {}  # site -> Counter on the default registry


def _site_counter(site):
    counter = _census.get(site)
    if counter is None:
        from petastorm_tpu.obs.metrics import default_registry

        with _census_lock:
            counter = _census.get(site)
            if counter is None:
                counter = default_registry().counter(
                    "ptpu_copy_bytes_total",
                    help="payload bytes memcpy'd on the batch path, by site",
                    site=site)
                _census[site] = counter
    return counter


def count_copy(site, nbytes):
    """Charge ``nbytes`` to ``site`` in the copy census (cheap: one counter
    inc; callers batch per payload, not per array element)."""
    if nbytes:
        _site_counter(site).inc(int(nbytes))


def copy_census():
    """Snapshot ``{site: total_bytes}`` — what ``petastorm-tpu-bench copies``
    diffs around a measured window."""
    with _census_lock:
        return {site: counter.value for site, counter in _census.items()}


def lease_stats():
    """Snapshot of the ``ptpu_lease_*`` counters as a flat dict (collector
    shape, for private-registry loaders and the bench summary)."""
    m = _lease_metrics()
    return {
        "acquired": m.acquired.value,
        "released": m.released.value,
        "retained": m.retained.value,
        "cow": m.cow.value,
        "revoked": m.revoked.value,
        "leaked": m.leaked.value,
        "active": m.active.value,
    }
