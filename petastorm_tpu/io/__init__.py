"""Async IO layer under the reader workers (ISSUE 4).

BENCH_HISTORY showed the Parquet *read* path as the pipeline bottleneck: workers
sat in blocking ``read_row_group`` calls while decode and the device idled
("Hiding Latencies in Network-Based Image Loading for Deep Learning",
PAPERS.md). This package hides that latency inside each worker instead of
adding more workers:

- :mod:`petastorm_tpu.io.readahead` — a bounded per-process prefetcher: the
  next K row-group reads are issued on a small IO thread pool while the
  current table decodes, so IO overlaps decode within one worker.
- :mod:`petastorm_tpu.io.coalesce` — adjacent row groups of the same file
  queued together merge into ONE ranged read (``read_row_groups``) and the
  resulting table is sliced back apart, cutting per-call / object-store
  round-trip overhead on sequential scans.
- :mod:`petastorm_tpu.io.memcache` — a process-wide, byte-budgeted in-memory
  row-group LRU (keyed by the reader's existing ``_cache_key``) in front of
  ``LocalDiskCache``: hot row groups skip disk AND parse on re-epochs.

The fourth piece — pull-based piece dispatch with work stealing — lives in
:mod:`petastorm_tpu.workers` (it is scheduling, not IO), but is configured
through the same :class:`IoOptions` struct so one knob object travels from the
reader factories to every layer. Every feature is independently disableable
and degrades to the synchronous path with a
``ptpu_degradations_total{cause=...}`` entry when a fallback engages
(docs/performance.md "Read path").
"""
from __future__ import annotations

import os


def _env_int(name, default):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _env_float(name, default):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_bool(name, default):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


class IoOptions:
    """Knobs for the async read path — one picklable struct handed from the
    reader factories (``io_options=`` on ``make_reader``/``make_batch_reader``)
    to the workers (readahead/coalesce/memcache) and executors (work stealing).

    Every field has an env-var override so deployments tune the read path
    without threading kwargs through launcher scripts:

    ==================  =========================  ==============================
    field               env var                    meaning
    ==================  =========================  ==============================
    readahead           PTPU_READAHEAD             prefetch next row groups on an
                                                   IO thread pool (default on)
    readahead_depth     PTPU_READAHEAD_DEPTH       max row-group reads in flight
                                                   per worker process (default 3)
    readahead_bytes     PTPU_READAHEAD_BYTES       byte budget for prefetched
                                                   tables awaiting consumption
                                                   (default 256 MB; 0 = no cap)
    io_threads          PTPU_IO_THREADS            IO pool size (default 2)
    coalesce            PTPU_IO_COALESCE           merge adjacent queued row
                                                   groups into ranged reads
    coalesce_max_run    PTPU_IO_COALESCE_MAX_RUN   max row groups per ranged
                                                   read (default 4)
    work_stealing       PTPU_WORK_STEALING         idle workers steal claimed
                                                   pieces from stuck peers
    memcache_bytes      PTPU_MEMCACHE_BYTES        in-memory decoded-row-group
                                                   LRU budget (0 = off, the
                                                   default)
    arena_bytes         PTPU_ARENA_BYTES           host-wide shared-memory
                                                   cache arena budget (ISSUE
                                                   17): decoded columns,
                                                   footer blobs and page-index
                                                   memos live in ONE mapped
                                                   warm set shared by every
                                                   process on the host (0 =
                                                   off, the default;
                                                   PTPU_ARENA=off kills it
                                                   even when budgeted)
    memcache_writable_  PTPU_MEMCACHE_WRITABLE_    legacy pre-lease contract:
    hits                HITS                       deep-copy every memcache
                                                   serve writable (default off:
                                                   zero-copy read-only views)
    pagedec             PTPU_PAGEDEC               compressed-page pass-through
                                                   (ISSUE 14): "auto" (on when
                                                   a non-CPU jax backend is
                                                   live in the worker process),
                                                   "on", "off". Eligible
                                                   fixed-width columns ship
                                                   raw snappy/uncompressed
                                                   pages to the loader and
                                                   inflate on device; others
                                                   fall back per column.
    remote              (see RemoteIoOptions)      the object-store tier's
                                                   knobs (ISSUE 8): ranged-GET
                                                   sizing, hedging, footer
                                                   cache, tiered admission —
                                                   a RemoteIoOptions or a dict
                                                   of its fields
    ==================  =========================  ==============================
    """

    __slots__ = ("readahead", "readahead_depth", "readahead_bytes", "io_threads",
                 "coalesce", "coalesce_max_run", "work_stealing", "memcache_bytes",
                 "memcache_writable_hits", "arena_bytes", "pagedec", "remote")

    def __init__(self, readahead=None, readahead_depth=None, readahead_bytes=None,
                 io_threads=None, coalesce=None, coalesce_max_run=None,
                 work_stealing=None, memcache_bytes=None,
                 memcache_writable_hits=None, arena_bytes=None, pagedec=None,
                 remote=None):
        self.readahead = _env_bool("PTPU_READAHEAD", True) \
            if readahead is None else bool(readahead)
        self.readahead_depth = max(1, _env_int("PTPU_READAHEAD_DEPTH", 3)
                                   if readahead_depth is None else int(readahead_depth))
        self.readahead_bytes = max(0, _env_int("PTPU_READAHEAD_BYTES", 256 << 20)
                                   if readahead_bytes is None else int(readahead_bytes))
        self.io_threads = max(1, _env_int("PTPU_IO_THREADS", 2)
                              if io_threads is None else int(io_threads))
        self.coalesce = _env_bool("PTPU_IO_COALESCE", True) \
            if coalesce is None else bool(coalesce)
        self.coalesce_max_run = max(1, _env_int("PTPU_IO_COALESCE_MAX_RUN", 4)
                                    if coalesce_max_run is None
                                    else int(coalesce_max_run))
        self.work_stealing = _env_bool("PTPU_WORK_STEALING", True) \
            if work_stealing is None else bool(work_stealing)
        self.memcache_bytes = max(0, _env_int("PTPU_MEMCACHE_BYTES", 0)
                                  if memcache_bytes is None else int(memcache_bytes))
        # legacy pre-lease serving contract: every memcache serve is an owned
        # writable deep copy (ISSUE 6 default is zero-copy read-only views with
        # copy-on-write escalation) — the rollback knob, and the copying
        # baseline `petastorm-tpu-bench copies` measures against
        self.memcache_writable_hits = \
            _env_bool("PTPU_MEMCACHE_WRITABLE_HITS", False) \
            if memcache_writable_hits is None else bool(memcache_writable_hits)
        # host-wide shared cache arena budget (ISSUE 17): 0 keeps today's
        # per-process caches; >0 makes the creating reader own one mapped warm
        # set that pool children and co-resident readers attach to
        self.arena_bytes = max(0, _env_int("PTPU_ARENA_BYTES", 0)
                               if arena_bytes is None else int(arena_bytes))
        # compressed-page pass-through (ISSUE 14): "auto" engages only when a
        # non-CPU jax backend is already initialized in the worker process
        # (host inflate is strictly cheaper when there is no PCIe link to
        # save); "on" forces it (process pools ship compressed over the pool
        # wire either way); "off" is the classic path. Also a live enum Knob
        # (control.build_knobset) the controller can flip back to host inflate.
        pagedec = (os.environ.get("PTPU_PAGEDEC") or "auto").strip().lower() \
            if pagedec is None else str(pagedec).strip().lower()
        if pagedec not in ("auto", "on", "off"):
            raise ValueError("pagedec must be 'auto', 'on' or 'off'; got %r"
                             % pagedec)
        self.pagedec = pagedec
        # the remote tier's knobs (ISSUE 8): a RemoteIoOptions (or a dict of
        # its fields) riding on the same struct so one `io_options=` kwarg
        # still configures the whole read path; lazy import — remote.py
        # imports this module's env helpers
        from petastorm_tpu.io.remote import RemoteIoOptions

        self.remote = RemoteIoOptions.normalize(remote)

    @classmethod
    def normalize(cls, value):
        """``None`` → defaults (env-aware), dict → kwargs, IoOptions → itself."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError("io_options must be an IoOptions, a dict of its fields, "
                        "or None; got %r" % type(value).__name__)

    @property
    def lookahead(self):
        """Per-worker dispatch claim size: how many upcoming plan items each
        worker holds (and prefetches). 0 when readahead is off — the dispatcher
        then degenerates to the plain shared pull queue."""
        return self.readahead_depth if self.readahead else 0

    def __getstate__(self):
        return {name: getattr(self, name) for name in self.__slots__}

    def __setstate__(self, state):
        for name in self.__slots__:
            # .get(name, MISSING): tolerate pickles from an older IoOptions
            # missing a newer field (a child on a stale worker image keeps the
            # new default)
            if name in state:
                setattr(self, name, state[name])
            else:
                setattr(self, name, getattr(type(self)(), name))

    def __repr__(self):
        return "IoOptions(%s)" % ", ".join(
            "%s=%r" % (name, getattr(self, name)) for name in self.__slots__)


from petastorm_tpu.io.coalesce import plan_runs, split_run_table  # noqa: E402,F401
from petastorm_tpu.io.memcache import MemCache  # noqa: E402,F401
from petastorm_tpu.io.readahead import ReadaheadPool  # noqa: E402,F401
from petastorm_tpu.io.remote import RemoteIoOptions  # noqa: E402,F401
from petastorm_tpu.io.footercache import FooterCache  # noqa: E402,F401
from petastorm_tpu.io.tiers import TieredCache  # noqa: E402,F401
