"""Host-wide zero-copy cache arena: one mapped warm set shared by every process.

Before ISSUE 17 every pool child warmed its own ``FooterCache`` / ``MemCache``
/ ``PageIndexCache`` — the explicit remaining headroom from PR 8 — so a host
running N decode workers plus a trainer paid N× the parse cost, N× the
resident bytes and N× the cold-start for one identical warm set. This module
is the Zerrow answer ("Zerrow: True Zero-Copy Arrow Pipelines in Bauplan",
PAPERS.md): put the hot bytes in ONE named shared-memory segment set and make
the per-process caches *views* that map instead of copy.

Architecture (extends the PR 6 ``Lease``/``SlabRing`` discipline from wire
transport to resident cache):

- One **creator** process (the first reader to ask, via :func:`host_arena`)
  owns a small fixed-size **control segment** holding a pickled byte-budgeted
  LRU index — ``{key -> (segment name, nbytes, generation token, LRU tick,
  per-pid holder refcounts)}`` — plus one shm segment per cached entry.
- **Attachers** (pool children at bootstrap via :func:`attach_from_env`, or
  any process handed a picklable :class:`ArenaSpec`) map the same segments
  read-mostly; promote/evict decisions go through the control segment under a
  cross-process ``fcntl.flock`` (serialized per-process by a ``threading``
  lock — one global order, lint-visible to GL-C006).
- Every serve is a **zero-copy read-only view** over the mapped entry segment
  pinned by a :class:`~petastorm_tpu.io.lease.Lease` (``kind="arena"`` — the
  existing ``ptpu_lease_*`` counters and leak census apply unchanged). The
  per-pid holder refcount in the control segment keeps an entry unevictable
  while ANY process holds it; :meth:`CacheArena.reclaim` drops the refcounts
  of dead pids (SIGKILLed children) exactly like ``SlabRing.reclaim``.
- **Generation tokens** (ISSUE 11) validate entries across the dataset-watch
  plane: a ``get`` under a different generation invalidates and misses, so a
  rewritten source file can never serve its predecessor's shared payload.
- Admission pays ONE copy into shm, charged to the ``arena_admit`` site of
  the copy census (``ptpu_copy_bytes_total``); serves add zero census bytes —
  the ``petastorm-tpu-bench shmcache`` gate pins both.

POSIX semantics make eviction safe without a coherence protocol: unlinking a
segment removes its NAME but never invalidates existing mappings, so peers'
live views survive any eviction/invalidation; only new attaches miss.

Degradations (never a raise on the read path): ``arena_unavailable`` (shm or
flock missing, creation failed, ``PTPU_ARENA=off``) falls back to today's
per-process caches; ``arena_full`` declines admission; ``arena_lease_revoked``
counts holder refcounts reclaimed from dead processes.
"""
from __future__ import annotations

import atexit
import os
import pickle
import struct
import tempfile
import threading

import numpy as np

from petastorm_tpu.io.lease import Lease, count_copy
from petastorm_tpu.obs.log import degradation

#: /dev/shm segment name prefix — the test suite's leak fixture and operators
#: debugging a wedged host both grep for it (same convention as
#: ``shm_ring.SEGMENT_PREFIX``).
ARENA_PREFIX = "ptpu_arena_"

_CTL_MAGIC = b"PTAC"
_ENTRY_MAGIC = b"PTAE"
_HEADER = struct.Struct("<4sQ")  # magic, payload length
_ALIGN = 64  # ndarray blob slots align to cache lines (clean dtype views)

#: default control-segment size: holds the pickled index for a few thousand
#: entries; admission degrades (``arena_full``) when the index outgrows it
DEFAULT_CTL_BYTES = 1 << 20


class ArenaSpec:
    """Picklable attach handle: everything a process needs to map an existing
    arena (segment names derive from the token). Rides worker pickles and the
    ``PTPU_ARENA_ATTACH`` env var (the ``PTPU_CHAOS_SPEC`` convention) so
    freshly respawned or elastically-grown children start warm."""

    __slots__ = ("token",)

    def __init__(self, token):
        self.token = str(token)

    def __repr__(self):
        return "ArenaSpec(%r)" % self.token

    def __eq__(self, other):
        return isinstance(other, ArenaSpec) and other.token == self.token

    def __hash__(self):
        return hash(self.token)


# -- zero-copy payload codec -----------------------------------------------------------
#
# A cached payload (column dict / row list / nested containers) is split into
# a picklable META tree — real ndarrays replaced by _ND placeholders — and one
# contiguous ndarray BLOB. Decode rebuilds the tree with np.frombuffer views
# over the mapped blob (read-only): fresh containers, shared buffers.


class _ND:
    """Placeholder for one non-object ndarray in the meta tree."""

    __slots__ = ("dtype", "shape", "off", "nbytes")

    def __init__(self, dtype, shape, off, nbytes):
        self.dtype = dtype
        self.shape = shape
        self.off = off
        self.nbytes = nbytes

    def __reduce__(self):
        return (_ND, (self.dtype, self.shape, self.off, self.nbytes))


class _NDObj:
    """Placeholder for an object-dtype ndarray: shape + encoded elements."""

    __slots__ = ("shape", "elems")

    def __init__(self, shape, elems):
        self.shape = shape
        self.elems = elems

    def __reduce__(self):
        return (_NDObj, (self.shape, self.elems))


def _encode_payload(value):
    """``(meta, parts, blob_len)`` — ``parts`` is ``[(offset, contiguous
    ndarray)]`` to be copied into the entry segment's blob region."""
    parts = []
    state = [0]  # running blob offset

    def enc(v):
        if isinstance(v, np.ndarray):
            if v.dtype == object:
                return _NDObj(v.shape, [enc(e) for e in v.reshape(-1)])
            arr = np.ascontiguousarray(v)
            off = (state[0] + _ALIGN - 1) & ~(_ALIGN - 1)
            state[0] = off + arr.nbytes
            parts.append((off, arr))
            return _ND(arr.dtype.str, arr.shape, off, arr.nbytes)
        if isinstance(v, dict):
            return {k: enc(e) for k, e in v.items()}
        if isinstance(v, list):
            return [enc(e) for e in v]
        if isinstance(v, tuple):
            return tuple(enc(e) for e in v)
        return v

    meta = enc(value)
    return meta, parts, state[0]


def _decode_payload(meta, buf, blob_base):
    """Rebuild the payload with read-only zero-copy views over ``buf``."""

    def dec(m):
        if isinstance(m, _ND):
            arr = np.frombuffer(buf, dtype=np.dtype(m.dtype),
                                count=m.nbytes // np.dtype(m.dtype).itemsize
                                if np.dtype(m.dtype).itemsize else 0,
                                offset=blob_base + m.off)
            arr = arr.reshape(m.shape)
            arr.flags.writeable = False
            return arr
        if isinstance(m, _NDObj):
            out = np.empty(m.shape, dtype=object)
            flat = out.reshape(-1)
            for i, e in enumerate(m.elems):
                flat[i] = dec(e)
            return out
        if isinstance(m, dict):
            return {k: dec(e) for k, e in m.items()}
        if isinstance(m, list):
            return [dec(e) for e in m]
        if isinstance(m, tuple):
            return tuple(dec(e) for e in m)
        return m

    return dec(meta)


# -- metrics ---------------------------------------------------------------------------


class _ArenaMetrics:
    """Process-local ``ptpu_io_arena_*`` family (built on first arena)."""

    __slots__ = ("hits", "misses", "admits", "evictions", "invalidations",
                 "attaches", "revoked", "bytes", "entries", "_reg", "_tagged")

    def __init__(self):
        from petastorm_tpu.obs.metrics import default_registry

        reg = default_registry()
        self._reg = reg
        self._tagged = {}  # (family, tenant) -> Counter (ISSUE 18 twins)
        self.hits = reg.counter("ptpu_io_arena_hits_total",
                                help="reads served from the shared cache arena")
        self.misses = reg.counter("ptpu_io_arena_misses_total",
                                  help="arena lookups that missed")
        self.admits = reg.counter("ptpu_io_arena_admits_total",
                                  help="entries copied into the arena")
        self.evictions = reg.counter("ptpu_io_arena_evictions_total",
                                     help="entries LRU-evicted for budget")
        self.invalidations = reg.counter(
            "ptpu_io_arena_invalidations_total",
            help="entries dropped by keyed/generation invalidation")
        self.attaches = reg.counter("ptpu_io_arena_attaches_total",
                                    help="processes that mapped this arena")
        self.revoked = reg.counter(
            "ptpu_io_arena_holders_revoked_total",
            help="dead-process holder refcounts dropped by reclaim()")
        self.bytes = reg.gauge("ptpu_io_arena_bytes",
                               help="payload bytes resident in the arena")
        self.entries = reg.gauge("ptpu_io_arena_entries",
                                 help="entries resident in the arena")

    def tagged(self, family, tenant):
        """The per-tenant twin of an arena counter (ISSUE 18) — charged
        alongside the untagged total, never instead of it. The label is a
        validated bounded slug, so cardinality stays bounded."""
        key = (family, tenant)
        c = self._tagged.get(key)
        if c is None:
            c = self._tagged[key] = self._reg.counter(family, tenant=tenant)
        return c


_metrics_lock = threading.Lock()
_metrics_cache = [None]


def _arena_metrics():
    if _metrics_cache[0] is None:
        with _metrics_lock:
            if _metrics_cache[0] is None:
                _metrics_cache[0] = _ArenaMetrics()
    return _metrics_cache[0]


class _CtlFull(Exception):
    """Pickled index outgrew the control segment (admission declined)."""


class CacheArena:
    """The host-wide shared cache arena: one control segment + one shm segment
    per entry, cross-process coordinated under a flock'd lock file.

    Construct with ``budget_bytes`` to CREATE (this process owns the segments
    and unlinks them at :meth:`close`), or with ``spec=`` to ATTACH to an
    existing arena (:meth:`close` then merely detaches — never unlinks).
    Graftlint GL-L001 tracks construction; ``close()``/``detach()`` are the
    closers.
    """

    def __init__(self, budget_bytes=None, spec=None, ctl_bytes=DEFAULT_CTL_BYTES):
        from multiprocessing import shared_memory

        if (budget_bytes is None) == (spec is None):
            raise ValueError("pass exactly one of budget_bytes (create) or "
                             "spec (attach)")
        import fcntl  # noqa: F401 — POSIX-only; ImportError → arena unavailable

        self._fcntl = fcntl
        self._tlock = threading.Lock()
        self._closed = False
        self._creator = spec is None
        self._maps = {}  # segment name -> SharedMemory (entry segments)
        self._pid = os.getpid()
        self._ctl_bytes = int(ctl_bytes)
        if self._creator:
            token = "%d_%s" % (os.getpid(), os.urandom(4).hex())
            self.spec = ArenaSpec(token)
            self._lock_path = _lock_path(token)
            lock_fd = os.open(self._lock_path,
                              os.O_CREAT | os.O_RDWR, 0o600)
            self._lock_fd = lock_fd
            ctl = shared_memory.SharedMemory(
                create=True, size=self._ctl_bytes, name=_ctl_name(token))
            self._ctl = ctl
            index = {"budget": int(budget_bytes), "serial": 0, "tick": 0,
                     "total": 0, "attached": {self._pid: True}, "entries": {}}
            with self._tlock:
                self._flock()
                try:
                    self._write_index(index)
                finally:
                    self._funlock()
        else:
            token = spec.token
            self.spec = ArenaSpec(token)
            self._lock_path = _lock_path(token)
            lock_fd = os.open(self._lock_path, os.O_RDWR)  # must pre-exist
            self._lock_fd = lock_fd
            ctl = shared_memory.SharedMemory(name=_ctl_name(token))
            _untrack_segment(ctl)
            self._ctl = ctl
            self._ctl_bytes = ctl.size
            with self._tlock:
                self._flock()
                try:
                    index = self._read_index()
                    index["attached"][self._pid] = True
                    self._write_index(index)
                finally:
                    self._funlock()
        _arena_metrics().attaches.inc()

    # -- cross-process lock (order: _tlock -> flock, everywhere) ------------------------

    def _flock(self):
        self._fcntl.flock(self._lock_fd, self._fcntl.LOCK_EX)

    def _funlock(self):
        self._fcntl.flock(self._lock_fd, self._fcntl.LOCK_UN)

    # -- control-segment index ----------------------------------------------------------

    def _read_index(self):
        buf = self._ctl.buf
        magic, length = _HEADER.unpack_from(buf, 0)
        if magic != _CTL_MAGIC or length > self._ctl_bytes - _HEADER.size:
            raise RuntimeError("arena control segment corrupt")
        return pickle.loads(bytes(buf[_HEADER.size:_HEADER.size + length]))

    def _write_index(self, index):
        blob = pickle.dumps(index, protocol=pickle.HIGHEST_PROTOCOL)
        if _HEADER.size + len(blob) > self._ctl_bytes:
            raise _CtlFull()
        buf = self._ctl.buf
        _HEADER.pack_into(buf, 0, _CTL_MAGIC, len(blob))
        buf[_HEADER.size:_HEADER.size + len(blob)] = blob

    # -- admission ----------------------------------------------------------------------

    def put(self, key, value, gen=None):
        """Admit ``value`` under ``key`` (idempotent: an existing same-
        generation entry is kept, not re-copied). Returns True when the entry
        is resident after the call. The one copy — payload bytes into shm —
        is charged to the ``arena_admit`` census site."""
        try:
            meta, parts, blob_len = _encode_payload(value)
        except Exception:  # noqa: BLE001 — unpicklable/exotic payloads stay local
            return False
        return self._admit(key, gen, meta, parts, blob_len)

    def put_bytes(self, key, data, gen=None):
        """Admit a raw blob (serialized footer, pickled page-boundary memo)."""
        arr = np.frombuffer(bytes(data), dtype=np.uint8)
        return self._admit(key, gen, _ND("|u1", arr.shape, 0, arr.nbytes),
                           [(0, arr)], arr.nbytes)

    def _admit(self, key, gen, meta, parts, blob_len):
        from multiprocessing import shared_memory

        if self._closed:
            return False
        try:
            meta_blob = pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:  # noqa: BLE001 — unpicklable meta leaf: stay local
            return False
        blob_base = _blob_base(len(meta_blob))
        seg_size = max(16, blob_base + blob_len)
        # budget/census charge = EVERYTHING written into shm: the ndarray
        # blob plus the pickled meta tree (bytes-leaf payloads — binary
        # columns — live in the meta, and must not ride the budget for free)
        nbytes = blob_len + len(meta_blob)
        with self._tlock:
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001 — corrupt ctl: decline, keep serving locally
                    return False
                entry = index["entries"].get(key)
                if entry is not None and entry["gen"] == gen:
                    return True  # another process admitted it first
                if entry is not None:
                    self._drop_entry(index, key, entry, invalidation=True)
                if nbytes > index["budget"]:
                    degradation(
                        "arena_full",
                        "arena admission declined: %d-byte payload exceeds "
                        "the whole arena budget (%d)", nbytes,
                        index["budget"])
                    return False
                self._evict_for(index, nbytes)
                if index["total"] + nbytes > index["budget"]:
                    degradation(
                        "arena_full",
                        "arena admission declined: budget %d full with "
                        "held/hot entries", index["budget"])
                    return False
                index["serial"] += 1
                seg_name = _entry_name(self.spec.token, index["serial"])
                try:
                    seg = shared_memory.SharedMemory(
                        create=True, size=seg_size, name=seg_name)
                except Exception as e:  # noqa: BLE001 — /dev/shm full, etc.
                    degradation("arena_full",
                                "arena entry segment create failed (%s)", e)
                    return False
                if not self._creator:
                    _untrack_segment(seg)
                self._maps[seg_name] = seg
                buf = seg.buf
                _HEADER.pack_into(buf, 0, _ENTRY_MAGIC, len(meta_blob))
                buf[_HEADER.size:_HEADER.size + len(meta_blob)] = meta_blob
                for off, arr in parts:
                    if arr.nbytes:
                        start = blob_base + off
                        buf[start:start + arr.nbytes] = \
                            memoryview(arr).cast("B")
                index["tick"] += 1
                from petastorm_tpu.obs import tenant as _tenant_ctx

                admit_tenant = _tenant_ctx.current_label()
                index["entries"][key] = {
                    "seg": seg_name, "nbytes": nbytes, "gen": gen,
                    "tick": index["tick"], "holders": {},
                    # who admitted it (ISSUE 18): evictions/invalidations
                    # debit the OWNER's residency, not the evictor's
                    "tenant": admit_tenant}
                index["total"] += nbytes
                try:
                    self._write_index(index)
                except _CtlFull:
                    # index outgrew the control segment: back the entry out
                    del index["entries"][key]
                    index["total"] -= nbytes
                    self._unlink_seg(seg_name)
                    self._rewrite_best_effort(index)
                    degradation(
                        "arena_full",
                        "arena index outgrew the %d-byte control segment; "
                        "admission declined", self._ctl_bytes)
                    return False
            finally:
                self._funlock()
        count_copy("arena_admit", nbytes)
        m = _arena_metrics()
        m.admits.inc()
        m.bytes.set(index["total"])
        m.entries.set(len(index["entries"]))
        if admit_tenant is not None:
            m.tagged("ptpu_io_arena_admits_total", admit_tenant).inc()
            from petastorm_tpu.obs import tenant as _tenant_ctx

            _tenant_ctx.meter().arena_adjust(admit_tenant, nbytes)
        return True

    def _rewrite_best_effort(self, index):
        try:
            self._write_index(index)
        except Exception:  # noqa: BLE001 — ctl already held a larger index
            pass  # graftlint: disable=GL-O002 (backout path; next write retries)

    def _evict_for(self, index, incoming):
        """LRU-evict unheld entries until ``incoming`` fits (lock held).
        Entries with live holders are skipped — a mapped view pinned by a
        lease must never have its bytes budget-reclaimed out from under the
        budget accounting; dead holders are self-healed here."""
        if index["total"] + incoming <= index["budget"]:
            return
        order = sorted(index["entries"].items(), key=lambda kv: kv[1]["tick"])
        for key, entry in order:
            if index["total"] + incoming <= index["budget"]:
                break
            self._prune_dead_holders(entry)
            if any(entry["holders"].values()):
                continue
            self._drop_entry(index, key, entry, invalidation=False)

    @staticmethod
    def _prune_dead_holders(entry):
        for pid in list(entry["holders"]):
            if not _pid_alive(pid):
                del entry["holders"][pid]

    def _drop_entry(self, index, key, entry, invalidation):
        del index["entries"][key]
        index["total"] -= entry["nbytes"]
        self._unlink_seg(entry["seg"])
        m = _arena_metrics()
        if invalidation:
            m.invalidations.inc()
        else:
            m.evictions.inc()
        owner = entry.get("tenant")
        if owner is not None:
            family = "ptpu_io_arena_invalidations_total" if invalidation \
                else "ptpu_io_arena_evictions_total"
            m.tagged(family, owner).inc()
            from petastorm_tpu.obs import tenant as _tenant_ctx

            # debit the OWNER's residency meter (byte*seconds integral closes
            # here). Exact in-process; a peer-process eviction debits the
            # peer's meter best-effort — the index-derived per-tenant bytes in
            # stats() stay the host-wide ground truth.
            _tenant_ctx.meter().arena_adjust(owner, -entry["nbytes"])

    def _unlink_seg(self, seg_name):
        """Remove a segment's NAME (POSIX keeps peers' live mappings valid).
        Our own mapping is kept in ``_maps`` — outstanding local views stay
        backed; the mapping frees when the map entry drops and the last view
        dies (numpy refcounting)."""
        from multiprocessing import shared_memory

        seg = self._maps.pop(seg_name, None)  # graftlint: disable=GL-C001 (every caller holds self._tlock: _admit, _lookup and invalidate take it before the index mutation that reaches here)
        if seg is None:
            try:
                seg = shared_memory.SharedMemory(name=seg_name)
                _untrack_segment(seg)
            except FileNotFoundError:
                return
            except Exception:  # noqa: BLE001 — best-effort per segment
                return
        _tracked_unlink(seg)
        _close_mappings([seg])

    # -- serves -------------------------------------------------------------------------

    def get(self, key, gen=None):
        """``(value, lease)`` — zero-copy read-only views pinned by a
        ``kind="arena"`` lease — or ``None`` on miss/generation mismatch.
        The caller (a per-process cache admitting the views) releases the
        lease when its entry drops; the holder refcount in the control
        segment keeps the entry unevictable until then."""
        got = self._lookup(key, gen)
        if got is None:
            return None
        seg, meta_blob = got
        try:
            meta = pickle.loads(meta_blob)
            value = _decode_payload(meta, seg.buf, _blob_base(len(meta_blob)))
        except Exception:  # noqa: BLE001 — undecodable entry: release + miss
            self._drop_holder(key, seg.name)
            return None
        lease = Lease(release_cb=_release_cb(self, key, seg.name),
                      kind="arena")
        return value, lease

    def get_bytes(self, key, gen=None):
        """A raw blob admitted with :meth:`put_bytes`, as ``bytes`` — or
        ``None``. The (small, metadata-plane) blob is copied out and the
        holder refcount dropped before returning: blob consumers parse once
        per process and memoize the parse, not the bytes."""
        got = self._lookup(key, gen)
        if got is None:
            return None
        seg, meta_blob = got
        try:
            meta = pickle.loads(meta_blob)
            base = _blob_base(len(meta_blob))
            data = bytes(seg.buf[base:base + meta.nbytes])
        except Exception:  # noqa: BLE001 — undecodable entry: miss
            data = None
        self._drop_holder(key, seg.name)
        return data

    def _lookup(self, key, gen):
        """Hit: bump LRU + this pid's holder refcount, return the mapped
        segment and its meta blob. Generation mismatch invalidates."""
        from multiprocessing import shared_memory

        if self._closed:
            return None
        m = _arena_metrics()
        with self._tlock:
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001 — corrupt ctl: miss
                    m.misses.inc()
                    return None
                entry = index["entries"].get(key)
                if entry is not None and gen is not None \
                        and entry["gen"] != gen:
                    self._drop_entry(index, key, entry, invalidation=True)
                    self._rewrite_best_effort(index)
                    entry = None
                if entry is None:
                    m.misses.inc()
                    return None
                seg = self._maps.get(entry["seg"])
                if seg is None:
                    try:
                        seg = shared_memory.SharedMemory(name=entry["seg"])
                    except Exception:  # noqa: BLE001 — vanished segment: self-heal
                        self._drop_entry(index, key, entry, invalidation=True)
                        self._rewrite_best_effort(index)
                        m.misses.inc()
                        return None
                    if not self._creator:
                        _untrack_segment(seg)
                    self._maps[entry["seg"]] = seg
                index["tick"] += 1
                entry["tick"] = index["tick"]
                holders = entry["holders"]
                holders[self._pid] = holders.get(self._pid, 0) + 1
                self._rewrite_best_effort(index)
            finally:
                self._funlock()
        try:
            magic, meta_len = _HEADER.unpack_from(seg.buf, 0)
            if magic != _ENTRY_MAGIC:
                raise RuntimeError("arena entry segment corrupt")
            meta_blob = bytes(seg.buf[_HEADER.size:_HEADER.size + meta_len])
        except Exception:  # noqa: BLE001 — torn entry: release holder, miss
            self._drop_holder(key, seg.name)
            m.misses.inc()
            return None
        m.hits.inc()
        from petastorm_tpu.obs import tenant as _tenant_ctx

        reader_tenant = _tenant_ctx.current_label()
        if reader_tenant is not None:
            m.tagged("ptpu_io_arena_hits_total", reader_tenant).inc()
        return seg, meta_blob

    def _drop_holder(self, key, seg_name):
        """Lease release callback: drop one of this pid's holder refcounts.
        The entry may already be gone (invalidated/evicted after the holder's
        process died and was reclaimed) — then there is nothing to do; the
        local mapping stays until its views die."""
        if self._closed:
            return
        with self._tlock:
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001 — corrupt ctl during teardown
                    return
                entry = index["entries"].get(key)
                if entry is None or entry["seg"] != seg_name:
                    return
                holders = entry["holders"]
                n = holders.get(self._pid, 0)
                if n <= 1:
                    holders.pop(self._pid, None)
                else:
                    holders[self._pid] = n - 1
                self._rewrite_best_effort(index)
            finally:
                self._funlock()

    def contains(self, key):
        with self._tlock:
            if self._closed:
                return False
            self._flock()
            try:
                try:
                    return key in self._read_index()["entries"]
                except Exception:  # noqa: BLE001 — corrupt ctl reads as empty
                    return False
            finally:
                self._funlock()

    # -- invalidation / reclaim ---------------------------------------------------------

    def invalidate(self, key):
        """Drop one entry by key (ISSUE 11: dataset mutation). Peers' live
        views stay valid — unlink removes the name, not the mappings."""
        if self._closed:
            return
        with self._tlock:
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001
                    return
                entry = index["entries"].get(key)
                if entry is None:
                    return
                self._drop_entry(index, key, entry, invalidation=True)
                self._rewrite_best_effort(index)
            finally:
                self._funlock()

    def reclaim(self, pid=None):
        """Drop the holder refcounts (and attach record) of dead processes —
        the SIGKILLed-child path, same semantics as ``SlabRing.reclaim``:
        the dead holder's pins vanish so its entries become evictable again;
        live peers' views are untouched. ``pid=None`` sweeps every recorded
        pid; returns the number of holder refcounts revoked."""
        if self._closed:
            return 0
        revoked = 0
        with self._tlock:
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001
                    return 0
                targets = [pid] if pid is not None else None
                for entry in index["entries"].values():
                    for holder in list(entry["holders"]):
                        dead = (holder in targets) if targets is not None \
                            else not _pid_alive(holder)
                        if dead:
                            revoked += entry["holders"].pop(holder)
                for holder in list(index["attached"]):
                    dead = (holder in targets) if targets is not None \
                        else not _pid_alive(holder)
                    if dead:
                        del index["attached"][holder]
                if revoked:
                    self._rewrite_best_effort(index)
            finally:
                self._funlock()
        if revoked:
            _arena_metrics().revoked.inc(revoked)
            degradation(
                "arena_lease_revoked",
                "%d arena holder refcount(s) of dead process(es) reclaimed; "
                "their entries are evictable again (live peers' views stay "
                "valid)", revoked, once=False)
        return revoked

    # -- budget / stats -----------------------------------------------------------------

    @property
    def budget(self):
        with self._tlock:
            if self._closed:
                return 0
            self._flock()
            try:
                try:
                    return self._read_index()["budget"]
                except Exception:  # noqa: BLE001
                    return 0
            finally:
                self._funlock()

    def set_budget(self, nbytes):
        """Live budget retune (ISSUE 13) — host-wide: the budget lives in the
        control segment, so a parent-side retune governs every attached
        process's admissions. Shrinking evicts unheld entries immediately."""
        nbytes = max(0, int(nbytes))
        if self._closed:
            return 0
        with self._tlock:
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001
                    return 0
                index["budget"] = nbytes
                self._evict_for(index, 0)
                self._rewrite_best_effort(index)
            finally:
                self._funlock()
        m = _arena_metrics()
        m.bytes.set(index["total"])
        m.entries.set(len(index["entries"]))
        return nbytes

    def stats(self):
        with self._tlock:
            if self._closed:
                return {}
            self._flock()
            try:
                try:
                    index = self._read_index()
                except Exception:  # noqa: BLE001
                    return {}
            finally:
                self._funlock()
        m = _arena_metrics()
        m.bytes.set(index["total"])
        m.entries.set(len(index["entries"]))
        tenant_bytes = {}
        for e in index["entries"].values():
            owner = e.get("tenant")
            if owner is not None:
                tenant_bytes[owner] = tenant_bytes.get(owner, 0) + e["nbytes"]
        return {
            "arena_entries": len(index["entries"]),
            "arena_payload_bytes": index["total"],
            "arena_budget_bytes": index["budget"],
            "arena_attached": len(index["attached"]),
            "arena_held_entries": sum(
                1 for e in index["entries"].values() if e["holders"]),
            # host-wide per-tenant residency, index-derived (ISSUE 18): the
            # ground truth the per-process meters approximate
            "arena_tenant_bytes": tenant_bytes,
            "arena_held_leases": sum(
                sum(h.values()) for e in index["entries"].values()
                for h in (e["holders"],)),
            # process-LOCAL funnel counters (each process warms independently)
            "arena_hits": m.hits.value,
            "arena_misses": m.misses.value,
            "arena_admits": m.admits.value,
            "arena_evictions": m.evictions.value,
            "arena_invalidations": m.invalidations.value,
        }

    # -- teardown -----------------------------------------------------------------------

    def close(self):
        """Creator: unlink every entry segment, the control segment and the
        lock file — nothing survives in ``/dev/shm`` (peers' live views stay
        backed by their own mappings). Attacher: detach only. Idempotent."""
        with self._tlock:
            if self._closed:
                return
            self._closed = True
            maps, self._maps = self._maps, {}
            entry_names = []
            try:
                self._flock()
                try:
                    try:
                        index = self._read_index()
                        if self._creator:
                            entry_names = [e["seg"] for e in
                                           index["entries"].values()]
                        else:
                            index["attached"].pop(self._pid, None)
                            for entry in index["entries"].values():
                                entry["holders"].pop(self._pid, None)
                            self._rewrite_best_effort(index)
                    except Exception:  # noqa: BLE001 — corrupt ctl: unlink what we mapped
                        if self._creator:
                            entry_names = list(maps)
                finally:
                    self._funlock()
            except Exception:  # noqa: BLE001 — lock fd already gone (exit races)
                if self._creator:
                    entry_names = list(maps)
        for name in entry_names:
            seg = maps.pop(name, None)
            _unlink_by_name(name, seg)
        _close_mappings(maps.values())
        if self._creator:
            _unlink_by_name(self._ctl.name, self._ctl)
            try:
                os.unlink(self._lock_path)
            except OSError:
                pass
        else:
            _close_mappings([self._ctl])
        try:
            os.close(self._lock_fd)
        except OSError:
            pass

    def detach(self):
        """Alias of :meth:`close` for attachers — the GL-L001 closer name the
        arena's lifecycle contract documents."""
        self.close()

    def __repr__(self):
        return "<CacheArena %s token=%s%s>" % (
            "creator" if self._creator else "attached", self.spec.token,
            " CLOSED" if self._closed else "")


def _release_cb(arena, key, seg_name):
    def release():
        arena._drop_holder(key, seg_name)
    return release


def _close_mappings(segs):
    for seg in segs:
        try:
            seg.close()
        except BufferError:
            # exported views still alive (a consumer kept a served batch):
            # the mapping frees with the last view; shadow close() so the
            # segment's teardown does not retry and spam at GC
            seg.close = _noop
        except Exception:  # noqa: BLE001 — exit path
            pass  # graftlint: disable=GL-O002 (exit path: mapping frees at process exit)


def _unlink_by_name(name, seg):
    from multiprocessing import shared_memory

    if seg is None:
        try:
            seg = shared_memory.SharedMemory(name=name)
            _untrack_segment(seg)
        except Exception:  # noqa: BLE001 — already gone
            return
    _tracked_unlink(seg)
    _close_mappings([seg])


def _tracked_unlink(seg):
    """Unlink with BALANCED resource_tracker bookkeeping: ``unlink()`` always
    sends an unregister, but attached segments were deliberately deregistered
    (gh-82300) — re-register first (the tracker's cache is a set; re-adding a
    creator-registered name is a no-op) so the pair never underflows into
    tracker KeyError spam at exit."""
    registered = False
    try:
        from multiprocessing import resource_tracker

        resource_tracker.register(seg._name, "shared_memory")
        registered = True
    except Exception:  # noqa: BLE001 — tracker internals vary
        pass  # graftlint: disable=GL-O002 (bookkeeping only; unlink below still runs)
    try:
        seg.unlink()  # sends the matching unregister on success
        registered = False
    except FileNotFoundError:
        pass  # another process already unlinked it
    except Exception:  # noqa: BLE001 — unlink is best-effort per segment
        pass  # graftlint: disable=GL-O002 (name removal; mappings stay valid)
    if registered:
        # unlink raised before its internal unregister: take the name back
        # out or the tracker would warn about (and re-unlink) it at exit
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:  # noqa: BLE001
            pass  # graftlint: disable=GL-O002 (bookkeeping only)


def _noop():
    pass


def _ctl_name(token):
    return "%s%s_ctl" % (ARENA_PREFIX, token)


def _entry_name(token, serial):
    return "%s%s_e%d" % (ARENA_PREFIX, token, serial)


def _lock_path(token):
    return os.path.join(tempfile.gettempdir(), "%s%s.lock"
                        % (ARENA_PREFIX, token))


def _blob_base(meta_len):
    return (_HEADER.size + meta_len + _ALIGN - 1) & ~(_ALIGN - 1)


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False


def _untrack_segment(seg):
    """gh-82300: deregister an ATTACHED segment from this process's
    resource_tracker (the shm_ring helper — one fix, one place)."""
    from petastorm_tpu.parallel.shm_ring import untrack_attachment

    untrack_attachment(seg)


# -- process-wide singleton + env handoff ----------------------------------------------
#
# One arena handle per process, whoever asked first: the creating reader
# (host_arena), a pool child's bootstrap (attach_from_env), or a cache lazily
# resolving a pickled spec (resolve). Stored in a dict (not a bare global) so
# ownership is visibly held for GL-L001.

ENV_ATTACH = "PTPU_ARENA_ATTACH"

_state_lock = threading.Lock()
_STATE = {"arena": None, "failed_tokens": set()}


def arena_enabled():
    """The ``PTPU_ARENA=off`` kill switch (also accepts 0/false/no)."""
    raw = (os.environ.get("PTPU_ARENA") or "").strip().lower()
    return raw not in ("0", "off", "false", "no")


def process_arena():
    """This process's arena handle, or ``None`` (never creates/attaches)."""
    with _state_lock:
        arena = _STATE["arena"]
    return arena if arena is not None and not arena._closed else None


def current_token():
    """The attach token children should receive via ``PTPU_ARENA_ATTACH``."""
    arena = process_arena()
    return arena.spec.token if arena is not None else None


def host_arena(create_bytes, ctl_bytes=DEFAULT_CTL_BYTES):
    """Create (or return) this process's arena with ``create_bytes`` budget.

    Returns ``None`` — with a warn-once ``arena_unavailable`` degradation —
    when the kill switch is set or shared memory/flock is unusable on this
    platform; callers then keep today's per-process caches (byte-identical
    output, just N× resident)."""
    if not create_bytes or not arena_enabled():
        return None
    with _state_lock:
        arena = _STATE["arena"]
        if arena is not None and not arena._closed:
            return arena
        from petastorm_tpu.parallel.shm_ring import shm_supported

        if not shm_supported():
            degradation("arena_unavailable",
                        "shared-memory cache arena unavailable (no shm); "
                        "per-process caches in effect")
            return None
        try:
            arena = CacheArena(budget_bytes=int(create_bytes),
                               ctl_bytes=ctl_bytes)
        except Exception as e:  # noqa: BLE001 — any failure degrades to local caches
            degradation("arena_unavailable",
                        "shared-memory cache arena create failed (%s); "
                        "per-process caches in effect", e)
            return None
        _STATE["arena"] = arena
        _register_atexit()
    return arena


def resolve(spec):
    """Attach to the arena named by ``spec`` (memoized per process). A pool
    child that already attached at bootstrap — or IS the creator (thread
    pools) — gets the existing handle. Returns ``None`` on failure (the
    creator died and unlinked, spec from another host, ...) with a warn-once
    degradation."""
    if spec is None or not arena_enabled():
        return None
    with _state_lock:
        arena = _STATE["arena"]
        if arena is not None and not arena._closed:
            return arena
        if spec.token in _STATE["failed_tokens"]:
            return None
        try:
            arena = CacheArena(spec=spec)
        except Exception as e:  # noqa: BLE001 — attach failure degrades to local caches
            _STATE["failed_tokens"].add(spec.token)
            degradation("arena_unavailable",
                        "cache arena attach failed for token %s (%s); "
                        "per-process caches in effect", spec.token, e)
            return None
        _STATE["arena"] = arena
        _register_atexit()
    return arena


def attach_from_env():
    """Pool-child bootstrap hook (the ``PTPU_CHAOS_SPEC`` convention): attach
    the parent's arena named by ``PTPU_ARENA_ATTACH`` so a freshly spawned —
    or RESPAWNED (the env survives on the executor's ``_child_env``) — child
    starts warm. Failure-tolerant; returns the arena or ``None``."""
    token = os.environ.get(ENV_ATTACH)
    if not token:
        return None
    return resolve(ArenaSpec(token))


_atexit_armed = []


def _register_atexit():
    if not _atexit_armed:
        _atexit_armed.append(True)
        atexit.register(close_process_arena)


def close_process_arena():
    """Close/detach this process's arena (atexit safety net + test hook).
    The creator unlinks every segment; attachers detach."""
    with _state_lock:
        arena, _STATE["arena"] = _STATE["arena"], None
    if arena is not None:
        arena.close()
    return arena is not None
