"""One read funnel: MemCache → LocalDiskCache → remote, with admission policy.

Before ISSUE 8 the tiers were ad-hoc: ``reader.py``'s ``_maybe_memcache``
bolted a :class:`~petastorm_tpu.io.memcache.MemCache` in front of whatever
``make_cache`` built, and nothing counted which tier actually served a read
or decided what deserved admission where. :class:`TieredCache` is the one
funnel every worker read goes through:

- **Per-tier accounting**: every serve is attributed —
  ``ptpu_io_tier_hits_total{tier="mem"|"disk"|"remote"}`` and
  ``ptpu_io_tier_bytes_total{tier=...}`` — so "where do my bytes come from"
  is one Prometheus query (and one ``Reader.io_stats()`` read: warm epochs
  should be mem/disk-served; a remote-heavy steady state means the budgets
  are wrong).
- **Admission policy** (``disk_admit``): ``"always"`` is the legacy
  contract — a remote fill is written to the disk tier unconditionally.
  ``"scan-resistant"`` applies the object-store economics: a value the mem
  tier just admitted is NOT also written to disk (it will serve from memory;
  re-filling disk doubles the write amplification for bytes already paid
  for), and a **single-epoch scan** — each row group read exactly once,
  nothing ever re-read — is not admitted to disk at all (classic scan
  resistance; an epoch-1 training sweep would otherwise evict the hot
  validation set to cache bytes nobody will read again). Disk HITS are always
  served either way; only admission is policed.

The funnel degrades to exactly its parts: no mem budget → mem tier absent;
``cache_type="null"`` → the disk tier is a no-op and every miss is a remote
fill. The lease/read-only serving contract of the mem tier (ISSUE 6) is
unchanged — this class composes :class:`MemCache`, it does not reimplement
it.
"""
from __future__ import annotations

from petastorm_tpu.cache import CacheBase, NullCache
from petastorm_tpu.io.memcache import payload_nbytes
from petastorm_tpu.obs import provenance as _prov
from petastorm_tpu.obs.metrics import default_registry

#: serve-attribution tiers, hot-to-cold: ``arena`` (ISSUE 17) sits between
#: this process's mem store and the disk tier — a host-shared mapping is
#: cheaper than a disk read but costs a cross-process lock + map vs a local
#: dict hit. The mem tier reports which of the two actually served.
TIERS = ("mem", "arena", "disk", "remote")


class TieredCache(CacheBase):
    """The MemCache → disk-cache → remote read funnel (one per reader; thin
    and picklable — pool children rebuild their tier counters on first use).

    ``mem`` is a :class:`~petastorm_tpu.io.memcache.MemCache` or ``None``;
    ``disk`` is any :class:`~petastorm_tpu.cache.CacheBase` (the configured
    ``LocalDiskCache``, or :class:`NullCache` for uncached readers).
    ``single_epoch`` is the reader's scan hint (``num_epochs == 1``) consumed
    by the ``scan-resistant`` policy. ``clear()``/``cleanup()`` release the
    mem tier's process-wide bytes — graftlint GL-L001 accepts them as this
    type's closers.
    """

    def __init__(self, mem=None, disk=None, disk_admit="always",
                 single_epoch=False, tenant=None):
        if disk_admit not in ("always", "scan-resistant"):
            raise ValueError("disk_admit must be 'always' or 'scan-resistant', "
                             "got %r" % (disk_admit,))
        self._mem = mem
        self._disk = disk if disk is not None else NullCache()
        self._disk_admit = disk_admit
        self._single_epoch = bool(single_epoch)
        #: tenant slug (ISSUE 18): a plain string survives pickling into pool
        #: children, so a child-side rebuild keeps charging the same tenant
        self._tenant = tenant
        self._metrics = None  # lazy; a registry handle must not cross pickling

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_metrics"] = None
        return state

    def _count(self, tier, value):
        metrics = self._metrics
        if metrics is None:
            reg = default_registry()
            metrics = self._metrics = {
                t: (reg.counter("ptpu_io_tier_hits_total",
                                help="reads served per cache tier", tier=t),
                    reg.counter("ptpu_io_tier_bytes_total",
                                help="payload bytes served per cache tier",
                                tier=t),
                    [0, 0],
                    # tenant twins (ISSUE 18): charged ALONGSIDE the untagged
                    # totals above, never instead — per-tenant sums reconcile
                    # against the totals by construction
                    None if self._tenant is None else
                    (reg.counter("ptpu_io_tier_hits_total",
                                 tier=t, tenant=self._tenant),
                     reg.counter("ptpu_io_tier_bytes_total",
                                 tier=t, tenant=self._tenant)))
                for t in TIERS
            }
        hits, nbytes, local, tagged = metrics[tier]
        hits.inc()
        n = payload_nbytes(value)
        nbytes.inc(n)
        local[0] += 1
        local[1] += n
        if tagged is not None:
            tagged[0].inc()
            tagged[1].inc(n)
            from petastorm_tpu.obs import tenant as _tenant_ctx

            _tenant_ctx.charge("read_bytes", n, label=self._tenant)

    def _admit_disk(self, value):
        """Should this remote-filled ``value`` be written to the disk tier?
        Decided from the VALUE, after the fill: the scan-resistant policy
        skips disk only for what the mem tier will actually hold — a payload
        the memcache rejects as oversized still earns its disk slot, or it
        would be cached in no tier and refetched remotely every epoch."""
        if self._disk_admit == "always":
            return True
        if self._single_epoch:
            return False  # scan resistance: one-shot sweeps don't earn disk
        if self._mem is not None and self._mem.would_admit(value):
            return False  # the mem tier serves it; don't double-store
        return True

    def _through_disk(self, key, fill, served):
        """disk tier → remote fill, honoring the admission policy."""
        def from_remote():
            served[0] = "remote"
            return fill()

        served[0] = "disk"
        if isinstance(self._disk, NullCache):
            return from_remote()
        if self._disk_admit == "always":
            return self._disk.get(key, from_remote)
        # scan-resistant: serve hits; on a miss, fill remote first and admit
        # per-value (a disk .get would write through unconditionally)
        if self._disk.contains(key):
            return self._disk.get(key, from_remote)
        value = from_remote()
        if self._admit_disk(value):
            self._disk.get(key, lambda: value)  # miss → stores the value
        return value

    def get(self, key, fill_cache_func):
        served = ["mem"]
        if self._mem is not None:
            # the mem tier flips served[0] to "arena" when the payload came
            # off the host-shared mapping instead of the local store
            value = self._mem.get(
                key, lambda: self._through_disk(key, fill_cache_func, served),
                served=served)
        else:
            value = self._through_disk(key, fill_cache_func, served)
        self._count(served[0], value)
        if _prov.ACTIVE is not None:  # which tier fed this item (ISSUE 10)
            _prov.annotate("cache_tier", served[0])
        return value

    def get_writable(self, key, fill_cache_func):
        """The mem tier's copy-on-write escalation, threaded through the
        funnel (host ``TransformSpec`` consumers — see ``MemCache``)."""
        served = ["mem"]
        if self._mem is not None:
            value = self._mem.get_writable(
                key, lambda: self._through_disk(key, fill_cache_func, served),
                served=served)
        else:
            value = self._through_disk(key, fill_cache_func, served)
        self._count(served[0], value)
        if _prov.ACTIVE is not None:
            _prov.annotate("cache_tier", served[0])
        return value

    # -- live knobs (ISSUE 13) ----------------------------------------------------------

    def apply_disk_admit(self, policy):
        """Retune the disk admission policy live — the sanctioned seam (the
        options struct is never mutated, GL-C004). Applies from the next
        remote fill; already-admitted entries are untouched."""
        if policy not in ("always", "scan-resistant"):
            raise ValueError("disk_admit must be 'always' or 'scan-resistant', "
                             "got %r" % (policy,))
        self._disk_admit = policy
        return policy

    @property
    def disk_admit(self):
        return self._disk_admit

    @property
    def mem(self):
        """The mem tier (:class:`~petastorm_tpu.io.memcache.MemCache`) or
        ``None`` — the controller's hot-row-group promotion target."""
        return self._mem

    def contains(self, key):
        if self._mem is not None and self._mem.contains(key):
            return True
        return self._disk.contains(key)

    def invalidate(self, key):
        """Keyed invalidation through every tier (ISSUE 11: the dataset-watch
        plane drops a rewritten piece's decoded payloads from mem AND disk —
        generation-scoped keys already make them unreachable; this reclaims
        the bytes)."""
        if self._mem is not None:
            self._mem.invalidate(key)
        self._disk.invalidate(key)

    def clear(self):
        if self._mem is not None:
            self._mem.clear()

    def stats(self):
        out = {}
        if self._mem is not None:
            out.update(self._mem.stats())
        stats_fn = getattr(self._disk, "stats", None)
        if stats_fn is not None:
            out.update(stats_fn())
        metrics = self._metrics
        if metrics is not None:
            for tier, (_h, _b, local, _tagged) in metrics.items():
                out["tier_%s_hits" % tier] = local[0]
                out["tier_%s_bytes" % tier] = local[1]
        return out

    def cleanup(self):
        self.clear()
        self._disk.cleanup()
