"""Dataset-statistics resolution for declarative pipelines (ISSUE 9).

Statistics-dependent ops (``Normalize`` without bounds, ``Standardize``,
quantile ``Bucketize``, computed ``VocabLookup``) need dataset-level numbers
before the pipeline can compile. Resolution is tiered, cheapest first:

1. **Row-group statistics** — min/max aggregate from the parquet footers via
   :func:`petastorm_tpu.metadata.aggregate_column_stats` (the existing
   statistics plumbing; shared footer cache, zero data reads). Only exact
   aggregates ride this tier: mean/std/quantiles/vocab cannot.
2. **One streaming data pre-pass** — needed columns of every scheduled row
   group are read once, feeding per-column accumulators (count/sum/sumsq for
   mean/std, a deterministic stride-decimated sample for quantiles, a
   frequency table for vocabularies).
3. **Cache** — the pass result is cached per ``(dataset fingerprint,
   requirement set)`` in a process-wide table AND written through the
   reader's tiered cache (mem→disk) when one is configured, so re-opens and
   sibling readers skip the pass.

``resolve_statistics`` returns ``{requirement key: value}`` plus a
``sources`` ledger (``rowgroup-stats`` / ``data-pass`` / ``cached``) the
pipeline surfaces as ``FeaturePipeline.stats_info``.
"""
from __future__ import annotations

import hashlib
import threading

import numpy as np

#: cap on the deterministic quantile sample per column (stride-decimated —
#: when the stream exceeds the cap, every other retained sample is dropped and
#: the stride doubles, so the kept set stays uniform and run-deterministic)
QUANTILE_SAMPLE_CAP = 65536

#: distinct values tracked per vocabulary column before low-frequency entries
#: are pruned (bound on the frequency table, not on the final vocab)
VOCAB_TRACK_CAP = 1 << 16

_memo_lock = threading.Lock()
_memo = {}  # fingerprint -> {req key: value}


def dataset_fingerprint(fs, pieces, req_keys):
    """Stable identity of (scheduled data, requested statistics): the sorted
    ``(path, row_group, num_rows, generation)`` piece set, each file's
    size/mtime (so a dataset regenerated IN PLACE — same names, new values —
    invalidates the cached pass; the footer cache keys by size for the same
    reason), plus the requirement keys. Watch-stamped generation tokens
    (ISSUE 11) ride in the piece tuple, so a rewrite that collides on
    size/mtime still changes the fingerprint through the footer crc. Two
    readers over the same pieces share one pass."""
    h = hashlib.sha256()
    for p in sorted((p.path, p.row_group, p.num_rows,
                     p.generation or "") for p in pieces):
        h.update(repr(p).encode("utf-8"))
    for path in sorted({p.path for p in pieces}):
        try:
            info = fs.get_file_info(path)
            token = "%s|%s|%s" % (path, getattr(info, "size", None),
                                  getattr(info, "mtime_ns", None))
        except Exception:  # noqa: BLE001 — stat failure: path-only identity
            token = path
        h.update(token.encode("utf-8"))
    for key in sorted(req_keys):
        h.update(key.encode("utf-8"))
    return h.hexdigest()


def clear_memo():
    """Drop the in-process pass memo (test isolation)."""
    with _memo_lock:
        _memo.clear()


class _ColumnAccumulator:
    """Streaming accumulators for every pass-tier statistic of one column."""

    def __init__(self, want_moments, want_quantiles, want_vocab):
        self.want_moments = want_moments
        self.want_quantiles = want_quantiles
        self.want_vocab = want_vocab
        self.count = 0
        self.total = 0.0
        self.sq_total = 0.0
        self.minimum = None
        self.maximum = None
        self.samples = []
        self.stride = 1
        self._stride_phase = 0
        self.freq = {}
        self.freq_floor = 0  # lossy-counting error bound once pruning starts

    def update(self, arr):
        arr = np.asarray(arr)
        if self.want_moments or self.want_quantiles:
            values = arr.astype(np.float64, copy=False).ravel()
            if values.size and np.issubdtype(values.dtype, np.floating):
                values = values[~np.isnan(values)]
            if values.size:
                self.count += int(values.size)
                self.total += float(values.sum())
                self.sq_total += float(np.square(values).sum())
                mn, mx = float(values.min()), float(values.max())
                self.minimum = mn if self.minimum is None else min(self.minimum, mn)
                self.maximum = mx if self.maximum is None else max(self.maximum, mx)
                if self.want_quantiles:
                    self._sample(values)
        if self.want_vocab:
            uniques, counts = np.unique(arr.ravel(), return_counts=True)
            freq = self.freq
            # lossy counting: once pruning has happened, an unseen (or
            # pruned-and-returned) value re-enters at the error floor, so a
            # genuinely frequent value spread across the stream can be
            # UNDERcounted by at most freq_floor — never silently zeroed
            floor = self.freq_floor
            for value, n in zip(uniques.tolist(), counts.tolist()):
                freq[value] = freq.get(value, floor) + n
            if len(freq) > VOCAB_TRACK_CAP:
                ranked = sorted(freq.items(),
                                key=lambda kv: (-kv[1], str(kv[0])))
                cut = ranked[VOCAB_TRACK_CAP // 2:]
                self.freq_floor = max(self.freq_floor,
                                      max(c for _v, c in cut))
                self.freq = dict(ranked[:VOCAB_TRACK_CAP // 2])

    def _sample(self, values):
        take = values[self._stride_phase::self.stride]
        self._stride_phase = (self._stride_phase - values.size) % self.stride
        self.samples.extend(take.tolist())
        while len(self.samples) > QUANTILE_SAMPLE_CAP:
            self.samples = self.samples[::2]
            self.stride *= 2

    def moments(self):
        if not self.count:
            raise ValueError("statistics pass saw no values for the column")
        mean = self.total / self.count
        var = max(self.sq_total / self.count - mean * mean, 0.0)
        return mean, float(np.sqrt(var))

    def quantile_boundaries(self, num_buckets):
        if not self.samples:
            raise ValueError("statistics pass saw no values for the column")
        qs = [i / num_buckets for i in range(1, num_buckets)]
        return np.quantile(np.asarray(self.samples, dtype=np.float64), qs)

    def vocabulary(self, max_size):
        ranked = sorted(self.freq.items(), key=lambda kv: (-kv[1], str(kv[0])))
        return [value for value, _n in ranked[:max_size]]


def _column_pass(fs, pieces, accumulators):
    """THE streaming pre-pass: read only the accumulated columns of every
    scheduled row group once (shared footer cache keeps metadata reads at one
    per file) and feed the accumulators."""
    import pyarrow.parquet as pq

    columns = sorted(accumulators)
    by_path = {}
    for piece in pieces:
        by_path.setdefault(piece.path, set()).add(piece.row_group)
    for path in sorted(by_path):
        with fs.open_input_file(path) as f:
            pf = pq.ParquetFile(f)
            available = set(pf.schema_arrow.names)
            wanted = [c for c in columns if c in available]
            if not wanted:
                continue
            for rg in sorted(by_path[path]):
                table = pf.read_row_group(rg, columns=wanted)
                for name in wanted:
                    accumulators[name].update(
                        table.column(name).to_numpy(zero_copy_only=False))


def _tier_key(fingerprint):
    return "ptpu-tabular-stats|%s" % fingerprint


def resolve_statistics(requirements, fs, pieces, cache=None):
    """Resolve every :class:`~petastorm_tpu.ops.tabular.StatRequirement` into
    ``(stats, sources)``: the value dict the pipeline binds, and the per-key
    resolution ledger. ``cache`` is the reader's tiered cache (optional)."""
    stats = {}
    sources = {}
    remaining = []
    # tier 1: exact min/max from the row-group statistics plumbing
    minmax = [r for r in requirements if r.kind in ("min", "max")]
    if minmax:
        from petastorm_tpu.metadata import aggregate_column_stats

        covered = aggregate_column_stats(fs, pieces,
                                         sorted({r.field for r in minmax}))
        for req in minmax:
            bounds = covered.get(req.field)
            if bounds is not None:
                stats[req.key] = bounds[0] if req.kind == "min" else bounds[1]
                sources[req.key] = "rowgroup-stats"
            else:
                remaining.append(req)
    remaining.extend(r for r in requirements if r.kind not in ("min", "max"))
    if not remaining:
        return stats, sources

    fingerprint = dataset_fingerprint(fs, pieces, [r.key for r in remaining])
    with _memo_lock:
        memo = _memo.get(fingerprint)
    if memo is not None:
        stats.update(memo)
        for req in remaining:
            sources[req.key] = "cached"
        return stats, sources

    def run_pass():
        accumulators = {}
        for req in remaining:
            acc = accumulators.get(req.field)
            if acc is None:
                acc = accumulators[req.field] = _ColumnAccumulator(
                    False, False, False)
            if req.kind in ("min", "max", "mean", "std"):
                acc.want_moments = True
            if req.kind == "quantiles":
                acc.want_quantiles = True
            if req.kind == "vocab":
                acc.want_vocab = True
        _column_pass(fs, pieces, accumulators)
        out = {}
        for req in remaining:
            acc = accumulators[req.field]
            if req.kind == "min":
                if acc.minimum is None:
                    raise ValueError(
                        "statistics pass saw no values for %r" % req.field)
                out[req.key] = acc.minimum
            elif req.kind == "max":
                if acc.maximum is None:
                    raise ValueError(
                        "statistics pass saw no values for %r" % req.field)
                out[req.key] = acc.maximum
            elif req.kind == "mean":
                out[req.key] = acc.moments()[0]
            elif req.kind == "std":
                out[req.key] = acc.moments()[1]
            elif req.kind == "quantiles":
                out[req.key] = acc.quantile_boundaries(int(req.param))
            elif req.kind == "vocab":
                out[req.key] = acc.vocabulary(int(req.param))
        return out

    passed = [False]
    pass_result = {}  # survives a cache.get that throws AFTER fill ran

    def fill():
        passed[0] = True
        pass_result["payload"] = run_pass()
        return {"payload": pass_result["payload"]}

    if cache is not None:
        try:
            value = cache.get(_tier_key(fingerprint), fill)
            computed = dict(value["payload"])
        except Exception:  # noqa: BLE001 — a cache tier that can't hold this
            # payload shape must not fail the pipeline; keep the pass result
            # if fill already ran (never read the dataset twice), else run it
            computed = pass_result.get("payload")
            if computed is None:
                passed[0] = True
                computed = run_pass()
    else:
        passed[0] = True
        computed = run_pass()
    with _memo_lock:
        _memo[fingerprint] = computed
    source = "data-pass" if passed[0] else "cached"
    stats.update(computed)
    for req in remaining:
        sources[req.key] = source
    return stats, sources
