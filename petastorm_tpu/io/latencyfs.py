"""Latency-injecting filesystem proxies: the object-store emulation harness.

Two layers, shared by `petastorm-tpu-bench io`, `petastorm-tpu-bench remote`
and the tests (one copy — ISSUE 8 satellite; ``benchmark/io.py`` used to own a
private ``LatencyFS`` the remote bench would have had to duplicate):

- :class:`LatencyFS` — the PR 4 model: every ``read()`` call against a file
  pays one flat round-trip delay. Right for "how many read calls does this
  path issue" experiments; too simple for hedging/sizing ones.
- :class:`CloudLatencyFS` — the ISSUE 8 cloud-object-store simulator:
  per-request latency = ``base + per_byte * nbytes + lognormal jitter``, with
  **seeded tail spikes** (a deterministic fraction of requests pays a
  multiplied delay — the object store's fat tail that request hedging exists
  to cut) and **per-request accounting** (``requests`` records every GET's
  path/offset/bytes/delay/attempt) so benchmarks assert round-trip counts and
  footer-read counts as hard numbers, without credentials or a network.

Determinism: spike/jitter decisions are pure functions of ``(seed, path,
offset, nbytes, attempt)`` via crc32 (the :mod:`petastorm_tpu.chaos` trick), so
a scenario replays identically however threads interleave — and a *hedged
duplicate* of the same range (attempt 2) rolls fresh dice, which is exactly
how a re-issued GET against a different storage replica behaves.
"""
from __future__ import annotations

import math
import threading
import time
import zlib

#: one simulated GET: (path, offset, nbytes, delay_s, attempt)
_REQUEST_FIELDS = ("path", "offset", "nbytes", "delay_s", "attempt")


class LatencyFile:
    """File-object proxy paying one round-trip delay per ``read`` call —
    what a ranged GET against an object store costs. Wrapped back into a
    pyarrow file via ``pa.PythonFile`` by :meth:`LatencyFS.open_input_file`."""

    def __init__(self, inner, latency_s, counter):
        self._inner = inner
        self._latency_s = latency_s
        self._counter = counter

    def _delay(self, offset, nbytes):
        self._counter[0] += 1
        if self._latency_s > 0.0:
            time.sleep(self._latency_s)

    def read(self, nbytes=None):
        offset = self._inner.tell()
        data = self._inner.read(nbytes) if nbytes is not None else self._inner.read()
        self._delay(offset, len(data))
        return data

    def seek(self, pos, whence=0):
        return self._inner.seek(pos, whence)

    def tell(self):
        return self._inner.tell()

    def size(self):
        return self._inner.size()

    def close(self):
        self._inner.close()

    @property
    def closed(self):
        return self._inner.closed

    def readable(self):
        return True

    def seekable(self):
        return True

    def writable(self):
        return False


class LatencyFS:
    """pyarrow-filesystem proxy injecting per-read-call latency (the PR 4
    benchmark's object-store emulation; also counts total read calls so the
    coalesce ratio is visible as a hard number)."""

    #: subclasses override to wrap reads with their own cost model
    _file_cls = LatencyFile

    def __init__(self, inner, latency_s):
        self._inner = inner
        self._latency_s = latency_s
        self.read_calls = [0]  # shared mutable cell: files outlive this scope

    def open_input_file(self, path):
        import pyarrow as pa

        inner = self._inner.open_input_file(path)
        return pa.PythonFile(
            self._make_file(inner, path), mode="r")

    def _make_file(self, inner, path):
        return self._file_cls(inner, self._latency_s, self.read_calls)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _hash01(*parts):
    """Deterministic uniform in [0, 1) from the identity of one request."""
    h = zlib.crc32("|".join(str(p) for p in parts).encode("utf-8"))
    return (h & 0xFFFFFF) / float(1 << 24)


class _CloudFile(LatencyFile):
    """Per-request cost model + accounting (built by :class:`CloudLatencyFS`)."""

    def __init__(self, inner, path, fs):
        super().__init__(inner, 0.0, fs.read_calls)
        self._path = path
        self._fs = fs

    def _delay(self, offset, nbytes):
        self._counter[0] += 1
        self._fs._account(self._path, offset, nbytes)


class CloudLatencyFS(LatencyFS):
    """Seeded cloud-object-store simulator over any pyarrow filesystem.

    ``base_latency_s`` is the same-region request floor (~5 ms for GCS/S3),
    ``per_byte_s`` the streaming cost (default ≈ 1 s/GB ≈ 8 Gbps),
    ``jitter_sigma`` the lognormal spread on the floor, and a ``tail_fraction``
    of requests pays ``tail_multiplier``× the floor — the fat tail. All
    randomness is a pure function of ``(seed, path, offset, nbytes, attempt)``
    where ``attempt`` counts repeat GETs of the identical range (a hedged
    duplicate is attempt 2 and rolls fresh dice).

    ``requests`` collects ``(path, offset, nbytes, delay_s, attempt)`` dicts;
    :meth:`request_count`/:meth:`footer_requests` turn them into the hard
    numbers the remote bench asserts. ``type_name`` reports ``"cloudsim"`` so
    the auto-enable probe in :mod:`petastorm_tpu.io.remote` treats this
    filesystem as a remote store.
    """

    type_name = "cloudsim"

    def __init__(self, inner, base_latency_s=0.005, per_byte_s=1.0 / (1 << 30),
                 jitter_sigma=0.15, tail_fraction=0.02, tail_multiplier=10.0,
                 seed=0, sleep=True):
        super().__init__(inner, 0.0)
        self._base = float(base_latency_s)
        self._per_byte = float(per_byte_s)
        self._sigma = float(jitter_sigma)
        self._tail_fraction = float(tail_fraction)
        self._tail_multiplier = float(tail_multiplier)
        self._seed = int(seed)
        self._sleep = bool(sleep)
        self._lock = threading.Lock()
        self._attempts = {}  # (path, offset, nbytes) -> GETs issued so far
        self.requests = []

    def __getstate__(self):
        # picklable for process pools: children re-create the lock and keep
        # their OWN accounting (per-process request logs, like the io counters)
        state = dict(self.__dict__)
        state["_lock"] = None
        state["_attempts"] = {}
        state["requests"] = []
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _make_file(self, inner, path):
        return _CloudFile(inner, path, self)

    def delay_for(self, path, offset, nbytes, attempt):
        """The deterministic delay of one GET (public: tests assert on it).

        The dice roll on the path's BASENAME, not the full path: benches and
        tests write their datasets under per-run temp dirs, and a seed must
        mean the same spike pattern every run — otherwise every seeded
        assertion (hedges fire, p99 improves) is a latent CI flake."""
        name = path.rsplit("/", 1)[-1]
        u = _hash01(self._seed, name, offset, nbytes, attempt, "jitter")
        # inverse-transform a lognormal from the uniform (Box-Muller needs two;
        # a probit approximation is plenty for a latency floor's spread)
        z = _probit(min(max(u, 1e-9), 1.0 - 1e-9))
        delay = self._base * math.exp(self._sigma * z)
        if _hash01(self._seed, name, offset, nbytes, attempt,
                   "tail") < self._tail_fraction:
            delay *= self._tail_multiplier
        return delay + self._per_byte * nbytes

    def _account(self, path, offset, nbytes):
        key = (path, offset, nbytes)
        with self._lock:
            attempt = self._attempts.get(key, 0) + 1
            self._attempts[key] = attempt
        delay = self.delay_for(path, offset, nbytes, attempt)
        with self._lock:
            self.requests.append(dict(zip(
                _REQUEST_FIELDS, (path, offset, nbytes, delay, attempt))))
        if self._sleep and delay > 0.0:
            time.sleep(delay)

    # -- accounting views ---------------------------------------------------------------

    def request_count(self):
        with self._lock:
            return len(self.requests)

    def reset_accounting(self):
        with self._lock:
            self.requests = []
            self.read_calls[0] = 0

    def footer_requests(self, file_sizes, footer_window=1 << 16):
        """GETs that touched any file's footer region (its last
        ``footer_window`` bytes) — the metadata-plane round trips the footer
        cache exists to collapse. ``file_sizes`` maps path -> total bytes;
        ``footer_window`` is an int or a per-path dict (e.g. each file's
        exact footer length, so tail data GETs are never miscounted on small
        files)."""
        out = []
        with self._lock:
            reqs = list(self.requests)
        for r in reqs:
            size = file_sizes.get(r["path"])
            if size is None:
                continue
            window = footer_window.get(r["path"], 0) \
                if isinstance(footer_window, dict) else footer_window
            if r["offset"] + r["nbytes"] > max(0, size - window):
                out.append(r)
        return out


def _probit(u):
    """Acklam's inverse-normal-CDF approximation (no scipy dependency)."""
    # coefficients for the central region are enough at our precision needs
    a = (-3.969683028665376e+01, 2.209460984245205e+02, -2.759285104469687e+02,
         1.383577518672690e+02, -3.066479806614716e+01, 2.506628277459239e+00)
    b = (-5.447609879822406e+01, 1.615858368580409e+02, -1.556989798598866e+02,
         6.680131188771972e+01, -1.328068155288572e+01)
    c = (-7.784894002430293e-03, -3.223964580411365e-01, -2.400758277161838e+00,
         -2.549732539343734e+00, 4.374664141464968e+00, 2.938163982698783e+00)
    d = (7.784695709041462e-03, 3.224671290700398e-01, 2.445134137142996e+00,
         3.754408661907416e+00)
    plow, phigh = 0.02425, 1 - 0.02425
    if u < plow:
        q = math.sqrt(-2 * math.log(u))
        return (((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q + c[5]) / \
            ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    if u > phigh:
        q = math.sqrt(-2 * math.log(1 - u))
        return -(((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q + c[5]) / \
            ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    q = u - 0.5
    r = q * q
    return (((((a[0] * r + a[1]) * r + a[2]) * r + a[3]) * r + a[4]) * r + a[5]) * q / \
        (((((b[0] * r + b[1]) * r + b[2]) * r + b[3]) * r + b[4]) * r + 1)
