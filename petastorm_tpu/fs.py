"""URL → (pyarrow FileSystem, path) resolution.

Capability parity with the reference filesystem layer (petastorm/fs_utils.py ~L40
``FilesystemResolver``, ~L200 ``get_filesystem_and_path_or_paths``; petastorm/hdfs/;
petastorm/gcsfs_helpers/): file/hdfs/s3/gs URL schemes, user-supplied ``filesystem`` and
``storage_options`` passthrough.

TPU-first delta: built directly on ``pyarrow.fs`` (which wraps GCS/S3/HDFS natively) with an
fsspec bridge for anything else — no hand-rolled namenode HA logic; pyarrow's HDFS client already
consumes ``core-site.xml``. GCS is the north-star source (BASELINE.json reads ImageNet-Parquet
from GCS), so ``gs://`` resolves through pyarrow's GcsFileSystem when available, else gcsfs.

HDFS HA (petastorm/hdfs/namenode.py ~L40–L200 parity, two layers): libhdfs (behind
``pyarrow.fs.HadoopFileSystem``) natively resolves nameservice authorities from
``core-site.xml``/``hdfs-site.xml``; ON TOP, :mod:`petastorm_tpu.hdfs` resolves
``dfs.nameservices``/``dfs.ha.namenodes.*``/``dfs.namenode.rpc-address.*`` itself and wraps
multi-namenode services in ``HAHdfsClient`` — every filesystem call retries across the
namenode list with reconnect-on-standby and raises ``MaxFailoversExceeded`` after the
configured passes (the reference's app-level guarantee, so a namenode flip mid-epoch
rotates instead of killing the read). ``hdfs:///path`` (no authority) maps to
``fs.defaultFS``. URL→constructor dispatch + failover are covered by mocked tests
(tests/test_fs.py, tests/test_hdfs_ha.py) without a cluster.
"""
from __future__ import annotations

from urllib.parse import urlparse


def get_filesystem_and_path_or_paths(url_or_urls, storage_options=None, filesystem=None):
    """Resolve a dataset URL (or list of URLs) to (pyarrow_filesystem, path_or_paths).

    All URLs in a list must share scheme+authority (reference behavior, fs_utils.py ~L200).
    """
    urls = url_or_urls if isinstance(url_or_urls, (list, tuple)) else [url_or_urls]
    if not urls:
        raise ValueError("Empty URL list")
    parsed = [urlparse(str(u)) for u in urls]
    scheme0, netloc0 = parsed[0].scheme, parsed[0].netloc
    for i, p in enumerate(parsed[1:], 1):
        if (p.scheme, p.netloc) != (scheme0, netloc0):
            raise ValueError(
                "All dataset URLs must share scheme and authority; got %r vs %r"
                % (urls[0], urls[i])
            )
    if filesystem is not None:
        paths = [_strip_scheme(p) for p in parsed]
    else:
        filesystem, paths = _resolve(parsed, urls, storage_options or {})
    result = paths if isinstance(url_or_urls, (list, tuple)) else paths[0]
    return filesystem, result


def get_dataset_path(parsed_url):
    """Path component of a parsed dataset URL (reference: fs_utils.get_dataset_path)."""
    if parsed_url.scheme in ("", "file"):
        return parsed_url.path
    return _strip_scheme(parsed_url)


class FilesystemResolver:
    """Compat shim for the reference resolver CLASS (petastorm/fs_utils.py ~L40) —
    user code calls it directly (``FilesystemResolver(url).filesystem()``). New code
    should prefer :func:`get_filesystem_and_path_or_paths`.

    ``hdfs_driver`` and ``user`` are accepted for signature compatibility; driver
    selection is libhdfs-only here (see the module docstring's HA compat decision).
    """

    def __init__(self, dataset_url, storage_options=None, filesystem=None,
                 hdfs_driver=None, user=None):  # noqa: ARG002 — reference signature
        self._dataset_url = str(dataset_url)
        self._parsed = urlparse(self._dataset_url)
        self._filesystem, self._path = get_filesystem_and_path_or_paths(
            self._dataset_url, storage_options=storage_options, filesystem=filesystem)

    def filesystem(self):
        """The resolved ``pyarrow.fs`` filesystem."""
        return self._filesystem

    def get_dataset_path(self):
        """Filesystem-relative dataset path."""
        return self._path

    def parsed_dataset_url(self):
        """The ``urllib.parse`` result for the original URL."""
        return self._parsed


def _strip_scheme(parsed):
    if parsed.scheme in ("", "file"):
        return parsed.path
    # bucket-style schemes keep the authority as path prefix (s3/gs); hdfs does not
    if parsed.scheme in ("s3", "s3a", "s3n", "gs", "gcs"):
        return (parsed.netloc + parsed.path).rstrip("/")
    return parsed.path


def _resolve(parsed, urls, storage_options):
    import pyarrow.fs as pafs

    scheme = parsed[0].scheme
    if scheme in ("", "file"):
        return pafs.LocalFileSystem(), [p.path for p in parsed]
    if scheme in ("s3", "s3a", "s3n"):
        fs = pafs.S3FileSystem(**storage_options)
        return fs, [(p.netloc + p.path).rstrip("/") for p in parsed]
    if scheme in ("gs", "gcs"):
        try:
            fs = pafs.GcsFileSystem(**storage_options)
        except Exception:  # noqa: BLE001 - fall back to fsspec/gcsfs
            import gcsfs

            fs = pafs.PyFileSystem(pafs.FSSpecHandler(gcsfs.GCSFileSystem(**storage_options)))
        return fs, [(p.netloc + p.path).rstrip("/") for p in parsed]
    if scheme == "hdfs":
        from petastorm_tpu.hdfs import connect_hdfs

        # nameservice authorities resolve through Hadoop config to an HA failover
        # client (petastorm/hdfs/namenode.py parity); explicit host:port stays a
        # plain libhdfs connection
        fs = connect_hdfs(parsed[0].hostname, parsed[0].port,
                          storage_options=storage_options)
        return fs, [p.path for p in parsed]
    # anything else: try fsspec
    try:
        import fsspec
        import pyarrow.fs as pafs2

        fsspec_fs, _, fpaths = fsspec.get_fs_token_paths(urls, storage_options=storage_options)
        return pafs2.PyFileSystem(pafs2.FSSpecHandler(fsspec_fs)), list(fpaths)
    except Exception as e:  # noqa: BLE001
        raise ValueError("Unsupported URL scheme %r (%s)" % (scheme, e)) from e
