"""Fleet telemetry aggregation + the autoscaling sensor (ISSUE 20).

Two read-only planes over the :class:`~petastorm_tpu.service.server
.DataService`:

- :class:`FleetTelemetry` holds the latest ``/timelines``-shaped export each
  peer piggybacked on its frames (decode workers on lease replies, trainers
  on ``want``) and assembles the ``GET /fleet`` document: the service's own
  export merged with every peer's on anchored clocks
  (:func:`~petastorm_tpu.obs.timeseries.merge_exports` — the same clock-anchor
  discipline the PR 12 ``--merge`` CLI uses), per-worker health (outstanding
  leases + oldest age, decode p50/p99, idle share, tenants served), and the
  straggler/advice state.
- :class:`FleetAdvisor` rides the TimelineStore listener cadence (the same
  seam the SLO engine attaches to) and computes an **advised fleet size**
  from the starvation / idle / burn-rate windows plus per-worker straggler
  p99s. It publishes ``ptpu_svc_advised_workers`` and a ``svc_advise`` flight
  event on every change — a sensor only: the ``ensure_workers``/``withdraw``
  actuator belongs to a later PR, exactly like the PR 13 controller grew out
  of the PR 12 temporal plane.
"""
from __future__ import annotations

import math
import os
import threading
import time
from collections import deque

from petastorm_tpu.obs.slo import strip_label

#: worker-labeled series the advisor and the health panel read
WORKER_DECODE_HIST = "ptpu_svc_worker_decode_seconds"
WORKER_IDLE_TOTAL = "ptpu_svc_worker_idle_seconds_total"


class FleetTelemetry:
    """Latest-export store + ``/fleet`` document assembly for one service."""

    def __init__(self, service, registry):
        self._service = service
        self._registry = registry
        self._lock = threading.Lock()
        self._peers = {}  # (kind, name) -> latest export document

    def note_peer(self, kind, name, doc):
        """Absorb one piggybacked export (``kind`` = worker|trainer). Only
        the latest document per peer is kept — telemetry is a level, not a
        log."""
        if not isinstance(doc, dict):
            return
        with self._lock:
            self._peers[(kind, str(name))] = doc

    def drop_peer(self, kind, name):
        with self._lock:
            self._peers.pop((kind, str(name)), None)

    def peer_exports(self):
        with self._lock:
            return dict(self._peers)

    def document(self):
        """The ``GET /fleet`` JSON document. Pull-model: assembled per
        request from the latest state — nothing here runs on a hot path."""
        from petastorm_tpu.obs.timeseries import (
            export_document,
            export_to_merge_shape,
            merge_exports,
        )

        own = export_document(self._registry,
                              extra={"source": "service:%d" % os.getpid()})
        exports = [export_to_merge_shape(own)]
        for (kind, name), doc in sorted(self.peer_exports().items()):
            exports.append(export_to_merge_shape(
                doc, fallback_source="%s:%s" % (kind, name)))
        return {
            "schema": "ptpu-svc-fleet-v1",
            "ts": time.time(),
            "workers": self._service.worker_health(),
            "advice": self._service.advice(),
            "alerts": self._service.straggler_alerts(),
            "fleet": merge_exports(exports),
            "sources": [e["source"] for e in exports],
        }


class FleetAdvisor:
    """Advised-fleet-size sensor on the TimelineStore listener cadence.

    Per sampled window, with ``actual`` the connected-worker gauge:

    - **stragglers**: every worker whose window decode p99 exceeds
      ``straggler_p99_s`` is effectively lost capacity — advise a
      replacement for each (the same threshold the straggler SLO debounces
      on, so the alert and the advice agree on who is slow);
    - **burn**: when trainers starved (``ptpu_svc_starved_seconds_total``
      rate above ``starved_hi`` seconds-per-second) while the fleet ran hot
      (decode burn-rate ≥ ``util_hi`` × actual), add the starvation rate's
      ceiling — the fleet undersupplied attached demand;
    - **idle**: with no stragglers and no starvation, a mean per-worker idle
      share above ``idle_hi`` advises shrinking toward the busy core.

    The published value is the median of the last ``smooth`` windows (one
    anomalous window cannot flap the advice), clamped to
    ``[min_workers, max_workers]``.
    """

    def __init__(self, registry, straggler_p99_s=None, min_workers=1,
                 max_workers=64, starved_hi=0.05, idle_hi=0.6, util_hi=0.8,
                 smooth=3):
        from petastorm_tpu.service.protocol import svc_metrics

        self._registry = registry
        self._gauge = svc_metrics(registry)["advised_workers"]
        self._straggler_s = straggler_p99_s
        self._min = max(0, int(min_workers))
        self._max = int(max_workers)
        self._starved_hi = float(starved_hi)
        self._idle_hi = float(idle_hi)
        self._util_hi = float(util_hi)
        self._history = deque(maxlen=max(1, int(smooth)))
        self._published = None
        self.last_detail = None
        self._store = None
        self._listener = None

    # -- wiring ---------------------------------------------------------------------

    def attach(self, store):
        self.detach()
        self._store = store
        self._listener = store.add_listener(self._on_window)
        return self

    def detach(self):
        store, self._store = self._store, None
        if store is not None and self._listener is not None:
            store.remove_listener(self._listener)
        self._listener = None

    # -- the window fold ------------------------------------------------------------

    def _on_window(self, window, t):
        advised, detail = self._advise(window)
        if advised is None:
            return
        self._history.append(advised)
        ordered = sorted(self._history)
        smoothed = ordered[len(ordered) // 2]
        self._gauge.set(smoothed)
        self.last_detail = dict(detail, advised=smoothed, t=t)
        if smoothed != self._published:
            self._published = smoothed
            self._emit(smoothed, detail)

    def _emit(self, advised, detail):
        from petastorm_tpu.obs import flight as _flight

        for recorder in _flight.active_recorders():
            recorder.record("svc_advise", advised=advised, **detail)

    def _advise(self, window):
        point = window.get("ptpu_svc_workers")
        actual = None if point is None else point.get("value")
        if not actual:
            return None, None  # no fleet connected: nothing to advise
        actual = int(actual)
        starved = (window.get("ptpu_svc_starved_seconds_total")
                   or {}).get("rate") or 0.0
        busy = (window.get("ptpu_svc_decode_seconds_total")
                or {}).get("rate") or 0.0
        util = busy / actual
        stragglers = []
        idle_shares = []
        for series, p in window.items():
            base, worker = strip_label(series, "worker")
            if worker is None:
                continue
            if base == WORKER_DECODE_HIST:
                p99 = p.get("p99")
                if self._straggler_s is not None and p.get("count", 0) >= 1 \
                        and p99 is not None and p99 > self._straggler_s:
                    stragglers.append(worker)
            elif base == WORKER_IDLE_TOTAL:
                rate = p.get("rate")
                if rate is not None:
                    idle_shares.append(min(1.0, rate))
        advised = actual + len(stragglers)
        if starved > self._starved_hi and util >= self._util_hi:
            advised += max(1, int(math.ceil(starved)))
        idle_share = (sum(idle_shares) / len(idle_shares)) \
            if idle_shares else 0.0
        if not stragglers and starved <= self._starved_hi \
                and idle_share > self._idle_hi:
            advised = min(advised,
                          max(self._min,
                              actual - int(actual * (idle_share - 0.5))))
        advised = max(self._min, min(self._max, advised))
        return advised, {
            "actual": actual,
            "stragglers": sorted(stragglers),
            "starved_rate": round(starved, 4),
            "idle_share": round(idle_share, 3),
            "util": round(util, 3),
        }
