"""Decode-worker side of the data service (ISSUE 19).

A :class:`DecodeWorker` dials one hub session, announces readiness, and then
runs the strict request/response lease conversation: receive a lease, run the
job's decode callable, reply done (columns payload + timings) or fail
(error + permanence). Link deaths ride the child transport's redial policy —
a :class:`~petastorm_tpu.errors.TransportLinkDown` means the conversation
died but the link is back, so the worker simply waits for the service's
re-dispatch (the service always speaks first); ``EOFError`` means the
service is gone and the worker exits.

Decode callables arrive over the wire in the first lease of each job per
link generation (``JobSpec.wire_spec()``), so a worker process needs no
job-specific code — only the modules the pickled callable imports.

Cross-wire provenance (ISSUE 20): the worker arms a private per-item
collector (:func:`~petastorm_tpu.obs.provenance.child_collector` — the pool
``_child_worker`` pattern) and records one ``svc.decode@<name>`` span per
lease on its own ``perf_counter`` timeline. The blob piggybacks on the DONE
reply together with the (wall, perf) anchor pair sampled at construction, so
the trainer's recorder clock-aligns it exactly like pool-child absorption
and ``slow_top`` names the culprit worker end to end. A ``/timelines``-shaped
telemetry document rides the same replies on a slow cadence
(``telemetry_s``) — the service's ``/fleet`` aggregator merges the latest
one per worker.
"""
from __future__ import annotations

import os
import threading
import time

from petastorm_tpu.errors import (
    PERMANENT_IO_ERRORS,
    PagedecCorruptError,
    TransportLinkDown,
)
from petastorm_tpu.obs.provenance import child_collector
from petastorm_tpu.recovery import RecoveryOptions
from petastorm_tpu.service.protocol import (
    OP_DONE,
    OP_FAIL,
    OP_LEASE,
    OP_READY,
    OP_STOP,
    svc_worker_metrics,
)


def _normalize(result):
    """``decode(item)`` contract: ``{name: ndarray}`` or ``(cols, rows)``;
    without an explicit count the first column's length is the row count."""
    if isinstance(result, tuple):
        cols, rows = result
        return cols, int(rows)
    rows = 0
    for value in result.values():
        rows = int(len(value))
        break
    return result, rows


def _is_permanent(exc):
    return isinstance(exc, PERMANENT_IO_ERRORS) \
        or isinstance(exc, PagedecCorruptError)


class DecodeWorker:
    """One fleet member: dial ``address`` (from
    :meth:`~petastorm_tpu.service.server.DataService.worker_address`) with
    the service's hello ``token`` and decode leases until told to stop."""

    def __init__(self, address, token, recovery=None, name=None,
                 decoders=None, registry=None, provenance=True,
                 telemetry_s=2.0):
        from petastorm_tpu.transport.tcp import TcpChildTransport, \
            parse_address

        self._rec = recovery or RecoveryOptions()
        host, port, session = parse_address(address)
        self._transport = TcpChildTransport(host, port, session, token,
                                            self._rec)
        self.name = name or "decode-%d" % session
        #: preloaded {job: decode} (tests / co-hosted fleets); wire specs
        #: from lease messages land here too
        self._decoders = dict(decoders or {})
        self._thread = None
        #: worker-side counters resolved HERE, before the serve loop starts,
        #: so they home on the caller's registry (see svc_worker_metrics)
        self._registry = registry
        self._wm = svc_worker_metrics(registry)
        self._collector = child_collector() if provenance else None
        # the clock-alignment anchor pair: wall trusted ONCE, here; every
        # span ships perf_counter times relative to this anchor
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()
        self._telemetry_s = None if telemetry_s is None \
            else max(0.1, float(telemetry_s))
        self._telemetry_next = time.monotonic()

    def run(self):
        """Dial and serve until the service stops or the link dies for good.
        Safe to call in a dedicated thread (:meth:`start`)."""
        transport = self._transport
        transport.dial()
        transport.mark_ready()
        try:
            transport.send({"op": OP_READY, "worker": self.name})
        except TransportLinkDown:
            pass  # redialed; the ready that mattered was the hello itself
        except EOFError:
            return
        while True:
            try:
                msg = transport.recv()
            except TransportLinkDown:
                continue  # link is back; await the service's re-dispatch
            except (EOFError, OSError):
                break
            op = msg.get("op")
            if op == OP_STOP:
                break
            if op != OP_LEASE:
                continue
            spec = msg.get("spec")
            if spec:
                self._decoders[spec["job"]] = spec["decode"]
            reply = self._decode_lease(msg)
            try:
                transport.send(reply)
            except TransportLinkDown:
                continue  # reply died with its generation; service requeues
            except (EOFError, OSError):
                break
        transport.close()

    def _decode_lease(self, msg):
        t0 = time.monotonic()
        decode = self._decoders.get(msg.get("job"))
        if decode is None:
            self._wm["failures"].inc()
            return self._with_telemetry(
                {"op": OP_FAIL, "lease": msg["lease"],
                 "error": "no decoder for job %r" % msg.get("job"),
                 "permanent": False})
        rec = None
        if self._collector is not None:
            rec = self._collector.open_item(
                (msg.get("epoch", 0), msg.get("ordinal", 0), msg.get("item")))
        try:
            td0 = time.monotonic()
            p0 = time.perf_counter()
            cols, rows = _normalize(decode(msg["item"]))
            p1 = time.perf_counter()
            decode_s = time.monotonic() - td0
        except Exception as exc:  # noqa: BLE001 — every decode error is a wire verdict
            self._wm["failures"].inc()
            return self._with_telemetry(
                {"op": OP_FAIL, "lease": msg["lease"],
                 "error": "%s: %s" % (type(exc).__name__, exc),
                 "permanent": _is_permanent(exc)})
        self._wm["decodes"].inc()
        self._wm["decode_seconds"].inc(decode_s)
        reply = {"op": OP_DONE, "lease": msg["lease"], "payload": cols,
                 "rows": rows,
                 "meta": {"decode_s": decode_s,
                          "wall_s": time.monotonic() - t0}}
        if rec is not None:
            rec.add_span("svc.decode@%s" % self.name, p0, p1)
            rec.annotate("svc_worker", self.name)
            blob = self._collector.close_item(rec)
            if blob is not None:
                reply["prov"] = (blob, os.getpid(), self._wall_anchor,
                                 self._perf_anchor)
        return self._with_telemetry(reply)

    def _with_telemetry(self, reply):
        """Piggyback a ``/timelines``-shaped export on this reply when the
        telemetry cadence elapsed (strict request/response conversation: the
        replies that already flow are the only frames we get)."""
        if self._telemetry_s is None:
            return reply
        now = time.monotonic()
        if now < self._telemetry_next:
            return reply
        self._telemetry_next = now + self._telemetry_s
        try:
            from petastorm_tpu.obs.metrics import default_registry
            from petastorm_tpu.obs.timeseries import export_document

            reg = self._registry if self._registry is not None \
                else default_registry()
            reg.sample_timelines()
            reply["telemetry"] = export_document(
                reg, extra={"source": "worker:%s" % self.name})
        except Exception:  # noqa: BLE001 — telemetry must never fail a lease
            from petastorm_tpu.obs.log import degradation

            degradation("svc_worker_telemetry_error",
                        "decode worker %r could not export telemetry; the "
                        "reply ships without it", self.name)
        return reply

    def start(self):
        """Run :meth:`run` on a daemon thread; returns the thread."""
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="ptpu-%s" % self.name)
        self._thread.start()
        return self._thread

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)

    def close(self):
        self._transport.close()


# -- parquet helpers ---------------------------------------------------------------------


class ParquetRowGroupDecoder:
    """Picklable decode callable for ``(path, row_group)`` plan items: one
    classic columnar read of that row group into numpy columns."""

    def __init__(self, columns=None):
        self.columns = list(columns) if columns else None

    def __call__(self, item):
        import pyarrow.parquet as pq

        path, row_group = item
        table = pq.ParquetFile(path).read_row_group(row_group,
                                                    columns=self.columns)
        cols = {name: table.column(name).to_numpy(zero_copy_only=False)
                for name in table.column_names}
        return cols, table.num_rows


def parquet_job(job, paths, tenant=None, priority=None, num_epochs=1,
                shuffle=False, seed=0, columns=None):
    """Build a :class:`~petastorm_tpu.service.protocol.JobSpec` over a
    parquet store: one plan item per ``(file, row_group)``, schema inferred
    from the first file (the trainer-facing
    :class:`~petastorm_tpu.unischema.Unischema`)."""
    import os

    import pyarrow.parquet as pq

    from petastorm_tpu.service.protocol import JobSpec
    from petastorm_tpu.unischema import Unischema

    if isinstance(paths, str):
        root = paths[len("file://"):] if paths.startswith("file://") else paths
        if os.path.isdir(root):
            paths = sorted(
                os.path.join(root, f) for f in os.listdir(root)
                if f.endswith(".parquet") and not f.startswith("_"))
        else:
            paths = [root]
    if not paths:
        raise ValueError("parquet_job %r: no parquet files found" % job)
    items = []
    schema = None
    for path in paths:
        pf = pq.ParquetFile(path)
        if schema is None:
            schema = Unischema.from_arrow_schema(pf.schema_arrow)
        items.extend((path, rg) for rg in range(pf.num_row_groups))
    return JobSpec(job, items, ParquetRowGroupDecoder(columns), schema,
                   tenant=tenant, priority=priority, num_epochs=num_epochs,
                   shuffle=shuffle, seed=seed)
