"""Disaggregated data service (ISSUE 19): one decode fleet, many trainers.

- :class:`DataService` — the server: owns each job's plan, leases items to
  decode workers over the PR 15 tcp transport, fans every decoded payload
  out to all attached trainers (decode-once / serve-many), and runs
  per-tenant QoS between jobs sharing the fleet.
- :class:`DecodeWorker` — one fleet member: dials a hub session and decodes
  leases until stopped.
- :class:`ServiceReader` — the trainer-side batched reader: plugs into
  :class:`~petastorm_tpu.loader.DataLoader` unchanged and checkpoints the
  consumed-ordinal watermark the service resumes from.
- :class:`JobSpec` / :func:`parquet_job` — job definitions.

See ``docs/service.md`` for the wire protocol and the attach/detach
contract.
"""
from petastorm_tpu.service.client import ServiceAttachRejected, ServiceReader
from petastorm_tpu.service.protocol import PROTOCOL_VERSION, JobSpec, \
    svc_metrics
from petastorm_tpu.service.server import DataService, ServiceOptions
from petastorm_tpu.service.worker import DecodeWorker, \
    ParquetRowGroupDecoder, parquet_job

__all__ = [
    "DataService",
    "DecodeWorker",
    "JobSpec",
    "PROTOCOL_VERSION",
    "ParquetRowGroupDecoder",
    "ServiceAttachRejected",
    "ServiceOptions",
    "ServiceReader",
    "parquet_job",
    "svc_metrics",
]
