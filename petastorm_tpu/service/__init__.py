"""Disaggregated data service (ISSUE 19): one decode fleet, many trainers.

- :class:`DataService` — the server: owns each job's plan, leases items to
  decode workers over the PR 15 tcp transport, fans every decoded payload
  out to all attached trainers (decode-once / serve-many), and runs
  per-tenant QoS between jobs sharing the fleet.
- :class:`DecodeWorker` — one fleet member: dials a hub session and decodes
  leases until stopped.
- :class:`ServiceReader` — the trainer-side batched reader: plugs into
  :class:`~petastorm_tpu.loader.DataLoader` unchanged and checkpoints the
  consumed-ordinal watermark the service resumes from.
- :class:`JobSpec` / :func:`parquet_job` — job definitions.
- :class:`FleetTelemetry` / :class:`FleetAdvisor` — the ISSUE 20 fleet
  observability plane: the ``GET /fleet`` aggregator and the read-only
  autoscaling sensor publishing ``ptpu_svc_advised_workers``.

See ``docs/service.md`` for the wire protocol and the attach/detach
contract, and ``docs/observability.md`` for the fleet plane.
"""
from petastorm_tpu.service.client import ServiceAttachRejected, ServiceReader
from petastorm_tpu.service.protocol import PROTOCOL_VERSION, JobSpec, \
    svc_metrics, svc_worker_metrics
from petastorm_tpu.service.server import DataService, ServiceOptions
from petastorm_tpu.service.telemetry import FleetAdvisor, FleetTelemetry
from petastorm_tpu.service.worker import DecodeWorker, \
    ParquetRowGroupDecoder, parquet_job

__all__ = [
    "DataService",
    "DecodeWorker",
    "FleetAdvisor",
    "FleetTelemetry",
    "JobSpec",
    "PROTOCOL_VERSION",
    "ParquetRowGroupDecoder",
    "ServiceAttachRejected",
    "ServiceOptions",
    "ServiceReader",
    "parquet_job",
    "svc_metrics",
    "svc_worker_metrics",
]
