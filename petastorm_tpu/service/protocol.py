"""Data-service wire protocol (ISSUE 19): ops, job specs, metrics.

The disaggregated data service speaks pickled-object frames over the PR 15
framed tcp transport (:mod:`petastorm_tpu.transport.tcp`) — every message is
a plain dict with an ``"op"`` key, so old and new peers can skip fields they
do not understand. Two conversations share the hub:

Decode worker <-> service (strict request/response per lease)::

    worker:  {"op": "ready", "worker": name}          once per fresh link
    service: {"op": "lease", "lease": id, "job": j, "epoch": e,
              "ordinal": o, "item": spec_item, ["spec": JobSpec]}
    worker:  {"op": "done", "lease": id, "payload": cols, "rows": n,
              "meta": {"decode_s": ..., "wall_s": ...},
              ["prov": (blob, pid, wall_anchor, perf_anchor)],
              ["telemetry": export_doc]}
          |  {"op": "fail", "lease": id, "error": str, "permanent": bool,
              ["telemetry": export_doc]}
    service: {"op": "stop"}                            shutdown

Cross-wire provenance (ISSUE 20) piggybacks on the frames that already flow
— no new conversation ops, and old peers skip the fields they do not know.
``prov`` is the pool-child blob shape
(:class:`~petastorm_tpu.obs.provenance._ChildCollector`):
``((epoch, ordinal, key, spans, annotations), pid, wall_anchor,
perf_anchor)`` with spans on the sender's ``perf_counter`` timeline and one
(wall, perf) anchor pair sampled at worker start for clock alignment.
``telemetry`` is a ``/timelines``-shaped export document
(:func:`~petastorm_tpu.obs.timeseries.export_document`) shipped on a slow
cadence; the service's ``/fleet`` aggregator merges the latest one per peer
on anchored clocks.

A lease conversation is pinned to its link generation by the transport's
in-flight ledger: a link death mid-conversation re-dispatches the un-acked
lease (never twice — frames from a dead generation are discarded with it).

Trainer <-> service (credit-flow push)::

    trainer: {"op": "attach", "job": j, "trainer": t, "tenant": slug,
              "consumed": {epoch: [ordinals]}, "arena": bool}
    service: {"op": "attached", "schema": Unischema, "num_epochs": n,
              "epoch_sizes": {epoch: count}, "arena": bool, "version": 1}
          |  {"op": "rejected", "reason": str}
    trainer: {"op": "want", "credits": n, ["telemetry": export_doc]}
    service: {"op": "item", "epoch": e, "ordinal": o, "rows": n,
              "payload": cols | None, ["arena_key": key],
              ["prov": [(blob, pid, wall, perf), ...]]}
          |  {"op": "quarantined", "epoch": e, "ordinal": o, "cause": str,
              ["attempts": n]}
          |  {"op": "end"}
    trainer: {"op": "refetch", "epoch": e, "ordinal": o}  arena-key miss
    trainer: {"op": "detach", "consumed": {...}}
    service: {"op": "detached"}

``consumed`` is the trainer's checkpoint watermark — the exact same
``{epoch: set(ordinal)}`` map the :class:`~petastorm_tpu.reader.Reader`
keeps. The service never tracks delivery acks: a (re)attach recomputes the
remaining shard from the client-presented map, so detach returns unconsumed
work with no loss, and reattach resumes watermark-exact with no replay.
"""
from __future__ import annotations

PROTOCOL_VERSION = 1

# worker <-> service
OP_READY = "ready"
OP_LEASE = "lease"
OP_DONE = "done"
OP_FAIL = "fail"
OP_STOP = "stop"

# trainer <-> service
OP_ATTACH = "attach"
OP_ATTACHED = "attached"
OP_REJECTED = "rejected"
OP_WANT = "want"
OP_ITEM = "item"
OP_QUARANTINED = "quarantined"
OP_END = "end"
OP_DETACH = "detach"
OP_DETACHED = "detached"
OP_REFETCH = "refetch"

#: scheduler tiers for the TenantContext priority hints (lower = first)
PRIORITY_TIERS = {"high": 0, "normal": 1, None: 1, "low": 2}


class JobSpec:
    """One job the fleet decodes: a plan over picklable items plus the decode
    callable that turns one item into a columns dict.

    ``decode(item)`` must be picklable (module-level function or
    ``functools.partial`` over one) and return either ``{name: ndarray}`` or
    ``({name: ndarray}, rows)``; without an explicit row count the first
    column's length is used. ``schema`` is the
    :class:`~petastorm_tpu.unischema.Unischema` trainers receive at attach —
    the :class:`~petastorm_tpu.service.client.ServiceReader` exposes it to
    the :class:`~petastorm_tpu.loader.DataLoader` unchanged.
    """

    __slots__ = ("job", "items", "decode", "schema", "tenant", "priority",
                 "num_epochs", "shuffle", "seed")

    def __init__(self, job, items, decode, schema, tenant=None, priority=None,
                 num_epochs=1, shuffle=False, seed=0):
        if not items:
            raise ValueError("JobSpec %r needs at least one plan item" % job)
        if priority not in PRIORITY_TIERS:
            raise ValueError("priority must be one of %r, got %r"
                             % (sorted(k for k in PRIORITY_TIERS if k),
                                priority))
        self.job = str(job)
        self.items = list(items)
        self.decode = decode
        self.schema = schema
        self.tenant = tenant
        self.priority = priority
        self.num_epochs = num_epochs
        self.shuffle = bool(shuffle)
        self.seed = seed

    def wire_spec(self):
        """The worker-facing subset: just enough to run ``decode`` (the plan
        and trainer bookkeeping never leave the service)."""
        return {"job": self.job, "decode": self.decode,
                "tenant": self.tenant}


# -- metrics ---------------------------------------------------------------------------

_default_metrics = None


def svc_metrics(registry=None):
    """The ``ptpu_svc_*`` family (memoized for the default registry — the
    lease and delivery hot paths resolve handles once per process)."""
    global _default_metrics
    from petastorm_tpu.obs.metrics import default_registry

    if registry is None or registry is default_registry():
        if _default_metrics is None:
            _default_metrics = _build_metrics(default_registry())
        return _default_metrics
    return _build_metrics(registry)


def _build_metrics(reg):
    return {
        "workers": reg.gauge(
            "ptpu_svc_workers",
            help="decode workers currently connected to the data service"),
        "trainers": reg.gauge(
            "ptpu_svc_trainers",
            help="trainers currently attached to the data service"),
        "jobs": reg.gauge(
            "ptpu_svc_jobs", help="jobs registered with the data service"),
        "leases": reg.counter(
            "ptpu_svc_leases_total",
            help="decode leases dispatched to the worker fleet"),
        "lease_redispatch": reg.counter(
            "ptpu_svc_lease_redispatch_total",
            help="leases returned to the pool by a dead link / transient "
                 "failure and dispatched again"),
        "lease_leaked": reg.counter(
            "ptpu_svc_lease_leaked_total",
            help="leases still outstanding when the service stopped — "
                 "should be 0; growth is a dispatcher bug"),
        "leases_outstanding": reg.gauge(
            "ptpu_svc_leases_outstanding",
            help="decode leases currently held by workers"),
        "decodes": reg.counter(
            "ptpu_svc_decodes_total",
            help="plan items decoded by the fleet (decode-once: compare "
                 "with served items for the fan-out ratio)"),
        "redecodes": reg.counter(
            "ptpu_svc_redecodes_total",
            help="items decoded again after their payload was dropped "
                 "(reattach after eviction — correctness, not the hot path)"),
        "decode_seconds": reg.counter(
            "ptpu_svc_decode_seconds_total",
            help="fleet decode seconds (the worker-seconds numerator of the "
                 "decode-once acceptance ratio)"),
        "served_items": reg.counter(
            "ptpu_svc_served_items_total",
            help="decoded items pushed to trainers (each decode serves "
                 "every attached trainer that still needs it)"),
        "served_rows": reg.counter(
            "ptpu_svc_served_rows_total",
            help="rows pushed to trainers"),
        "fanout_serves": reg.counter(
            "ptpu_svc_fanout_serves_total",
            help="serves beyond the first per decoded item — the rows a "
                 "dedicated pipeline would have decoded again"),
        "quarantined": reg.counter(
            "ptpu_svc_quarantined_total",
            help="plan items quarantined service-wide (broadcast to every "
                 "trainer's watermark exactly once)"),
        "attaches": reg.counter(
            "ptpu_svc_attaches_total", help="trainer attach handshakes"),
        "detaches": reg.counter(
            "ptpu_svc_detaches_total",
            help="trainer detaches (clean requests + link deaths)"),
        "rejected": reg.counter(
            "ptpu_svc_rejected_total",
            help="attach requests refused by admission control"),
        "refetches": reg.counter(
            "ptpu_svc_refetches_total",
            help="arena-key misses a trainer asked the service to re-serve"),
        "cache_items": reg.gauge(
            "ptpu_svc_cache_items",
            help="decoded payloads resident in the serve cache"),
        "cache_bytes": reg.gauge(
            "ptpu_svc_cache_bytes",
            help="decoded payload bytes resident in the serve cache"),
        "starved_seconds": reg.counter(
            "ptpu_svc_starved_seconds_total",
            help="seconds trainers sat with credits granted and an empty "
                 "push queue while their plan still had work — the fleet "
                 "undersupplied them (the autoscaling pressure signal)"),
        "advised_workers": reg.gauge(
            "ptpu_svc_advised_workers",
            help="decode fleet size the FleetAdvisor currently recommends "
                 "(read-only sensor: compare with ptpu_svc_workers)"),
    }


def svc_worker_metrics(registry=None):
    """The ``ptpu_svc_worker_*`` families a :class:`DecodeWorker` owns in its
    OWN process. Never memoized: the worker resolves these once in
    ``__init__`` — before the serve loop starts — so the counters home on
    the registry the caller intended (the PR 19 loader-histogram lesson: a
    first-touch inside the hot loop races default-registry memoization when
    a co-hosted test hands each worker a private registry)."""
    from petastorm_tpu.obs.metrics import default_registry

    reg = registry if registry is not None else default_registry()
    return {
        "decodes": reg.counter(
            "ptpu_svc_worker_decodes_total",
            help="leases this worker process decoded successfully"),
        "decode_seconds": reg.counter(
            "ptpu_svc_worker_decode_seconds_total",
            help="seconds this worker process spent inside decode callables"),
        "failures": reg.counter(
            "ptpu_svc_worker_failures_total",
            help="leases this worker process failed (transient + permanent)"),
    }
