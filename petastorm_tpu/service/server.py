"""The DataService: one decode fleet feeding many trainers (ISSUE 19).

The service owns each job's :class:`~petastorm_tpu.plan.EpochPlan` and leases
plan items to remote decode workers over the PR 15 framed tcp transport,
reusing the :class:`~petastorm_tpu.workers.PullDispatcher` claim/return
discipline across the wire: a dead link's un-acked lease re-dispatches (the
transport's in-flight ledger pins the conversation to its link generation),
a withdraw returns claims with no loss and no duplicates, and quarantine
stays exactly-once service-wide.

Decode-once / serve-many: each decoded payload fans out to every attached
trainer of its job that still needs it, so N trainers sharing one job cost
the fleet ~1 decode per plan item instead of N. Payloads are not hoarded in
the service process — once pushed to every current needer the reference is
dropped; the host-wide cache arena (PR 17) is the serve cache, so co-hosted
trainers map the decoded warm set instead of receiving a copy, and a trainer
that attaches after eviction triggers a re-decode (correctness path, counted
as ``ptpu_svc_redecodes_total``).

Attach/detach elasticity: the service never tracks per-item delivery acks.
The trainer's consumed-ordinal watermark — the same ``{epoch: set(ordinal)}``
map :class:`~petastorm_tpu.reader.Reader` checkpoints — is presented at every
(re)attach, and the remaining shard is recomputed from it: detach (clean or
link death) returns unconsumed work to the pool with no loss, reattach
resumes watermark-exact with no replay.

Per-tenant QoS: decode dispatch runs stride scheduling over jobs inside
strict priority tiers (``TenantContext`` priorities high/normal/low), with a
live per-tenant weight knob (``svc_weight:<tenant>``) the PR 13 controller
actuates through :func:`petastorm_tpu.control.controller.tenant_qos_rules`.
Admission control caps attached trainers globally and per tenant.

Fleet observability (ISSUE 20): the service is the natural aggregation
point for everything crossing it. Per worker it keeps labeled decode
latency / idle / lease families (``ptpu_svc_worker_*{worker=...}``), absorbs
the ``/timelines``-shaped telemetry documents workers and trainers piggyback
on frames they already send, threads each item's cross-wire provenance
(worker ``svc.decode@`` blob + a service-side ``svc.wire`` span) through to
the trainers that receive it, counts trainer starvation seconds (credits
granted, queue empty, plan unfinished — the undersupply signal), and serves
the merged fleet view at ``GET /fleet`` (:meth:`DataService.fleet_document`).
With ``ServiceOptions.straggler_decode_p99_s`` set, a ``per_worker`` SLO
debounces a straggler alert naming the worker, and the read-only
:class:`~petastorm_tpu.service.telemetry.FleetAdvisor` publishes
``ptpu_svc_advised_workers`` on the TimelineStore sampling cadence
(``ServiceOptions.sample_s`` runs that cadence in-process).
"""
from __future__ import annotations

import os
import threading
import time

from petastorm_tpu.errors import TransportLinkDown
from petastorm_tpu.plan import EpochPlan
from petastorm_tpu.recovery import RecoveryOptions
from petastorm_tpu.service.protocol import (
    OP_ATTACH,
    OP_ATTACHED,
    OP_DETACH,
    OP_DETACHED,
    OP_DONE,
    OP_END,
    OP_FAIL,
    OP_ITEM,
    OP_LEASE,
    OP_QUARANTINED,
    OP_READY,
    OP_REFETCH,
    OP_REJECTED,
    OP_STOP,
    OP_WANT,
    PRIORITY_TIERS,
    PROTOCOL_VERSION,
    svc_metrics,
)
from petastorm_tpu.workers import PullDispatcher

#: service poll tick — trainer serve loops alternate between flushing their
#: push queue and polling the socket at this cadence
TICK_S = 0.05


def _degradation(*args, **kwargs):
    from petastorm_tpu.obs.log import degradation

    degradation(*args, **kwargs)


def _charge(resource, amount, label):
    if label is None:
        return
    from petastorm_tpu.obs import tenant as tenant_mod

    tenant_mod.charge(resource, amount, label=label)


class ServiceOptions:
    """Service-side policy knobs."""

    __slots__ = ("host", "max_trainers", "max_trainers_per_tenant", "arena",
                 "link_redispatch_limit", "straggler_decode_p99_s",
                 "sample_s", "min_workers", "max_workers")

    def __init__(self, host="127.0.0.1", max_trainers=64,
                 max_trainers_per_tenant=None, arena=True,
                 link_redispatch_limit=None, straggler_decode_p99_s=None,
                 sample_s=None, min_workers=1, max_workers=64):
        self.host = host
        self.max_trainers = int(max_trainers)
        self.max_trainers_per_tenant = max_trainers_per_tenant
        #: admit decoded payloads into the host-wide cache arena (PR 17) so
        #: co-hosted trainers map the warm set instead of copying it
        self.arena = bool(arena)
        #: per-item ceiling on link-death re-dispatches before the item is
        #: quarantined as poison (a payload that reliably kills its link);
        #: None derives a generous multiple of the poison budget — plain
        #: link flaps must re-dispatch, never quarantine
        self.link_redispatch_limit = link_redispatch_limit
        #: per-worker window decode p99 above this arms the straggler SLO
        #: (debounced per worker) AND the advisor's replace-a-straggler term;
        #: None disables the straggler alert
        self.straggler_decode_p99_s = straggler_decode_p99_s
        #: run an in-process timeline sampling cadence at this period so the
        #: SLO engine + FleetAdvisor see windows without an external Reporter;
        #: None = whoever owns the registry samples (loader Reporter, tests)
        self.sample_s = sample_s
        #: FleetAdvisor clamp — the advice never leaves [min, max]
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)


class _Trainer:
    __slots__ = ("tid", "session", "tenant", "priority", "arena", "queue",
                 "credits", "remaining", "end_sent", "transport")

    def __init__(self, tid, session, tenant, priority, arena):
        self.tid = tid
        self.session = session
        self.tenant = tenant
        self.priority = priority
        self.arena = bool(arena)
        self.queue = []          # entries ready to push (credit-gated)
        self.credits = 0
        self.remaining = {}      # epoch -> set(ordinal) not yet queued
        self.end_sent = False
        #: the serve loop's transport while attached — queue producers
        #: ``wake()`` it so a fresh entry flushes without riding out the
        #: poll tick (delivery latency would quantize to it otherwise)
        self.transport = None

    def finished(self):
        return not self.queue and all(not s for s in self.remaining.values())


def _nudge(trainer):
    """Wake ``trainer``'s serve loop out of its wakeable poll so the entry
    just queued flushes immediately. Safe anywhere: a no-op before the serve
    loop attaches (attach replay entries flush on its first pass) and never
    blocks or raises."""
    transport = trainer.transport
    if transport is not None:
        transport.wake()


class _Job:
    __slots__ = ("spec", "plan", "dispatcher", "epoch_sizes", "trainers",
                 "need", "done_with", "quarantined", "fail_attempts",
                 "link_attempts", "arena_admitted", "inline_keys", "rows_of",
                 "decoded", "pass_value", "prov_of")

    def __init__(self, spec):
        self.spec = spec
        self.plan = EpochPlan(list(range(len(spec.items))),
                              num_epochs=spec.num_epochs,
                              shuffle=spec.shuffle, seed=spec.seed,
                              with_epoch=True)
        self.dispatcher = PullDispatcher(self.plan, workers_count=1,
                                         lookahead=0)
        self.epoch_sizes = {e: self.plan.items_in_epoch(e)
                            for e in range(spec.num_epochs)}
        self.trainers = {}       # tid -> _Trainer
        self.need = {}           # (epoch, ordinal) -> set(tid)
        #: items that exited the dispatch pipeline (decoded or quarantined);
        #: a late attach needing one re-enters it via return_items()
        self.done_with = set()
        self.quarantined = {}    # (epoch, ordinal) -> cause
        self.fail_attempts = {}  # (epoch, ordinal) -> decode failures
        self.link_attempts = {}  # (epoch, ordinal) -> link-death redispatches
        self.arena_admitted = set()
        #: keys that missed the arena once — re-served inline so a refetch
        #: can never loop on admit/evict races
        self.inline_keys = set()
        self.rows_of = {}        # (epoch, ordinal) -> delivered row count
        self.decoded = set()     # keys ever completed (second pass = redecode)
        self.pass_value = 0.0    # stride-scheduling virtual time
        #: (epoch, ordinal) -> [(blob, pid, wall, perf), ...] cross-wire
        #: provenance entries riding every push of that item
        self.prov_of = {}

    def tier(self):
        return PRIORITY_TIERS.get(self.spec.priority, 1)


class _Lease:
    __slots__ = ("lease_id", "job", "epoch", "ordinal", "slot", "t0",
                 "worker")

    def __init__(self, lease_id, job, epoch, ordinal, slot, worker=None):
        self.lease_id = lease_id
        self.job = job
        self.epoch = epoch
        self.ordinal = ordinal
        self.slot = slot
        self.t0 = time.monotonic()
        self.worker = worker


class DataService:
    """The disaggregated data-service server. See the module docstring for
    semantics; :mod:`petastorm_tpu.service.protocol` for the wire contract.

    Lifecycle::

        svc = DataService(recovery=RecoveryOptions(...))
        svc.add_job(JobSpec(...))
        addr = svc.worker_address()    # hand to a DecodeWorker + svc.token
        addr2 = svc.trainer_address()  # hand to a ServiceReader
        ...
        svc.stop()
    """

    def __init__(self, options=None, recovery=None, registry=None):
        from petastorm_tpu.obs.metrics import default_registry
        from petastorm_tpu.service.telemetry import FleetAdvisor, \
            FleetTelemetry
        from petastorm_tpu.transport.tcp import TcpHub

        self._opt = options or ServiceOptions()
        self._rec = recovery or RecoveryOptions()
        self._registry = registry if registry is not None \
            else default_registry()
        self._m = svc_metrics(self._registry)
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._jobs = {}
        self._leases = {}
        self._threads = []
        self._transports = {}
        self._next_session = 1
        self._next_lease_id = 1
        self._next_slot = 0
        self._tenant_weight = {}
        # the service's clock-anchor pair: its svc.wire spans ship perf
        # times relative to this, exactly like a pool child's piggyback
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()
        self._workers = {}        # worker name -> health/handle dict
        self._worker_tenants = {} # worker name -> set(tenant)
        self._telemetry = FleetTelemetry(self, self._registry)
        store = self._registry.timeline_store()
        self._advisor = FleetAdvisor(
            self._registry,
            straggler_p99_s=self._opt.straggler_decode_p99_s,
            min_workers=self._opt.min_workers,
            max_workers=self._opt.max_workers).attach(store)
        self._slo = None
        if self._opt.straggler_decode_p99_s is not None:
            from petastorm_tpu.obs.slo import SloEngine, SloSpec

            self._slo = SloEngine(specs=[SloSpec(
                name="svc-straggler",
                metric="ptpu_svc_worker_decode_seconds",
                stat="p99", op="<=",
                threshold=float(self._opt.straggler_decode_p99_s),
                per_worker=True, breach_windows=2, min_count=1,
                description="a decode worker's window p99 ran past the "
                            "straggler threshold — the fleet is dragging "
                            "an outlier")],
                registry=self._registry).attach(store)
        self._arena = None
        if self._opt.arena:
            from petastorm_tpu.io import arena as arena_mod

            self._arena = arena_mod.process_arena()
        self._hub = TcpHub(self._rec, host=self._opt.host)
        if self._opt.sample_s:
            t = threading.Thread(target=self._sample_loop,
                                 args=(float(self._opt.sample_s),),
                                 daemon=True, name="ptpu-svc-sampler")
            self._threads.append(t)
            t.start()

    # -- public surface -----------------------------------------------------------------

    @property
    def token(self):
        """The hub's shared-secret hello token (hex string)."""
        return self._hub.token

    def add_job(self, spec):
        with self._cond:
            if spec.job in self._jobs:
                raise ValueError("job %r already registered" % spec.job)
            self._jobs[spec.job] = _Job(spec)
            self._m["jobs"].set(len(self._jobs))
            self._cond.notify_all()

    def worker_address(self):
        """Register a fresh worker session and return its dial address (the
        hub idiom: sessions exist before the peer dials them)."""
        return self._spawn_session(self._worker_loop, "ptpu-svc-worker")

    def trainer_address(self):
        """Register a fresh trainer session and return its dial address."""
        return self._spawn_session(self._trainer_loop, "ptpu-svc-trainer")

    def get_tenant_weight(self, tenant):
        with self._cond:
            return self._tenant_weight.get(tenant, 1.0)

    def set_tenant_weight(self, tenant, weight):
        """Live QoS actuation seam (the ``svc_weight:<tenant>`` knob): a
        tenant's stride-scheduling share of the decode fleet."""
        weight = max(0.0, float(weight))
        with self._cond:
            self._tenant_weight[tenant] = weight
            self._cond.notify_all()
        return weight

    def register_knobs(self, knobs, tenants):
        """Add one ``svc_weight:<tenant>`` knob per tenant to ``knobs`` (the
        PR 13 KnobSet) — the actuation seam
        :func:`~petastorm_tpu.control.controller.tenant_qos_rules` moves."""
        import functools

        for tenant in tenants:
            knobs.numeric(
                "svc_weight:%s" % tenant,
                get=functools.partial(self.get_tenant_weight, tenant),
                apply_fn=functools.partial(self.set_tenant_weight, tenant),
                lo=0.05, hi=8.0, default=1.0, integer=False, unit="x")

    def usage_report(self, registry=None):
        """The per-tenant usage report over the service's charges (delegates
        to the PR 18 accounting plane)."""
        from petastorm_tpu.obs.tenant import TenantUsageReport

        return TenantUsageReport.from_registry(registry)

    def outstanding_leases(self):
        with self._cond:
            return len(self._leases)

    # -- fleet observability surface (ISSUE 20) -----------------------------------------

    @property
    def slo_engine(self):
        """The straggler SLO engine (None unless
        ``ServiceOptions.straggler_decode_p99_s`` is set)."""
        return self._slo

    @property
    def advisor(self):
        """The read-only :class:`~petastorm_tpu.service.telemetry
        .FleetAdvisor` publishing ``ptpu_svc_advised_workers``."""
        return self._advisor

    def fleet_document(self):
        """The ``GET /fleet`` JSON document: per-worker health, advice,
        straggler alerts, and every peer's telemetry merged with the
        service's own export on anchored clocks."""
        return self._telemetry.document()

    def metrics_server(self, host="127.0.0.1", port=0):
        """A started :class:`~petastorm_tpu.obs.serve.MetricsServer` over the
        service's registry with ``/fleet`` mounted and the straggler SLO
        engine wired into ``/alerts``. Caller stops it."""
        from petastorm_tpu.obs.serve import MetricsServer

        return MetricsServer(self._registry, host=host, port=port,
                             slo_engine=self._slo,
                             routes={"/fleet": self.fleet_document}).start()

    def worker_health(self):
        """Per-worker health gauges: connection state, outstanding leases +
        oldest lease age, cumulative decode p50/p99, idle/lease totals, and
        the tenants the worker has decoded for."""
        now = time.monotonic()
        with self._cond:
            leases = {}
            for lease in self._leases.values():
                if lease.worker is not None:
                    leases.setdefault(lease.worker, []).append(lease.t0)
            out = {}
            for name, info in self._workers.items():
                mine = leases.get(name, ())
                out[name] = {
                    "connected": info["connected"],
                    "leases_outstanding": len(mine),
                    "oldest_lease_age_s":
                        round(now - min(mine), 3) if mine else 0.0,
                    "decode_p50_s": info["hist"].percentile(0.5),
                    "decode_p99_s": info["hist"].percentile(0.99),
                    "leases_total": info["leases"].value,
                    "idle_seconds_total": round(info["idle"].value, 3),
                    "tenants": sorted(
                        t for t in self._worker_tenants.get(name, ())
                        if t is not None),
                }
            return out

    def advice(self):
        """The advisor's latest decision detail (None before the first
        sampled window with a connected fleet)."""
        return self._advisor.last_detail

    def straggler_alerts(self):
        """Debounced straggler alerts, enriched with the provenance site the
        trainer-side fold charges (``svc.decode@<worker>``) and the tenants
        the worker served — [] with no SLO engine armed."""
        if self._slo is None:
            return []
        out = []
        for alert in self._slo.alerts():
            worker = getattr(alert, "worker", None)
            if worker is None:
                continue
            with self._cond:
                tenants = sorted(
                    t for t in self._worker_tenants.get(worker, ())
                    if t is not None)
            out.append({"slo": alert.name, "worker": worker,
                        "site": "svc.decode@%s" % worker,
                        "tenants": tenants, "value": alert.value,
                        "threshold": alert.threshold, "t": alert.t})
        return out

    def _sample_loop(self, period):
        while not self._stop.wait(period):
            try:
                self._registry.sample_timelines()
            except Exception:  # noqa: BLE001 — sampling must never kill the service
                _degradation("svc_sample_error",
                             "data service timeline sampling failed; the "
                             "SLO/advisor cadence skipped a window")

    def stop(self):
        """Drain and shut down: wakes every loop, closes the hub, joins the
        loops, and counts any lease STILL outstanding after they exit as
        leaked (should be zero — every loop requeues its un-acked leases on
        the way out, so a survivor means a dispatcher bug). Counting before
        the joins would flag leases merely in flight at stop time — normal
        when tearing down mid-decode — as leaks."""
        with self._cond:
            self._stop.set()
            self._cond.notify_all()
            transports = list(self._transports.values())
        self._advisor.detach()
        if self._slo is not None:
            self._slo.detach()
        for transport in transports:
            transport.close()  # wakes loops blocked in recv/poll
        self._hub.close()
        for t in self._threads:
            t.join(timeout=10.0)
        with self._cond:
            leaked = len(self._leases)
            if leaked:
                self._m["lease_leaked"].inc(leaked)
                _degradation(
                    "svc_lease_leaked",
                    "data service stopped with %d outstanding decode "
                    "lease(s) — dispatcher bug, items were neither "
                    "delivered nor requeued", leaked, once=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- session plumbing ---------------------------------------------------------------

    def _spawn_session(self, loop, name):
        with self._cond:
            session = self._next_session
            self._next_session += 1
        transport = self._hub.create_session(session)
        t = threading.Thread(target=loop, args=(session, transport),
                             daemon=True, name="%s-%d" % (name, session))
        with self._cond:
            self._threads.append(t)
            self._transports[session] = transport
        t.start()
        return self._hub.address_for(session)

    def _wait_connected(self, transport):
        while not self._stop.is_set():
            if transport.wait_connected(0.2):
                transport.mark_ready()
                return True
        return False

    # -- decode dispatch ----------------------------------------------------------------

    def _alloc_slot(self):
        with self._cond:
            slot = self._next_slot
            self._next_slot += 1
            return slot

    def _try_claim(self, slot, worker=None):
        """One dispatch decision under the lock: strict priority tiers, then
        stride scheduling (min virtual time / tenant weight) across jobs with
        attached trainers and pending work."""
        candidates = [j for j in self._jobs.values()
                      if j.trainers and j.dispatcher.has_work()]
        candidates.sort(key=lambda j: (j.tier(), j.pass_value))
        for job in candidates:
            job.dispatcher.ensure_workers(slot + 1)
            claim = job.dispatcher.next(slot)
            if claim is None:
                continue
            (epoch, ordinal, _idx), _upcoming = claim
            weight = max(self._tenant_weight.get(job.spec.tenant, 1.0), 1e-3)
            job.pass_value += 1.0 / weight
            lease = _Lease(self._next_lease_id, job, epoch, ordinal, slot,
                           worker)
            self._next_lease_id += 1
            self._leases[lease.lease_id] = lease
            self._m["leases"].inc()
            self._m["leases_outstanding"].set(len(self._leases))
            return lease
        return None

    def _next_lease(self, slot, timeout=0.2, worker=None):
        with self._cond:
            lease = self._try_claim(slot, worker)
            if lease is None and not self._stop.is_set():
                self._cond.wait(timeout)
                lease = self._try_claim(slot, worker)
            return lease

    def _requeue_lease(self, lease_id, link=False):
        """A lease whose conversation died: return the item to its job's
        dispatcher pool (claim/return discipline across the wire). Link
        deaths re-dispatch essentially forever — only a pathological per-item
        ceiling quarantines them as poison."""
        with self._cond:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            self._m["leases_outstanding"].set(len(self._leases))
            job, key = lease.job, (lease.epoch, lease.ordinal)
            if link:
                limit = self._opt.link_redispatch_limit
                if limit is None:
                    limit = max(10, 3 * self._rec.poison_attempts)
                job.link_attempts[key] = job.link_attempts.get(key, 0) + 1
                if job.link_attempts[key] >= limit:
                    self._quarantine_locked(job, lease.epoch, lease.ordinal,
                                            "poison")
                    return
            job.dispatcher.return_items(
                [(lease.epoch, lease.ordinal, lease.ordinal)])
            self._m["lease_redispatch"].inc()
            self._cond.notify_all()

    def _withdraw_slot(self, slot):
        with self._cond:
            for job in self._jobs.values():
                job.dispatcher.ensure_workers(slot + 1)
                job.dispatcher.withdraw(slot)
            self._cond.notify_all()

    def _complete(self, lease_id, payload, rows, meta, prov=None, wire=None):
        """A decode finished: charge its tenant, fan the payload out to every
        attached trainer that still needs it, admit it to the arena, and drop
        the service-side reference.

        ``prov`` is the worker's piggybacked ``(blob, pid, wall, perf)``
        entry; ``wire`` the service-side ``(perf0, perf1)`` send→reply stamp.
        Both land in ``job.prov_of`` and ride every push of this item. The
        service entry ships ``-os.getpid()`` as its pid: co-hosted fleets
        (worker threads in the trainer's process) would otherwise collide
        with the worker blob's pid and trip the recorder's same-pid retry
        replacement, dropping the decode spans it just absorbed."""
        with self._cond:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            self._m["leases_outstanding"].set(len(self._leases))
            job, key = lease.job, (lease.epoch, lease.ordinal)
            job.done_with.add(key)
            job.rows_of[key] = rows
            entries = []
            if prov is not None:
                entries.append(tuple(prov))
            if wire is not None:
                from petastorm_tpu.obs.provenance import item_identity

                _e, _o, ikey = item_identity(
                    (lease.epoch, lease.ordinal,
                     job.spec.items[lease.ordinal]))
                annotations = {} if lease.worker is None \
                    else {"svc_worker": lease.worker}
                entries.append((
                    (lease.epoch, lease.ordinal, ikey,
                     [("svc.wire", wire[0], wire[1], None)], annotations),
                    -os.getpid(), self._wall_anchor, self._perf_anchor))
            if entries:
                job.prov_of[key] = entries
            if lease.worker is not None and job.spec.tenant is not None:
                self._worker_tenants.setdefault(
                    lease.worker, set()).add(job.spec.tenant)
            if key in job.decoded:
                self._m["redecodes"].inc()
            job.decoded.add(key)
            needers = job.need.pop(key, set())
            served = 0
            for tid in needers:
                trainer = job.trainers.get(tid)
                if trainer is None:
                    continue
                trainer.remaining.get(lease.epoch, set()).discard(
                    lease.ordinal)
                trainer.queue.append(("item", lease.epoch, lease.ordinal,
                                      payload, rows))
                _nudge(trainer)
                served += 1
            self._m["decodes"].inc()
            self._m["decode_seconds"].inc(
                max(0.0, float(meta.get("decode_s", 0.0))))
            if served > 1:
                self._m["fanout_serves"].inc(served - 1)
            tenant = job.spec.tenant
            self._cond.notify_all()
        _charge("worker_s", max(0.0, float(meta.get("wall_s", 0.0))), tenant)
        _charge("decode_s", max(0.0, float(meta.get("decode_s", 0.0))),
                tenant)
        if self._arena is not None:
            arena_key = ("svc", job.spec.job, lease.epoch, lease.ordinal)
            if self._arena.put(arena_key, payload):
                with self._cond:
                    job.arena_admitted.add(key)

    def _fail(self, lease_id, error, permanent):
        with self._cond:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            self._m["leases_outstanding"].set(len(self._leases))
            job, key = lease.job, (lease.epoch, lease.ordinal)
            job.fail_attempts[key] = job.fail_attempts.get(key, 0) + 1
            if permanent or \
                    job.fail_attempts[key] >= self._rec.poison_attempts:
                self._quarantine_locked(job, lease.epoch, lease.ordinal,
                                        "decode_error" if permanent
                                        else "poison")
                return
            job.dispatcher.return_items(
                [(lease.epoch, lease.ordinal, lease.ordinal)])
            self._m["lease_redispatch"].inc()
            self._cond.notify_all()
        _degradation(
            "svc_decode_retry",
            "data service decode of %s[%d:%d] failed transiently (%s); "
            "re-dispatching", job.spec.job, lease.epoch, lease.ordinal,
            error, once=False)

    def _quarantine_locked(self, job, epoch, ordinal, cause):
        """Caller holds self._cond. Exactly-once: the verdict is recorded in
        the job ledger and broadcast to every attached trainer's watermark;
        trainers attaching later receive it during their attach replay."""
        key = (epoch, ordinal)
        if key in job.quarantined:
            return
        job.quarantined[key] = cause
        job.done_with.add(key)
        for tid in job.need.pop(key, set()):
            trainer = job.trainers.get(tid)
            if trainer is None:
                continue
            trainer.remaining.get(epoch, set()).discard(ordinal)
            trainer.queue.append(("quar", epoch, ordinal, cause))
            _nudge(trainer)
        self._m["quarantined"].inc()
        self._cond.notify_all()
        _degradation(
            "svc_quarantine",
            "data service quarantined %s[%d:%d] (cause=%s); every trainer's "
            "watermark is charged exactly once", job.spec.job, epoch,
            ordinal, cause, once=False)

    # -- worker loop --------------------------------------------------------------------

    def _register_worker(self, wname, session):
        """Resolve this worker's labeled health families ONCE at READY —
        never inside the lease loop (get-or-create takes the registry lock).
        A reconnecting worker of the same name reclaims its families: the
        totals are the worker's story, not the link's."""
        reg = self._registry
        info = {
            "session": session,
            "connected": True,
            "hist": reg.histogram(
                "ptpu_svc_worker_decode_seconds",
                help="per-lease decode seconds as reported by this worker "
                     "(the straggler SLO and FleetAdvisor read the window "
                     "p99 of this family)",
                worker=wname),
            "idle": reg.counter(
                "ptpu_svc_worker_idle_seconds_total",
                help="seconds this worker's dispatch slot waited with no "
                     "claimable work (the fleet-shrink signal)",
                worker=wname),
            "leases": reg.counter(
                "ptpu_svc_worker_leases_total",
                help="leases dispatched to this worker that reached a "
                     "verdict (done or fail)",
                worker=wname),
        }
        with self._cond:
            self._workers[wname] = info
        return info

    def _worker_loop(self, session, transport):
        slot = self._alloc_slot()
        counted = False
        try:
            if not self._wait_connected(transport):
                return
            try:
                msg = transport.recv()
            except (TransportLinkDown, EOFError, OSError):
                return
            if msg.get("op") != OP_READY:
                return
            wname = msg.get("worker") or "worker-%d" % session
            winfo = self._register_worker(wname, session)
            self._m["workers"].inc()
            counted = True
            announced = set()
            while not self._stop.is_set():
                i0 = time.perf_counter()
                lease = self._next_lease(slot, worker=wname)
                if lease is None:
                    # the whole timed-out claim was idle capacity
                    winfo["idle"].inc(time.perf_counter() - i0)
                    continue
                job = lease.job
                out = {"op": OP_LEASE, "lease": lease.lease_id,
                       "job": job.spec.job, "epoch": lease.epoch,
                       "ordinal": lease.ordinal,
                       "item": job.spec.items[lease.ordinal]}
                if job.spec.job not in announced:
                    out["spec"] = job.spec.wire_spec()
                transport.track(lease.lease_id)
                ws0 = time.perf_counter()
                try:
                    transport.send(out)
                    reply = transport.recv()
                except (TransportLinkDown, OSError):
                    self._requeue_lease(lease.lease_id, link=True)
                    self._withdraw_slot(slot)
                    announced = set()  # fresh generation: re-announce specs
                    if transport.reconnect(self._rec.link_reconnect_s):
                        continue
                    return
                except EOFError:
                    self._requeue_lease(lease.lease_id, link=True)
                    self._withdraw_slot(slot)
                    return
                ws1 = time.perf_counter()
                transport.settle()
                doc = reply.get("telemetry")
                if doc:
                    self._telemetry.note_peer("worker", wname, doc)
                op = reply.get("op")
                if op == OP_DONE and reply.get("lease") == lease.lease_id:
                    meta = reply.get("meta") or {}
                    winfo["leases"].inc()
                    winfo["hist"].observe(
                        max(0.0, float(meta.get("decode_s", 0.0))))
                    self._complete(lease.lease_id, reply.get("payload"),
                                   reply.get("rows"), meta,
                                   prov=reply.get("prov"), wire=(ws0, ws1))
                elif op == OP_FAIL and reply.get("lease") == lease.lease_id:
                    winfo["leases"].inc()
                    self._fail(lease.lease_id, reply.get("error"),
                               bool(reply.get("permanent")))
                else:
                    # an unparseable reply is a broken conversation: requeue
                    self._requeue_lease(lease.lease_id, link=True)
        finally:
            with self._cond:
                for lid, lease in list(self._leases.items()):
                    if lease.slot == slot:
                        self._leases.pop(lid)
                        self._m["leases_outstanding"].set(len(self._leases))
                        lease.job.dispatcher.return_items(
                            [(lease.epoch, lease.ordinal, lease.ordinal)])
                        self._m["lease_redispatch"].inc()
                self._cond.notify_all()
            self._withdraw_slot(slot)
            if counted:
                self._m["workers"].dec()
                with self._cond:
                    info = self._workers.get(wname)
                    if info is not None and info["session"] == session:
                        info["connected"] = False
                self._telemetry.drop_peer("worker", wname)
            try:
                transport.send({"op": OP_STOP})
            except Exception:  # graftlint: disable=GL-O002 — best-effort goodbye on a possibly-dead link
                pass
            transport.close()
            self._hub.drop_session(session)
            with self._cond:
                self._transports.pop(session, None)

    # -- trainer loop -------------------------------------------------------------------

    def _trainer_loop(self, session, transport):
        job = trainer = None
        try:
            if not self._wait_connected(transport):
                return
            while not self._stop.is_set():
                try:
                    msg = transport.recv()
                except (TransportLinkDown, OSError):
                    if transport.reconnect(self._rec.link_reconnect_s):
                        continue
                    return
                except EOFError:
                    return
                if msg.get("op") != OP_ATTACH:
                    continue
                while True:
                    job, trainer, reply = self._attach(session, msg)
                    try:
                        transport.send(reply)
                    except (TransportLinkDown, EOFError, OSError) as exc:
                        if trainer is not None:
                            self._detach(job, trainer)
                            job = trainer = None
                        if isinstance(exc, EOFError) or \
                                not transport.reconnect(
                                    self._rec.link_reconnect_s):
                            return
                        break
                    if trainer is None:
                        break  # rejected: the peer may retry another attach
                    transport.set_tenant(trainer.tenant)
                    outcome = self._serve(transport, job, trainer)
                    if isinstance(outcome, tuple):
                        # a fresh attach raced ahead of the link-death
                        # notice: the old conversation is dead — detach it
                        # and process the new watermark in place
                        self._detach(job, trainer)
                        job = trainer = None
                        msg = outcome[1]
                        continue
                    if outcome == "dead":
                        self._detach(job, trainer)
                        job = trainer = None
                        if transport.reconnect(self._rec.link_reconnect_s):
                            break  # await a watermark-exact re-attach
                        return
                    job = trainer = None
                    if outcome == "stop":
                        return
                    break  # clean detach: loop for a possible re-attach
        finally:
            if trainer is not None:
                self._detach(job, trainer)
            transport.close()
            self._hub.drop_session(session)
            with self._cond:
                self._transports.pop(session, None)

    def _attach(self, session, msg):
        """Admission + watermark-exact shard computation. Returns
        ``(job, trainer, reply)`` — trainer None when rejected."""
        job_name = msg.get("job")
        tid = msg.get("trainer") or "trainer-%d" % session
        tenant = msg.get("tenant")
        consumed = {int(e): set(v)
                    for e, v in (msg.get("consumed") or {}).items()}
        with self._cond:
            job = self._jobs.get(job_name)
            eff_tenant = tenant if tenant is not None else \
                (job.spec.tenant if job is not None else None)
            reason = None
            if job is None:
                reason = "unknown job %r" % job_name
            elif tid in job.trainers:
                reason = "trainer id %r already attached" % tid
            elif sum(len(j.trainers) for j in self._jobs.values()) \
                    >= self._opt.max_trainers:
                reason = "service at max_trainers=%d" % self._opt.max_trainers
            elif self._opt.max_trainers_per_tenant is not None and sum(
                    1 for j in self._jobs.values()
                    for t in j.trainers.values() if t.tenant == eff_tenant) \
                    >= self._opt.max_trainers_per_tenant:
                reason = "tenant %r at max_trainers_per_tenant=%d" \
                    % (eff_tenant, self._opt.max_trainers_per_tenant)
            elif self._tenant_weight.get(eff_tenant, 1.0) <= 0.0:
                reason = "tenant %r is throttled to weight 0 (admission)" \
                    % eff_tenant
            if reason is not None:
                self._m["rejected"].inc()
                return job, None, {"op": OP_REJECTED, "reason": reason}
            trainer = _Trainer(tid, session, eff_tenant, job.spec.priority,
                               msg.get("arena") and self._arena is not None)
            redecode = []
            for epoch, size in job.epoch_sizes.items():
                rem = set(range(size)) - consumed.get(epoch, set())
                queued = set()
                for ordinal in rem:
                    key = (epoch, ordinal)
                    if key in job.quarantined:
                        trainer.queue.append(("quar", epoch, ordinal,
                                              job.quarantined[key]))
                        queued.add(ordinal)
                    elif key in job.done_with:
                        # decoded before this trainer existed: serve from the
                        # arena warm set, or re-decode (correctness path)
                        if trainer.arena and key in job.arena_admitted:
                            trainer.queue.append(("arena", epoch, ordinal))
                            queued.add(ordinal)
                        else:
                            job.need.setdefault(key, set()).add(tid)
                            job.done_with.discard(key)
                            redecode.append((epoch, ordinal, ordinal))
                    else:
                        job.need.setdefault(key, set()).add(tid)
                trainer.remaining[epoch] = rem - queued
            if redecode:
                job.dispatcher.return_items(redecode)
            job.trainers[tid] = trainer
            self._m["attaches"].inc()
            self._m["trainers"].inc()
            self._cond.notify_all()
            return job, trainer, {
                "op": OP_ATTACHED, "version": PROTOCOL_VERSION,
                "schema": job.spec.schema, "trainer": tid,
                "num_epochs": job.spec.num_epochs,
                "epoch_sizes": dict(job.epoch_sizes),
                "arena": trainer.arena}

    def _detach(self, job, trainer):
        """Remove the trainer; its unconsumed interest leaves every need set
        (no loss: a re-attach recomputes from the client's watermark)."""
        with self._cond:
            trainer.transport = None
            job.trainers.pop(trainer.tid, None)
            for key in list(job.need):
                s = job.need[key]
                s.discard(trainer.tid)
                if not s:
                    del job.need[key]
            trainer.queue = []
            self._m["detaches"].inc()
            self._m["trainers"].dec()
            self._cond.notify_all()
        self._telemetry.drop_peer("trainer", trainer.tid)

    def _entry_msg(self, job, trainer, entry):
        kind = entry[0]
        if kind == "quar":
            _, epoch, ordinal, cause = entry
            key = (epoch, ordinal)
            return {"op": OP_QUARANTINED, "epoch": epoch,
                    "ordinal": ordinal, "cause": cause,
                    "attempts": max(1, job.fail_attempts.get(key, 0)
                                    + job.link_attempts.get(key, 0))}, 0
        if kind == "arena":
            _, epoch, ordinal = entry
            msg = {"op": OP_ITEM, "epoch": epoch, "ordinal": ordinal,
                   "rows": job.rows_of.get((epoch, ordinal)),
                   "payload": None,
                   "arena_key": ("svc", job.spec.job, epoch, ordinal)}
            prov = job.prov_of.get((epoch, ordinal))
            if prov:
                msg["prov"] = prov
            return msg, job.rows_of.get((epoch, ordinal)) or 0
        _, epoch, ordinal, payload, rows = entry
        msg = {"op": OP_ITEM, "epoch": epoch, "ordinal": ordinal,
               "rows": rows}
        if trainer.arena and (epoch, ordinal) in job.arena_admitted \
                and (epoch, ordinal) not in job.inline_keys:
            msg["payload"] = None
            msg["arena_key"] = ("svc", job.spec.job, epoch, ordinal)
        else:
            msg["payload"] = payload
        prov = job.prov_of.get((epoch, ordinal))
        if prov:
            # every fan-out push carries the item's cross-wire provenance:
            # each receiving trainer's recorder absorbs it independently
            msg["prov"] = prov
        return msg, rows or 0

    def _serve(self, transport, job, trainer):
        """The attached steady state: flush credit-gated pushes, poll for
        want/refetch/detach. Returns "detach" | "dead" | "stop", or
        ``("attach", msg)`` when a redialed peer's fresh attach raced ahead
        of this side's link-death notice."""
        with self._cond:
            trainer.transport = transport
        while not self._stop.is_set():
            to_send = []
            with self._cond:
                while trainer.credits > 0 and trainer.queue:
                    to_send.append(trainer.queue.pop(0))
                    trainer.credits -= 1
                finished = trainer.finished() and not trainer.end_sent
                # the undersupply signal: credits granted, nothing to push,
                # plan unfinished — the decode fleet is behind this trainer
                starving = trainer.credits > 0 and not trainer.queue \
                    and not trainer.finished()
            try:
                for entry in to_send:
                    msg, rows = self._entry_msg(job, trainer, entry)
                    transport.send(msg)
                    if msg["op"] == OP_ITEM:
                        self._m["served_items"].inc()
                        self._m["served_rows"].inc(rows)
                        _charge("rows", rows, trainer.tenant)
                        _charge("svc_items", 1, trainer.tenant)
                if finished:
                    transport.send({"op": OP_END})
                    trainer.end_sent = True
                w0 = time.monotonic()
                if not transport.poll(TICK_S, wakeable=True):
                    if starving:
                        # a wake() can end the poll early — charge the time
                        # actually spent waiting, not the full tick
                        self._m["starved_seconds"].inc(
                            max(0.0, time.monotonic() - w0))
                    continue
                msg = transport.recv()
            except (TransportLinkDown, OSError):
                return "dead"
            except EOFError:
                return "dead"
            op = msg.get("op")
            if op == OP_WANT:
                doc = msg.get("telemetry")
                if doc:
                    self._telemetry.note_peer("trainer", trainer.tid, doc)
                with self._cond:
                    trainer.credits += max(0, int(msg.get("credits", 0)))
            elif op == OP_REFETCH:
                self._refetch(job, trainer, int(msg.get("epoch", 0)),
                              int(msg.get("ordinal", 0)))
            elif op == OP_DETACH:
                self._detach(job, trainer)
                try:
                    transport.send({"op": OP_DETACHED})
                except (TransportLinkDown, EOFError, OSError):
                    return "dead"
                return "detach"
            elif op == OP_ATTACH:
                return ("attach", msg)
        return "stop"

    def _refetch(self, job, trainer, epoch, ordinal):
        """An arena-key push the trainer could not map (evicted between admit
        and get): re-serve it — from a decode if the payload is gone."""
        key = (epoch, ordinal)
        with self._cond:
            self._m["refetches"].inc()
            job.arena_admitted.discard(key)
            job.inline_keys.add(key)
            if key in job.quarantined:
                trainer.queue.append(("quar", epoch, ordinal,
                                      job.quarantined[key]))
                _nudge(trainer)
                self._cond.notify_all()
                return
            job.need.setdefault(key, set()).add(trainer.tid)
            if key in job.done_with:
                job.done_with.discard(key)
                job.dispatcher.return_items([(epoch, ordinal, ordinal)])
            self._cond.notify_all()
