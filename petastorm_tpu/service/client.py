"""Trainer side of the data service: :class:`ServiceReader` (ISSUE 19).

A ServiceReader attaches to one job on a :class:`DataService` and duck-types
the batched-reader surface the :class:`~petastorm_tpu.loader.DataLoader`
consumes — iteration yields the schema's namedtuple of numpy columns, and
``state_dict()``/``load_state_dict()`` checkpoint the same consumed-ordinal
watermark the in-process :class:`~petastorm_tpu.reader.Reader` keeps. The
service never tracks delivery acks: this watermark, presented at every
(re)attach, IS the resume contract — a link death mid-epoch turns into a
fresh attach that recomputes the remaining shard exactly (no loss from the
detach, no replay into the trainer).

Delivery is credit-flow push: the reader grants the service a small window
of pushes (``credits``) and replenishes as it consumes, so a stalled trainer
backpressures the service instead of ballooning its socket. Co-hosted
trainers negotiate the PR 17 host arena at attach: items then arrive as an
``arena_key`` instead of pickled columns, and the payload is mapped zero-
copy out of the shared warm set (a miss — evicted between admit and get —
is re-served via ``refetch``).
"""
from __future__ import annotations

import threading

from petastorm_tpu.errors import TransportLinkDown
from petastorm_tpu.recovery import RecoveryOptions
from petastorm_tpu.service.protocol import (
    OP_ATTACH,
    OP_ATTACHED,
    OP_DETACH,
    OP_DETACHED,
    OP_END,
    OP_ITEM,
    OP_QUARANTINED,
    OP_REFETCH,
    OP_REJECTED,
    OP_WANT,
)


class ServiceAttachRejected(RuntimeError):
    """The service's admission control refused the attach."""


class ServiceReader:
    """Batched reader over a data-service job. Plugs into
    :class:`~petastorm_tpu.loader.DataLoader` unchanged::

        reader = ServiceReader(svc.trainer_address(), svc.token, job="train")
        loader = DataLoader(reader, batch_size=256)
    """

    is_batched_reader = True

    def __init__(self, address, token, job, trainer=None, tenant=None,
                 recovery=None, credits=8, arena=True):
        from petastorm_tpu.transport.tcp import TcpChildTransport, \
            parse_address

        self._rec = recovery or RecoveryOptions()
        host, port, session = parse_address(address)
        self.job = job
        self.trainer = trainer or "trainer-%d" % session
        self.tenant = tenant
        self._want_arena = bool(arena)
        self._credit_target = max(1, int(credits))
        self._credits_out = 0
        self._consumed = {}          # epoch -> set(ordinal) — THE watermark
        self.quarantined = {}        # (epoch, ordinal) -> cause
        self._arena = None
        self._arena_leases = []
        self._refetching = set()     # keys re-requested after an arena miss
        self._end_seen = False
        self._stopped = False
        self._lock = threading.Lock()
        self.schema = None
        self.num_epochs = 0
        self.epoch_sizes = {}
        # loader duck surface
        self.keep_passthrough = False
        self.transform_spec = None
        self.last_row_consumed = False
        self.cur_shard = None
        self.shard_count = None
        self._transport = TcpChildTransport(host, port, session, token,
                                            self._rec)
        self._transport.dial()
        self._transport.mark_ready()
        self._attach()

    # -- attach / detach ----------------------------------------------------------------

    def _attach(self):
        """(Re)attach with the current watermark; retries across link deaths
        until the service answers or the redial ceiling kills the link."""
        out = {"op": OP_ATTACH, "job": self.job, "trainer": self.trainer,
               "tenant": self.tenant, "arena": self._want_arena,
               "consumed": {e: sorted(v)
                            for e, v in self._consumed.items()}}
        while True:
            try:
                self._transport.send(out)
                while True:
                    reply = self._transport.recv()
                    op = reply.get("op")
                    if op in (OP_ATTACHED, OP_REJECTED):
                        break
                    # stale pushes from the dead conversation: unconsumed,
                    # so the fresh attach re-serves them — drop here
            except TransportLinkDown:
                continue
            break
        if reply["op"] == OP_REJECTED:
            raise ServiceAttachRejected(reply.get("reason", "rejected"))
        self.schema = reply["schema"]
        self.num_epochs = reply["num_epochs"]
        self.epoch_sizes = dict(reply["epoch_sizes"])
        self._row_type = self.schema.make_namedtuple_type()
        self._credits_out = 0
        self._refetching = set()
        self._end_seen = False
        if reply.get("arena") and self._arena is None:
            from petastorm_tpu.io.arena import process_arena

            self._arena = process_arena()

    def detach(self):
        """Clean mid-epoch detach: unconsumed work returns to the pool with
        no loss; a later :class:`ServiceReader` restored from this reader's
        :meth:`state_dict` resumes watermark-exact."""
        try:
            self._transport.send({"op": OP_DETACH})
            while True:
                reply = self._transport.recv()
                if reply.get("op") == OP_DETACHED:
                    break
        except (TransportLinkDown, EOFError, OSError):
            pass  # a dead link IS a detach server-side

    # -- iteration ----------------------------------------------------------------------

    def __iter__(self):
        return self

    def _mark_consumed(self, epoch, ordinal):
        self._consumed.setdefault(int(epoch), set()).add(int(ordinal))

    def _materialize(self, msg):
        """Columns for one item push: inline payload, or an arena mapping
        pinned by a lease the reader holds until :meth:`stop`. Returns None
        when the arena missed (a refetch was sent)."""
        payload = msg.get("payload")
        if payload is not None:
            return payload
        key = msg.get("arena_key")
        got = self._arena.get(tuple(key)) if self._arena is not None else None
        if got is None:
            self._refetching.add((int(msg["epoch"]), int(msg["ordinal"])))
            self._transport.send({"op": OP_REFETCH, "epoch": msg["epoch"],
                                  "ordinal": msg["ordinal"]})
            return None
        value, lease = got
        self._arena_leases.append(lease)
        return value

    def __next__(self):
        if self._stopped:
            raise StopIteration
        while True:
            if self._end_seen and not self._refetching:
                # "end" marks the plan complete, but an in-flight refetch
                # (arena miss) still owes us its item — drain those first
                self.last_row_consumed = True
                raise StopIteration
            low_water = max(1, self._credit_target // 2)
            try:
                if self._credits_out < low_water:
                    grant = self._credit_target - self._credits_out
                    self._transport.send({"op": OP_WANT, "credits": grant})
                    self._credits_out += grant
                msg = self._transport.recv()
            except TransportLinkDown:
                self._attach()  # link is back: resume watermark-exact
                continue
            except (EOFError, OSError):
                self.last_row_consumed = True
                raise StopIteration from None
            op = msg.get("op")
            if op == OP_ITEM:
                self._credits_out = max(0, self._credits_out - 1)
                try:
                    cols = self._materialize(msg)
                except TransportLinkDown:
                    self._attach()
                    continue
                if cols is None:
                    continue  # arena miss: the refetch re-serves it
                self._refetching.discard(
                    (int(msg["epoch"]), int(msg["ordinal"])))
                self._mark_consumed(msg["epoch"], msg["ordinal"])
                return self._row_type(**cols)
            if op == OP_QUARANTINED:
                self._credits_out = max(0, self._credits_out - 1)
                self._refetching.discard(
                    (int(msg["epoch"]), int(msg["ordinal"])))
                self._mark_consumed(msg["epoch"], msg["ordinal"])
                self.quarantined[(int(msg["epoch"]), int(msg["ordinal"]))] \
                    = msg.get("cause")
                continue
            if op == OP_END:
                self._end_seen = True

    def next(self):
        return self.__next__()

    # -- checkpoint ---------------------------------------------------------------------

    def state_dict(self):
        """The consumed-work watermark — restoring it into a fresh
        ServiceReader (or this one) resumes exactly where this shard
        stopped, quarantined items charged exactly once."""
        return {
            "service": 1,
            "job": self.job,
            "consumed": {int(e): sorted(v)
                         for e, v in self._consumed.items()},
        }

    def load_state_dict(self, state):
        if state.get("service") != 1 or "consumed" not in state:
            raise ValueError(
                "not a ServiceReader state (keys: %s)" % sorted(state))
        if state.get("job") != self.job:
            raise ValueError(
                "checkpoint belongs to job %r; this reader is attached to "
                "%r — resuming would replay the wrong plan"
                % (state.get("job"), self.job))
        self.detach()
        self._consumed = {int(e): set(v)
                          for e, v in state["consumed"].items()}
        self.last_row_consumed = False
        self._attach()

    # -- loader duck surface ------------------------------------------------------------

    def set_trace(self, tracer):
        pass

    def set_provenance(self, recorder):
        pass

    def set_health(self, monitor):
        pass

    def reset(self):
        """Fresh pass over the full plan (clears the watermark)."""
        self.detach()
        self._consumed = {}
        self.last_row_consumed = False
        self._attach()

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self.detach()
        leases, self._arena_leases = self._arena_leases, []
        for lease in leases:
            lease.release()
        self._transport.close()

    def join(self):
        pass
