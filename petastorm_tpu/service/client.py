"""Trainer side of the data service: :class:`ServiceReader` (ISSUE 19).

A ServiceReader attaches to one job on a :class:`DataService` and duck-types
the batched-reader surface the :class:`~petastorm_tpu.loader.DataLoader`
consumes — iteration yields the schema's namedtuple of numpy columns, and
``state_dict()``/``load_state_dict()`` checkpoint the same consumed-ordinal
watermark the in-process :class:`~petastorm_tpu.reader.Reader` keeps. The
service never tracks delivery acks: this watermark, presented at every
(re)attach, IS the resume contract — a link death mid-epoch turns into a
fresh attach that recomputes the remaining shard exactly (no loss from the
detach, no replay into the trainer).

Delivery is credit-flow push: the reader grants the service a small window
of pushes (``credits``) and replenishes as it consumes, so a stalled trainer
backpressures the service instead of ballooning its socket. Co-hosted
trainers negotiate the PR 17 host arena at attach: items then arrive as an
``arena_key`` instead of pickled columns, and the payload is mapped zero-
copy out of the shared warm set (a miss — evicted between admit and get —
is re-served via ``refetch``).

Delivery follows fleet completion order by default — lowest latency, but a
straggler worker's delay smears across whichever items arrive after it.
``ordered=True`` re-sequences pushes into plan (epoch, ordinal) order
through a client-side reorder buffer: deterministic delivery at
head-of-line latency, which also pins a straggler's cost to its own items
so the attribution fold below can name the worker.

Cross-wire provenance (ISSUE 20): when a
:class:`~petastorm_tpu.obs.provenance.ProvenanceRecorder` is wired
(``DataLoader(provenance=...)`` calls :meth:`ServiceReader.set_provenance`),
every item push's piggybacked entries — the decode worker's
``svc.decode@<name>`` blob and the service's ``svc.wire`` span, each with
its own wall/perf anchor pair — are absorbed through the recorder's
clock-aligned child merge, and the reader adds its own ``svc.lease_wait``
span around the blocking receive. The critical-path fold then charges the
full cross-wire path and ``slow_top`` names the culprit worker. A
``/timelines``-shaped telemetry document rides the ``want`` credit grants
on a slow cadence (``telemetry_s``) for the service's ``/fleet`` view.
"""
from __future__ import annotations

import threading
import time

from petastorm_tpu.errors import TransportLinkDown
from petastorm_tpu.recovery import RecoveryOptions
from petastorm_tpu.service.protocol import (
    OP_ATTACH,
    OP_ATTACHED,
    OP_DETACH,
    OP_DETACHED,
    OP_END,
    OP_ITEM,
    OP_QUARANTINED,
    OP_REFETCH,
    OP_REJECTED,
    OP_WANT,
)


class ServiceAttachRejected(RuntimeError):
    """The service's admission control refused the attach."""


class ServiceReader:
    """Batched reader over a data-service job. Plugs into
    :class:`~petastorm_tpu.loader.DataLoader` unchanged::

        reader = ServiceReader(svc.trainer_address(), svc.token, job="train")
        loader = DataLoader(reader, batch_size=256)
    """

    is_batched_reader = True

    def __init__(self, address, token, job, trainer=None, tenant=None,
                 recovery=None, credits=8, arena=True, registry=None,
                 telemetry_s=2.0, ordered=False):
        from petastorm_tpu.transport.tcp import TcpChildTransport, \
            parse_address

        self._rec = recovery or RecoveryOptions()
        self._prov = None
        self._registry = registry
        self._telemetry_s = None if telemetry_s is None \
            else max(0.1, float(telemetry_s))
        self._telemetry_next = time.monotonic()
        #: ordered=True yields items in plan (epoch, ordinal) order instead
        #: of fleet completion order: out-of-order pushes park in a reorder
        #: buffer until the cursor item lands. Deterministic delivery, and a
        #: straggler worker's latency surfaces AT ITS OWN ITEMS (head-of-
        #: line), where the provenance fold can name it — completion order
        #: launders a straggler into uniform inter-arrival waits.
        self._ordered = bool(ordered)
        self._pending = {}           # (epoch, ordinal) -> buffered push
        self._cursor = (0, 0)        # next (epoch, ordinal) to yield (ordered)
        host, port, session = parse_address(address)
        self.job = job
        self.trainer = trainer or "trainer-%d" % session
        self.tenant = tenant
        self._want_arena = bool(arena)
        self._credit_target = max(1, int(credits))
        self._credits_out = 0
        self._consumed = {}          # epoch -> set(ordinal) — THE watermark
        self.quarantined = {}        # (epoch, ordinal) -> cause
        self._arena = None
        self._arena_leases = []
        self._refetching = set()     # keys re-requested after an arena miss
        self._end_seen = False
        self._stopped = False
        self._lock = threading.Lock()
        self.schema = None
        self.num_epochs = 0
        self.epoch_sizes = {}
        # loader duck surface
        self.keep_passthrough = False
        self.transform_spec = None
        self.last_row_consumed = False
        self.cur_shard = None
        self.shard_count = None
        self._transport = TcpChildTransport(host, port, session, token,
                                            self._rec)
        self._transport.dial()
        self._transport.mark_ready()
        self._attach()

    # -- attach / detach ----------------------------------------------------------------

    def _attach(self):
        """(Re)attach with the current watermark; retries across link deaths
        until the service answers or the redial ceiling kills the link."""
        out = {"op": OP_ATTACH, "job": self.job, "trainer": self.trainer,
               "tenant": self.tenant, "arena": self._want_arena,
               "consumed": {e: sorted(v)
                            for e, v in self._consumed.items()}}
        while True:
            try:
                self._transport.send(out)
                while True:
                    reply = self._transport.recv()
                    op = reply.get("op")
                    if op in (OP_ATTACHED, OP_REJECTED):
                        break
                    # stale pushes from the dead conversation: unconsumed,
                    # so the fresh attach re-serves them — drop here
            except TransportLinkDown:
                continue
            break
        if reply["op"] == OP_REJECTED:
            raise ServiceAttachRejected(reply.get("reason", "rejected"))
        self.schema = reply["schema"]
        self.num_epochs = reply["num_epochs"]
        self.epoch_sizes = dict(reply["epoch_sizes"])
        self._row_type = self.schema.make_namedtuple_type()
        self._credits_out = 0
        self._refetching = set()
        self._end_seen = False
        # stale reorder-buffered pushes belong to the dead conversation;
        # they are unconsumed, so the fresh attach re-serves them
        self._pending = {}
        self._cursor = (0, 0)
        self._advance_cursor()
        if reply.get("arena") and self._arena is None:
            from petastorm_tpu.io.arena import process_arena

            self._arena = process_arena()

    def detach(self):
        """Clean mid-epoch detach: unconsumed work returns to the pool with
        no loss; a later :class:`ServiceReader` restored from this reader's
        :meth:`state_dict` resumes watermark-exact."""
        try:
            self._transport.send({"op": OP_DETACH})
            while True:
                reply = self._transport.recv()
                if reply.get("op") == OP_DETACHED:
                    break
        except (TransportLinkDown, EOFError, OSError):
            pass  # a dead link IS a detach server-side

    # -- iteration ----------------------------------------------------------------------

    def __iter__(self):
        return self

    def _mark_consumed(self, epoch, ordinal):
        self._consumed.setdefault(int(epoch), set()).add(int(ordinal))

    def _advance_cursor(self):
        """Move the ordered-mode cursor to the smallest unconsumed
        (epoch, ordinal) at or after its current position."""
        if not self._ordered:
            return
        e, o = self._cursor
        while e < self.num_epochs:
            size = int(self.epoch_sizes.get(e, 0))
            consumed = self._consumed.get(e, ())
            while o < size and o in consumed:
                o += 1
            if o < size:
                break
            e, o = e + 1, 0
        self._cursor = (e, o)

    def _consume_quarantine(self, msg, epoch, ordinal):
        """A quarantine push occupies its ordinal: record the cause, mark
        the slot consumed, and (ordered mode) advance past it."""
        self._refetching.discard((epoch, ordinal))
        self._mark_consumed(epoch, ordinal)
        self.quarantined[(epoch, ordinal)] = msg.get("cause")
        if self._prov is not None:
            # the trainer-side twin of the service's exactly-once
            # quarantine ledger entry
            self._prov.note_quarantined(
                epoch, ordinal, int(msg.get("attempts", 1)),
                msg.get("cause") or "quarantined")
        self._advance_cursor()

    def _flush_pending(self):
        """Deliver the reorder-buffered push parked at the cursor, if any.
        Returns the row, or None when the head of line hasn't arrived yet
        (or a buffered quarantine / arena miss advanced state row-lessly)."""
        while True:
            entry = self._pending.pop(self._cursor, None)
            if entry is None:
                return None
            epoch, ordinal = self._cursor
            if entry[0] == "quar":
                self._consume_quarantine(entry[1], epoch, ordinal)
                continue
            _, msg, r0, r1 = entry
            try:
                cols = self._materialize(msg)
            except TransportLinkDown:
                self._attach()  # clears the buffer; the attach re-serves
                return None
            if cols is None:
                return None  # arena miss: the refetch re-serves at cursor
            self._refetching.discard((epoch, ordinal))
            self._mark_consumed(epoch, ordinal)
            self._absorb_prov(msg, epoch, ordinal, r0, r1)
            self._advance_cursor()
            return self._row_type(**cols)

    def _materialize(self, msg):
        """Columns for one item push: inline payload, or an arena mapping
        pinned by a lease the reader holds until :meth:`stop`. Returns None
        when the arena missed (a refetch was sent)."""
        payload = msg.get("payload")
        if payload is not None:
            return payload
        key = msg.get("arena_key")
        got = self._arena.get(tuple(key)) if self._arena is not None else None
        if got is None:
            self._refetching.add((int(msg["epoch"]), int(msg["ordinal"])))
            self._transport.send({"op": OP_REFETCH, "epoch": msg["epoch"],
                                  "ordinal": msg["ordinal"]})
            return None
        value, lease = got
        self._arena_leases.append(lease)
        return value

    def __next__(self):
        if self._stopped:
            raise StopIteration
        while True:
            if self._ordered:
                row = self._flush_pending()
                if row is not None:
                    return row
            if self._end_seen and not self._refetching and not self._pending:
                # "end" marks the plan complete, but an in-flight refetch
                # (arena miss) or a reorder-buffered push still owes us its
                # item — drain those first
                self.last_row_consumed = True
                raise StopIteration
            low_water = max(1, self._credit_target // 2)
            r0 = time.perf_counter()
            try:
                if self._credits_out < low_water:
                    grant = self._credit_target - self._credits_out
                    out = {"op": OP_WANT, "credits": grant}
                    doc = self._maybe_telemetry()
                    if doc is not None:
                        out["telemetry"] = doc
                    self._transport.send(out)
                    self._credits_out += grant
                msg = self._transport.recv()
            except TransportLinkDown:
                self._attach()  # link is back: resume watermark-exact
                continue
            except (EOFError, OSError):
                self.last_row_consumed = True
                raise StopIteration from None
            r1 = time.perf_counter()
            op = msg.get("op")
            if op == OP_ITEM:
                self._credits_out = max(0, self._credits_out - 1)
                epoch, ordinal = int(msg["epoch"]), int(msg["ordinal"])
                if self._ordered and (epoch, ordinal) != self._cursor:
                    if ordinal not in self._consumed.get(epoch, ()):
                        self._pending[(epoch, ordinal)] = \
                            ("item", msg, r0, r1)
                    continue  # head of line hasn't arrived yet
                try:
                    cols = self._materialize(msg)
                except TransportLinkDown:
                    self._attach()
                    continue
                if cols is None:
                    continue  # arena miss: the refetch re-serves it
                self._refetching.discard((epoch, ordinal))
                self._mark_consumed(epoch, ordinal)
                self._absorb_prov(msg, epoch, ordinal, r0, r1)
                self._advance_cursor()
                return self._row_type(**cols)
            if op == OP_QUARANTINED:
                self._credits_out = max(0, self._credits_out - 1)
                epoch, ordinal = int(msg["epoch"]), int(msg["ordinal"])
                if self._ordered and (epoch, ordinal) != self._cursor:
                    if ordinal not in self._consumed.get(epoch, ()):
                        self._pending[(epoch, ordinal)] = ("quar", msg)
                    continue
                self._consume_quarantine(msg, epoch, ordinal)
                continue
            if op == OP_END:
                self._end_seen = True

    def _absorb_prov(self, msg, epoch, ordinal, r0, r1):
        """Merge the push's cross-wire provenance into the wired recorder:
        absorb each producer blob through its own clock anchors, then record
        this reader's blocking receive as ``svc.lease_wait``."""
        rec = self._prov
        if rec is None:
            return
        try:
            for entry in msg.get("prov") or ():
                blob, pid, wall_anchor, perf_anchor = entry
                rec.absorb_child(tuple(blob), pid, wall_anchor, perf_anchor)
            rec.add_item_span(epoch, ordinal, "svc.lease_wait", r0, r1)
            rec.note_delivery(epoch, ordinal, int(msg.get("rows") or 0))
        except Exception:  # noqa: BLE001 — provenance must never fail delivery
            from petastorm_tpu.obs.log import degradation

            degradation(
                "svc_prov_absorb_error",
                "trainer %r could not absorb cross-wire provenance for "
                "%d:%d; the item is delivered without attribution",
                self.trainer, epoch, ordinal)

    def _maybe_telemetry(self):
        """An export document for the next ``want`` frame when the telemetry
        cadence elapsed, else None (the credit grants that already flow are
        the trainer's only service-bound frames)."""
        if self._telemetry_s is None:
            return None
        now = time.monotonic()
        if now < self._telemetry_next:
            return None
        self._telemetry_next = now + self._telemetry_s
        try:
            from petastorm_tpu.obs.metrics import default_registry
            from petastorm_tpu.obs.timeseries import export_document

            reg = self._registry if self._registry is not None \
                else default_registry()
            reg.sample_timelines()
            return export_document(
                reg, extra={"source": "trainer:%s" % self.trainer})
        except Exception:  # noqa: BLE001 — telemetry must never fail a credit grant
            from petastorm_tpu.obs.log import degradation

            degradation("svc_trainer_telemetry_error",
                        "trainer %r could not export telemetry; the credit "
                        "grant ships without it", self.trainer)
            return None

    def next(self):
        return self.__next__()

    # -- checkpoint ---------------------------------------------------------------------

    def state_dict(self):
        """The consumed-work watermark — restoring it into a fresh
        ServiceReader (or this one) resumes exactly where this shard
        stopped, quarantined items charged exactly once."""
        return {
            "service": 1,
            "job": self.job,
            "consumed": {int(e): sorted(v)
                         for e, v in self._consumed.items()},
        }

    def load_state_dict(self, state):
        if state.get("service") != 1 or "consumed" not in state:
            raise ValueError(
                "not a ServiceReader state (keys: %s)" % sorted(state))
        if state.get("job") != self.job:
            raise ValueError(
                "checkpoint belongs to job %r; this reader is attached to "
                "%r — resuming would replay the wrong plan"
                % (state.get("job"), self.job))
        self.detach()
        self._consumed = {int(e): set(v)
                          for e, v in state["consumed"].items()}
        self.last_row_consumed = False
        self._attach()

    # -- loader duck surface ------------------------------------------------------------

    def set_trace(self, tracer):
        pass

    def set_provenance(self, recorder):
        """Wire the loader's recorder; pushed items then absorb their
        cross-wire spans (see the module docstring)."""
        self._prov = recorder

    def set_health(self, monitor):
        pass

    def reset(self):
        """Fresh pass over the full plan (clears the watermark)."""
        self.detach()
        self._consumed = {}
        self.last_row_consumed = False
        self._attach()

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self.detach()
        leases, self._arena_leases = self._arena_leases, []
        for lease in leases:
            lease.release()
        self._transport.close()

    def join(self):
        pass
