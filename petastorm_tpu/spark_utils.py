"""Spark RDD helper (reference: petastorm/spark_utils.py ~L30 ``dataset_as_rdd``).

Reads a petastorm(-tpu) dataset back into a Spark RDD of namedtuple rows. The per-piece
decode reuses the reader's own :class:`~petastorm_tpu.reader.PyDictWorker` (picklable —
the same property the process pool relies on), so executors run the identical
column-pruned read + codec decode path as ``make_reader``.

Works against any session object exposing ``sparkContext.parallelize`` (real pyspark, or
the fake-session contract fixtures — pyspark is not installed in this image)."""
from __future__ import annotations


def dataset_as_rdd(dataset_url, spark_session, schema_fields=None,
                   storage_options=None, filesystem=None):
    """Return an RDD of decoded namedtuple rows for the dataset at ``dataset_url``."""
    from petastorm_tpu.cache import NullCache
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    from petastorm_tpu.metadata import get_schema, load_row_groups
    from petastorm_tpu.reader import PyDictWorker

    fs, path = get_filesystem_and_path_or_paths(dataset_url, storage_options, filesystem)
    stored_schema = get_schema(fs, path)
    read_schema = (
        stored_schema.create_schema_view(schema_fields) if schema_fields else stored_schema
    )
    pieces = load_row_groups(fs, path)
    worker = PyDictWorker(fs, read_schema, stored_schema, None, None, NullCache(),
                          1, None, None)
    row_type = read_schema.make_namedtuple_type()
    field_names = list(read_schema.fields.keys())

    def piece_to_rows(piece):
        rows = worker((piece, 0))
        return [row_type(**{name: r.get(name) for name in field_names}) for r in rows]

    rdd = spark_session.sparkContext.parallelize(pieces, max(1, len(pieces)))
    return rdd.flatMap(piece_to_rows)
