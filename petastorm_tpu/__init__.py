"""petastorm_tpu — a TPU-native (JAX/XLA/Pallas) data-loading framework with the capabilities of
Petastorm: Parquet datasets with tensor columns (Unischema + codecs), a parallel row-group reader
(``make_reader`` / ``make_batch_reader``) with shuffling, sharding, predicates, NGram windowing
and caching, and a JAX ``DataLoader`` that yields globally-sharded ``jax.Array`` batches.

Public API mirrors the reference surface (see SURVEY.md §8 parity checklist) while the
implementation is TPU-first: deterministic multi-host planning over ``jax.process_index()``,
Arrow record-batch streaming, async ``device_put`` prefetch, Pallas decode kernels.
"""

__version__ = "0.2.0"

from petastorm_tpu.errors import (  # noqa: F401
    DecodeFieldError,
    EmptyResultError,
    MetadataError,
    NoDataAvailableError,
    PetastormTpuError,
    StallError,
    TimeoutWaitingForResultError,
    WorkerDiedError,
)
from petastorm_tpu.transform import TransformSpec, transform_schema  # noqa: F401
from petastorm_tpu.unischema import (  # noqa: F401
    Unischema,
    UnischemaField,
    dict_to_record,
    dict_to_spark_row,
    encode_row,
    insert_explicit_nulls,
    match_unischema_fields,
)


def __getattr__(name):
    # Heavier entry points are imported lazily so `import petastorm_tpu` stays light.
    try:
        if name in ("make_reader", "make_batch_reader", "Reader"):
            from petastorm_tpu import reader

            return getattr(reader, name)
        if name == "WeightedSamplingReader":
            from petastorm_tpu.weighted_sampling import WeightedSamplingReader

            return WeightedSamplingReader
        if name == "DataLoader":
            from petastorm_tpu.loader import DataLoader

            return DataLoader
        if name == "InMemDataLoader":
            from petastorm_tpu.loader import InMemDataLoader

            return InMemDataLoader
        if name == "RecoveryOptions":
            from petastorm_tpu.recovery import RecoveryOptions

            return RecoveryOptions
        if name in ("WatchOptions", "DatasetWatcher"):
            from petastorm_tpu.dataset import watch

            return getattr(watch, name)
        if name in ("FeaturePipeline", "Normalize", "Standardize", "Clip",
                    "Cast", "FillNull", "Bucketize", "HashField",
                    "VocabLookup", "FeatureCross"):
            from petastorm_tpu.ops import tabular

            return getattr(tabular, name)
        if name == "checkpoint":
            import importlib

            # importlib (not `from petastorm_tpu import checkpoint`): the from-import
            # re-enters this __getattr__ before the submodule lands in sys.modules
            return importlib.import_module("petastorm_tpu.checkpoint")
    except ImportError as e:
        raise AttributeError(
            "petastorm_tpu.%s is unavailable (%s)" % (name, e)
        ) from e
    raise AttributeError("module 'petastorm_tpu' has no attribute %r" % name)
