"""NGram windowing: sliding windows over timestamp-sorted rows within a row group.

Capability parity with petastorm/ngram.py ~L40 (``NGram``: ``fields`` dict offset→field-list,
``delta_threshold``, ``timestamp_field``, ``timestamp_overlap``; ``form_ngram``,
``get_field_names_at_timestep``, ``resolve_regex_field_names``): windowed consecutive-row
samples for sequence/video models, with a timestamp-delta validity constraint.

TPU delta: window validity is computed **vectorized** over the whole row group
(:func:`valid_window_starts` — one numpy pass instead of a per-window python loop), and the same
helper serves the batch path, which windows entire record batches by index-gather.
"""
from __future__ import annotations

import numpy as np

from petastorm_tpu.unischema import UnischemaField


class NGram:
    def __init__(self, fields, delta_threshold, timestamp_field, timestamp_overlap=True):
        """``fields``: {offset: [UnischemaField | name | regex]}; offsets must be consecutive
        integers. ``delta_threshold``: max timestamp delta between consecutive window rows.
        ``timestamp_field``: field (or name) rows are ordered by. ``timestamp_overlap=False``
        yields only windows whose timestamp spans do not overlap.
        """
        if not fields:
            raise ValueError("NGram fields must be a non-empty dict of offset -> field list")
        offsets = sorted(fields.keys())
        if offsets != list(range(offsets[0], offsets[-1] + 1)):
            raise ValueError("NGram offsets must be consecutive integers, got %r" % offsets)
        self._fields = {k: list(v) for k, v in fields.items()}
        self._delta_threshold = delta_threshold
        self._timestamp_field = timestamp_field
        self._timestamp_overlap = timestamp_overlap

    @property
    def fields(self):
        return self._fields

    @property
    def length(self):
        return max(self._fields) - min(self._fields) + 1

    @property
    def delta_threshold(self):
        return self._delta_threshold

    @property
    def timestamp_field_name(self):
        ts = self._timestamp_field
        return ts.name if isinstance(ts, UnischemaField) else ts

    @property
    def timestamp_overlap(self):
        return self._timestamp_overlap

    def resolve_regex_field_names(self, schema):
        """Expand name/regex entries in ``fields`` against a schema (reference API)."""
        from petastorm_tpu.unischema import match_unischema_fields

        resolved = {}
        for offset, entries in self._fields.items():
            out = []
            for entry in entries:
                if isinstance(entry, UnischemaField):
                    out.append(entry)
                else:
                    matched = match_unischema_fields(schema, [entry])
                    if not matched:
                        raise ValueError("NGram field selector %r matched nothing" % entry)
                    out.extend(matched)
            resolved[offset] = out
        self._fields = resolved
        return self

    def get_field_names_at_timestep(self, timestep):
        return [
            f.name if isinstance(f, UnischemaField) else f
            for f in self._fields.get(timestep, [])
        ]

    def get_all_field_names(self):
        names = []
        for offset in sorted(self._fields):
            for name in self.get_field_names_at_timestep(offset):
                if name not in names:
                    names.append(name)
        ts = self.timestamp_field_name
        if ts not in names:
            names.append(ts)
        return names

    def make_schema_view(self, schema):
        """Schema view covering every field any timestep needs + the timestamp field."""
        return schema.create_schema_view(self.get_all_field_names())

    # -- window math --------------------------------------------------------------------

    def form_ngram(self, data, schema):
        """``data``: list of decoded row dicts (one row group). Returns a list of
        {offset: row namedtuple} windows (reference ``form_ngram`` contract).
        """
        if len(data) < self.length:
            return []
        ts_name = self.timestamp_field_name
        timestamps = np.asarray([row[ts_name] for row in data])
        order = np.argsort(timestamps, kind="stable")
        sorted_rows = [data[i] for i in order]
        starts = valid_window_starts(
            timestamps[order], self.length, self._delta_threshold, self._timestamp_overlap
        )
        offsets = sorted(self._fields)
        # views depend only on the offset: build once, not per window (hot path)
        views = {
            offset: schema.create_schema_view(self.get_field_names_at_timestep(offset))
            for offset in offsets
        }
        ngrams = []
        for s in starts:
            window = {}
            for pos, offset in enumerate(offsets):
                row = sorted_rows[s + pos]
                view = views[offset]
                window[offset] = view.make_namedtuple(
                    **{name: row[name] for name in view.fields}
                )
            ngrams.append(window)
        return ngrams


def form_ngram_columns(columns, ngram):
    """Columnar windowing for the BATCH reader path: one row group's ``{name:
    column}`` → flat ``{'offset/name': column[order[starts + pos]]}`` window
    columns.

    TPU-first extension over the reference (whose NGram exists only on the
    per-row path, one python dict per window): window assembly is one argsort of
    the timestamps plus one fancy-index gather per (offset, field) — no
    per-window python at all — and the flat ``offset/field`` naming is exactly
    the device-column convention the JAX loader already delivers, so batches go
    straight to ``jax.Array`` columns. Row count of every output column is the
    window count (one row == one window).

    Windows never span row groups (reference semantics: ``form_ngram`` runs per
    row group). Returns ``{}`` when the group is shorter than the window or no
    window satisfies ``delta_threshold``.
    """
    ts_name = ngram.timestamp_field_name
    ts = columns.get(ts_name)
    if ts is None:
        raise ValueError(
            "NGram timestamp field %r is not among the read columns" % ts_name)
    ts = np.asarray(ts)
    if len(ts) < ngram.length:
        return {}
    order = np.argsort(ts, kind="stable")
    starts = valid_window_starts(ts[order], ngram.length, ngram.delta_threshold,
                                 ngram.timestamp_overlap)
    if len(starts) == 0:
        return {}
    offsets = sorted(ngram.fields)
    out = {}
    for pos, offset in enumerate(offsets):
        idx = order[starts + pos]
        for name in ngram.get_field_names_at_timestep(offset):
            col = columns.get(name)
            if col is None:
                # match the per-row path, which raises when a requested field is
                # absent — silently dropping 'offset/name' would lose a feature
                # column without any error (review r5)
                raise ValueError(
                    "NGram field %r (offset %d) is not among the batch columns "
                    "%s — was it removed by a transform_spec?"
                    % (name, offset, sorted(columns)))
            out["%d/%s" % (offset, name)] = col[idx]
    return out


def valid_window_starts(sorted_timestamps, length, delta_threshold, overlap=True):
    """Start indices of valid windows over sorted timestamps — vectorized.

    A window of ``length`` rows starting at i is valid iff every consecutive delta within it is
    <= ``delta_threshold``. With ``overlap=False``, greedily keep only windows whose row spans
    do not overlap previously kept windows.
    """
    n = len(sorted_timestamps)
    if n < length:
        return np.empty(0, dtype=np.int64)
    if length == 1:
        starts = np.arange(n)
    else:
        deltas = np.diff(np.asarray(sorted_timestamps))
        ok = (deltas <= delta_threshold).astype(np.int64)
        # window i valid iff ok[i:i+length-1] all 1 -> rolling sum == length-1
        csum = np.concatenate([[0], np.cumsum(ok)])
        win = csum[length - 1:] - csum[: n - length + 1]
        starts = np.nonzero(win == length - 1)[0]
    if overlap or len(starts) == 0:
        return starts
    kept = []
    next_free = -1
    for s in starts:
        if s > next_free:
            kept.append(s)
            next_free = s + length - 1
    return np.asarray(kept, dtype=np.int64)
