"""Row-group result caches.

Capability parity with petastorm/cache.py (``CacheBase``, ``NullCache`` ~L20) and
petastorm/local_disk_cache.py + petastorm/local_disk_arrow_table_cache.py (~L30): memoize
decoded row-group results on local disk keyed by (url, piece, predicate...).

The reference uses the ``diskcache`` package (not available here); ``LocalDiskCache`` below is a
small self-contained file cache: one file per key (sha256 name), pickle or Arrow IPC payloads,
LRU-by-mtime eviction against a size limit. Reader workers cache python/numpy payloads via
pickle; the Arrow IPC serializer serves direct users caching pyarrow Tables (memory-mapped,
zero-copy reads).
"""
from __future__ import annotations

import hashlib
import os
import pickle


class CacheBase:
    def get(self, key, fill_cache_func):
        """Return cached value for ``key``; on miss call ``fill_cache_func()``, store, return."""
        raise NotImplementedError

    def contains(self, key):
        """Cheap (possibly stale) membership probe — the readahead layer skips
        prefetching row groups the cache will serve anyway. ``False`` is always
        a safe answer."""
        return False

    def invalidate(self, key):
        """Drop the entry for ``key`` if present (ISSUE 11: a rewritten or
        removed source file's decoded payloads must not linger). A no-op
        default — caches that hold nothing have nothing to drop."""

    def cleanup(self):
        pass


class NullCache(CacheBase):
    """No caching: always calls the fill function (reference ~L20)."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()


class LocalDiskCache(CacheBase):
    """File-per-key local disk cache with LRU-by-mtime eviction.

    ``serializer``: 'pickle' (any python value) or 'arrow' (pyarrow.Table payloads, IPC format —
    the reference's LocalDiskArrowTableCache equivalent).
    """

    def __init__(self, path, size_limit_bytes=None, expected_row_size_bytes=None,
                 serializer="pickle", cleanup=False, **_ignored):
        self._path = path
        self._size_limit = size_limit_bytes
        self._serializer = serializer
        self._cleanup_on_exit = cleanup
        os.makedirs(path, exist_ok=True)

    def _key_path(self, key):
        digest = hashlib.sha256(str(key).encode("utf-8")).hexdigest()
        ext = "arrow" if self._serializer == "arrow" else "pkl"
        return os.path.join(self._path, "%s.%s" % (digest, ext))

    def contains(self, key):
        return os.path.exists(self._key_path(key))

    def invalidate(self, key):
        """Unlink the entry for ``key`` (keyed invalidation, ISSUE 11).

        The cache has no wholesale validation of its own — entries are only as
        fresh as their keys. With dataset watching on, the reader embeds each
        piece's generation token (size+mtime+footer-crc) in the cache key, so
        a rewritten source file — even one colliding on size AND mtime — maps
        to a NEW key and can never serve the old generation's decoded
        payloads; this method lets the watcher reclaim the orphaned old-token
        entries the moment the rewrite is detected."""
        try:
            os.unlink(self._key_path(key))
        except OSError:
            pass  # absent (or concurrently evicted) is the goal state

    def get(self, key, fill_cache_func):
        from petastorm_tpu.obs.log import degradation

        fpath = self._key_path(key)
        if os.path.exists(fpath):
            try:
                value = self._read(fpath)
                try:  # touch for LRU; a concurrent evictor may have unlinked it
                    os.utime(fpath)
                except OSError:
                    pass
                return value
            except Exception as e:  # noqa: BLE001 - corrupt/vanished entry: refill
                degradation(
                    "disk_cache",
                    "disk cache read failed for %s (%s); refilling from source",
                    fpath, e)
                try:  # another process sharing the cache dir may have unlinked it already
                    os.unlink(fpath)
                except OSError:
                    pass
        value = fill_cache_func()
        try:
            self._write(fpath, value)
        except Exception as e:  # noqa: BLE001 — a full/readonly disk must not fail the read
            degradation(
                "disk_cache",
                "disk cache write failed for %s (%s); serving uncached", fpath, e)
            return value
        if self._size_limit:
            self._evict()
        return value

    def _read(self, fpath):
        if self._serializer == "arrow":
            import pyarrow as pa

            with pa.memory_map(fpath) as source:
                return pa.ipc.open_file(source).read_all()
        with open(fpath, "rb") as f:
            return pickle.load(f)

    def _write(self, fpath, value):
        # tmp name must be unique per WRITER, not per process: two pool threads
        # filling the same key concurrently would interleave writes into a shared
        # tmp file and the loser's os.replace would raise FileNotFoundError after
        # the winner moved it (caught by tests/test_stress.py concurrent readers)
        import uuid

        tmp = "%s.tmp.%s" % (fpath, uuid.uuid4().hex)
        try:
            if self._serializer == "arrow":
                import pyarrow as pa

                with pa.OSFile(tmp, "wb") as sink:
                    with pa.ipc.new_file(sink, value.schema) as writer:
                        writer.write_table(value)
            else:
                with open(tmp, "wb") as f:
                    pickle.dump(value, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, fpath)
        except BaseException:
            try:  # don't orphan a half-written tmp for the grace-period sweep
                os.unlink(tmp)
            except OSError:
                pass
            raise

    #: tmp files older than this are considered orphans of a crashed writer and are
    #: reclaimed by eviction; younger ones are in-flight (unlinking those would make
    #: the writer's os.replace fail)
    TMP_ORPHAN_GRACE_S = 300

    def _evict(self):
        import time

        entries = []
        total = 0
        now = time.time()
        for name in os.listdir(self._path):
            fpath = os.path.join(self._path, name)
            try:
                st = os.stat(fpath)
            except OSError:
                continue
            if ".tmp." in name:
                if now - st.st_mtime > self.TMP_ORPHAN_GRACE_S:
                    try:  # orphan of a SIGKILLed writer: reclaim the space
                        os.unlink(fpath)
                    except OSError:
                        pass
                continue  # in-flight writer: never unlink, never count
            entries.append((st.st_mtime, st.st_size, fpath))
            total += st.st_size
        entries.sort()
        for _, size, fpath in entries:
            if total <= self._size_limit:
                break
            try:
                os.unlink(fpath)
                total -= size
            except OSError:
                pass

    def cleanup(self):
        # ignore_errors covers concurrent removal too: two readers sharing one
        # cache dir may both clean up at exit, and files vanishing between the
        # tree walk and the unlink must not raise
        if self._cleanup_on_exit:
            import shutil

            shutil.rmtree(self._path, ignore_errors=True)


def make_cache(cache_type, cache_location=None, cache_size_limit=None,
               cache_row_size_estimate=None, cache_extra_settings=None):
    """Factory matching the reference's ``cache_type`` reader kwargs ('null'|'local-disk').

    Reader workers cache python/numpy payloads, so the pickle serializer is used; the 'arrow'
    serializer remains available to direct :class:`LocalDiskCache` users holding pyarrow Tables.
    """
    if cache_type in (None, "null"):
        return NullCache()
    if cache_type == "local-disk":
        if not cache_location:
            raise ValueError("cache_type='local-disk' requires cache_location")
        return LocalDiskCache(
            cache_location,
            size_limit_bytes=cache_size_limit,
            expected_row_size_bytes=cache_row_size_estimate,
            **(cache_extra_settings or {}),
        )
    raise ValueError("Unknown cache_type %r (expected 'null' or 'local-disk')" % cache_type)
