"""Worker executors: pull row-group work items from a plan, run a worker, stream results.

Functional parity with the reference worker-pool layer (petastorm/workers_pool/: ``ThreadPool``
thread_pool.py ~L60, ``ProcessPool`` process_pool.py ~L60 + ZeroMQ sockets, ``DummyPool``
dummy_pool.py ~L30, ``ConcurrentVentilator`` ventilator.py ~L60), redesigned per SURVEY.md §3.2:

- No ZeroMQ and no ventilator thread. Backpressure is a bounded results queue; the "ventilator"
  is the (possibly infinite, resumable) :class:`petastorm_tpu.plan.EpochPlan` pulled lazily
  through a :class:`PullDispatcher` — bounded per-worker claims (the readahead layer's
  lookahead window, ISSUE 4) with work stealing when the plan runs dry. Threads are the
  default pool — Arrow IO and cv2 decode release the GIL, and the
  heavy decode moves on-device anyway (Pallas), so forked processes buy little and cost pickling.
- ``ProcessPoolExecutor`` is kept for CPU-hungry user ``TransformSpec`` functions: workers are
  initialized once per child (no per-task worker pickling) and in-flight tasks are capped for
  backpressure, mirroring the reference's ``max_ventilation_queue_size``.

Contract: ``executor.start(worker, plan)`` then iterate ``executor.results()``; worker is a
picklable callable ``worker(item) -> result``; exceptions in workers propagate to the consumer;
``stop()``/``join()`` mirror the reference pool API.
"""
from __future__ import annotations

import logging
import queue
import threading
from collections import deque

from petastorm_tpu import chaos as _chaos
from petastorm_tpu.obs import provenance as _prov
from petastorm_tpu.errors import TimeoutWaitingForResultError, WorkerDiedError
from petastorm_tpu.recovery import QuarantinedItem, RecoveryOptions

logger = logging.getLogger(__name__)

_DONE = object()


_steal_counter = None


def _count_steal():
    """Bump ``ptpu_io_steals_total`` (resolved once per process)."""
    global _steal_counter
    counter = _steal_counter
    if counter is None:
        from petastorm_tpu.obs.metrics import default_registry

        counter = _steal_counter = default_registry().counter(
            "ptpu_io_steals_total",
            help="claimed plan items taken from a busy worker by an idle one")
    counter.inc()


class PullDispatcher:
    """Pull-based piece dispatch over a shared plan: bounded per-worker claims
    plus work stealing (ISSUE 4).

    Each worker claims up to ``1 + lookahead`` upcoming plan items into its own
    deque — the lookahead is what the readahead layer prefetches, so the items a
    worker announces as "next" really are the ones it will process. When the
    plan runs dry an idle worker steals from the TAIL of the longest peer claim
    (the piece its owner would reach last), so a worker stuck on one slow piece
    no longer strands the rest of its claim behind it. With ``lookahead=0`` and
    no stealing this degenerates to exactly the old shared ``next(plan_iter)``
    under a lock.

    Plan order is preserved at dispatch: claims are filled strictly in plan
    order and consumed FIFO; only completion order can differ (it always could
    — workers finish out of order), which the Reader's consumed-ordinal
    bookkeeping and the loader's checkpoint watermark already handle.
    """

    def __init__(self, plan, workers_count, lookahead=0, stealing=True,
                 recorder=None):
        self._iter = iter(plan)
        self._lock = threading.Lock()
        self._claims = [deque() for _ in range(max(1, workers_count))]
        self._exhausted = False
        self._lookahead = max(0, int(lookahead))
        self._stealing = bool(stealing)
        #: items handed back by a retiring worker (live fleet shrink, ISSUE
        #: 13): refilled into claims BEFORE the plan iterator — they were
        #: claimed earlier in plan order than anything still unclaimed
        self._returned = deque()
        #: optional petastorm_tpu.obs.flight.FlightRecorder — steal decisions
        #: ride in the health layer's event ring (None = no recording)
        self._recorder = recorder
        self.steals = 0

    def next(self, worker_idx):
        """Claim the next item for ``worker_idx``: ``(item, upcoming)`` where
        ``upcoming`` is the rest of this worker's claim (the prefetch hint), or
        ``None`` when no work is left anywhere."""
        with self._lock:
            claim = self._claims[worker_idx]
            self._fill(claim, 1 + self._lookahead)
            if not claim and self._stealing:
                victim = max((c for c in self._claims if c), key=len, default=None)
                if victim is not None:
                    claim.append(victim.pop())  # tail: the victim's furthest item
                    self.steals += 1
                    _count_steal()
                    if self._recorder is not None:
                        self._recorder.record("steal", thief=worker_idx,
                                              victim_len=len(victim))
            if not claim:
                return None
            item = claim.popleft()  # the fill above keeps the hint window full
            return item, tuple(claim)

    def set_recorder(self, recorder):
        """Attach/replace the flight recorder mid-stream (the usual order:
        the executor starts during ``Reader.__init__``, the health monitor
        arrives later via ``DataLoader`` → ``reader.set_health``)."""
        with self._lock:
            self._recorder = recorder

    def set_lookahead(self, lookahead):
        """Retune the per-worker claim window live (ISSUE 13): the claim IS
        the readahead hint window, so a controller growing the prefetch depth
        must widen the hints with it or the deeper pool never sees more than
        the old window's worth of upcoming items."""
        with self._lock:
            self._lookahead = max(0, int(lookahead))

    def ensure_workers(self, workers_count):
        """Grow the claim table to at least ``workers_count`` slots (live
        fleet grow — ISSUE 13). Never shrinks: a retiring worker's slot stays
        (empty) so surviving indices keep their claims."""
        with self._lock:
            while len(self._claims) < workers_count:
                self._claims.append(deque())

    def withdraw(self, worker_idx):
        """Return ``worker_idx``'s unprocessed claim to the pool (live fleet
        shrink): the items refill other workers' claims before the plan
        iterator, so a drained worker loses no work and duplicates none.
        Returns the number of items handed back."""
        with self._lock:
            claim = self._claims[worker_idx]
            n = len(claim)
            self._returned.extend(claim)
            claim.clear()
        return n

    def return_items(self, items):
        """Hand specific already-dispatched items back to the pool (the data
        service's wire-lease requeue seam, ISSUE 19): a dead link's un-acked
        lease, a transiently failed decode, or a re-attached trainer's
        evicted payload re-enters dispatch ahead of the plan iterator — the
        same no-loss/no-duplicate discipline as :meth:`withdraw`, for items
        that had already left their claim deque."""
        with self._lock:
            self._returned.extend(items)
        return len(items)

    def has_work(self):
        """Is anything left to dispatch — handed-back items, claimed items,
        or an unexhausted plan? The executors' last-worker exit gate: a
        retiring worker may hand its claim back AFTER the surviving peers
        already saw an empty dispatcher and exited, and posting the
        end-of-stream marker over those stranded items would silently drop
        rows (the resize contract is byte-identical delivery)."""
        with self._lock:
            return bool(self._returned) or not self._exhausted \
                or any(self._claims)

    def _fill(self, claim, target):
        # caller MUST hold self._lock (all call sites do — the analyzer
        # cannot see cross-method lock ownership)
        while len(claim) < target:
            if self._returned:
                claim.append(self._returned.popleft())  # graftlint: disable=GL-C001
                continue
            if self._exhausted:
                break
            try:
                claim.append(next(self._iter))
            except StopIteration:
                self._exhausted = True  # graftlint: disable=GL-C001 (caller holds self._lock)

    def stats(self):
        return {"steals": self.steals}


class _ExcResult:
    def __init__(self, exc):
        self.exc = exc


def _iter_results(results_q, stop_event, timeout, stop_fn, on_truncated=None):
    """Shared results-drain loop for the threaded/process pools.

    Ends on the ``_DONE`` marker, re-raises worker exceptions (stopping the pool
    first), raises :class:`TimeoutWaitingForResultError` when nothing arrives
    within ``timeout`` — and returns PROMPTLY once ``stop_event`` is set and the
    queue is empty. The prompt return matters: ``stop()`` drains the results queue
    (including a ``_DONE`` already posted), so a consumer on ANOTHER thread that
    was blocked in ``get()`` at stop time — e.g. a tf.data generator thread being
    finalized while the main thread tears the reader down — used to sleep out the
    full ``results_timeout_s`` (the flaky exactly-300.07s ``test_tf_tensors_eager``
    hang, VERDICT r4 #7).

    The stop-event return is a TRUNCATION, not exhaustion: ``on_truncated`` fires
    on that branch (and only that branch) so the executor can mark the stream as
    aborted — ``Reader.__next__`` must not flag ``last_row_consumed`` when the
    stream ended because somebody called ``stop()`` mid-pass (ADVICE r5)."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        try:
            value = results_q.get(timeout=0.2)
        except queue.Empty:
            if stop_event.is_set():
                if on_truncated is not None:
                    on_truncated()
                return  # stopped: the stream is over for this consumer
            if time.monotonic() > deadline:
                raise TimeoutWaitingForResultError(
                    "No worker result within %.0fs" % timeout
                ) from None
            continue
        if value is _DONE:
            # a _DONE that lands AFTER stop() is ambiguous: the stop-drain may
            # have discarded results ahead of it, so the stream cannot be called
            # fully consumed (the marker races the drain — workers re-post it)
            if stop_event.is_set() and on_truncated is not None:
                on_truncated()
            return
        if isinstance(value, _ExcResult):
            stop_fn()
            raise value.exc
        yield value
        # fresh budget per consumer request (matching the old per-get semantics):
        # time the CONSUMER spent between next() calls must not count against the
        # worker-result timeout
        deadline = time.monotonic() + timeout


class ExecutorBase:
    #: True when the result stream ended because ``stop()`` aborted it mid-pass
    #: rather than because the plan was exhausted (consumers use it to keep
    #: completion flags like ``Reader.last_row_consumed`` truthful)
    truncated = False

    #: optional petastorm_tpu.obs.health.HealthMonitor (ISSUE 5): worker
    #: threads / pool drivers register heartbeats and per-worker latency on it
    #: (None = disabled, one is-None check per loop iteration)
    _health = None

    #: optional petastorm_tpu.obs.provenance.ProvenanceRecorder (ISSUE 10):
    #: pool drivers record per-item wire spans and merge child-piggybacked
    #: item spans onto it (thread/dummy pools need no executor-side state —
    #: their worker threads feed the armed module-level collector directly)
    _prov = None

    def set_provenance(self, recorder):
        """Attach a provenance recorder (the Reader wires this; attachable
        mid-stream like ``set_health`` — drivers resolve it per item)."""
        self._prov = recorder

    def set_health(self, monitor):
        """Attach a :class:`petastorm_tpu.obs.health.HealthMonitor`: workers
        heartbeat per work item (busy vs backpressure-wait states), the
        dispatcher records steal events, and — on the process pool — children
        gain the stack-dump hook the stall watchdog collects evidence through.
        Attachable mid-stream: workers pick it up at their next loop pass, and
        an already-running dispatcher (the executor starts in
        ``Reader.__init__``, before the loader can attach health) is rewired
        to the monitor's flight ring here."""
        self._health = monitor
        dispatch = getattr(self, "_dispatch", None)
        if dispatch is not None:
            dispatch.set_recorder(monitor.flight if monitor is not None
                                  else None)

    def start(self, worker, plan):
        raise NotImplementedError

    def results(self):
        """Generator of worker results; raises worker exceptions; ends when plan exhausted."""
        raise NotImplementedError

    def _mark_truncated(self):
        self.truncated = True

    def _drain_results(self):
        """Shared ``results()`` body for the queue-backed pools (thread/process):
        one copy of the drain/timeout/truncation wiring."""
        return _iter_results(self._results, self._stop_event, self._timeout,
                             self.stop, on_truncated=self._mark_truncated)

    def stop(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()


class SyncExecutor(ExecutorBase):
    """Synchronous in-process execution (reference DummyPool): deterministic, for tests/debug.

    Readahead still applies (``lookahead > 0`` and a worker with ``prefetch``):
    the upcoming plan items come from ``plan.peek`` — the single consumer keeps
    its deterministic order while the IO pool reads ahead of it."""

    def __init__(self, lookahead=0, recovery=None, **_ignored):
        self._worker = None
        self._plan = None
        self._stopped = False
        self._lookahead = max(0, int(lookahead))
        self._recovery = RecoveryOptions.normalize(recovery)

    def set_lookahead(self, lookahead):
        """Live lookahead retune (ISSUE 13): ``results()`` reads the value
        per item, so the next iteration peeks the new window."""
        self._lookahead = max(0, int(lookahead))

    def start(self, worker, plan):
        self._worker = worker
        self._plan = plan
        self.truncated = False

    def results(self):
        prefetch = getattr(self._worker, "prefetch", None)
        peek = getattr(self._plan, "peek", None)
        recovery = self._recovery
        for item in self._plan:
            if self._stopped:
                self.truncated = True
                return
            if prefetch is not None and peek is not None and self._lookahead:
                upcoming = peek(self._lookahead)
                if upcoming:
                    prefetch(upcoming)
            attempts = 0
            result = None
            if _prov.ACTIVE is not None:
                _prov.begin_item(item)
            # end_item runs BEFORE the yield below: a generator suspends at
            # yield, and holding the item context open across the consumer's
            # turn would misattribute its spans to this item
            try:
                while True:
                    try:
                        if _chaos.ACTIVE is not None:
                            _chaos.ACTIVE.hit("worker.item",
                                              key=_chaos.item_key(item))
                        result = self._worker(item)
                    except Exception as e:  # noqa: BLE001 — policy-classified
                        attempts += 1
                        if not recovery.quarantine:
                            raise
                        if attempts >= recovery.poison_attempts:
                            result = QuarantinedItem(item, e, attempts)
                            break
                        continue  # retry the item in place
                    break
            finally:
                if _prov.ACTIVE is not None:
                    _prov.end_item()
            yield result

    def stop(self):
        self._stopped = True


class ThreadExecutor(ExecutorBase):
    """N threads pulling work items from the shared plan through a
    :class:`PullDispatcher` (bounded claims + work stealing); bounded results
    queue = backpressure."""

    def __init__(self, workers_count=4, results_queue_size=16, results_timeout_s=300.0,
                 lookahead=0, work_stealing=True, recovery=None, **_ignored):
        self._workers_count = workers_count
        self._queue_size = results_queue_size
        self._timeout = results_timeout_s
        self._lookahead = lookahead
        self._stealing = work_stealing
        self._recovery = RecoveryOptions.normalize(recovery)
        self._threads = []
        self._results = None
        self._stop_event = threading.Event()
        self._dispatch = None
        self._active = 0
        self._active_lock = threading.Lock()
        # live fleet-resize state (ISSUE 13), all under _active_lock
        self._target = workers_count   # intended fleet size
        self._retire = 0               # workers asked to drain and exit
        self._next_idx = workers_count
        self._worker_obj = None

    def start(self, worker, plan):
        self._results = queue.Queue(maxsize=self._queue_size)
        self._stop_event.clear()
        self.truncated = False
        self._worker_obj = worker
        monitor = self._health
        self._dispatch = PullDispatcher(
            plan, self._workers_count, lookahead=self._lookahead,
            stealing=self._stealing,
            recorder=monitor.flight if monitor is not None else None)
        with self._active_lock:
            self._active = self._workers_count
            self._target = self._workers_count
            self._retire = 0
            self._next_idx = self._workers_count
        for i in range(self._workers_count):
            t = threading.Thread(
                target=self._run_worker, args=(worker, self._dispatch, i),
                daemon=True, name="ptpu-worker-%d" % i,
            )
            t.start()
            self._threads.append(t)

    def _should_retire(self):
        """Claim one pending retirement (live shrink): checked by workers
        BETWEEN items only — a shrink drains, it never kills mid-item."""
        with self._active_lock:
            if self._retire > 0:
                self._retire -= 1
                return True
            return False

    def set_lookahead(self, lookahead):
        """Live dispatch-lookahead retune (rides with the readahead-depth
        knob — see :meth:`PullDispatcher.set_lookahead`)."""
        self._lookahead = max(0, int(lookahead))
        dispatch = self._dispatch
        if dispatch is not None:
            dispatch.set_lookahead(self._lookahead)

    @property
    def alive_workers(self):
        """Workers currently running (retiring ones still count until they
        drain out). Lock-free read: collectors poll this from other
        threads, and an int read is atomic."""
        return self._active

    @property
    def target_workers(self):
        return self._target

    def resize(self, workers_count):
        """Grow/shrink the worker fleet LIVE (ISSUE 13). Grow spawns fresh
        worker threads against the running dispatcher; shrink queues
        retirements that draining workers pick up between items — their
        unprocessed claims return to the dispatcher, so the delivered row
        set (and the consumed-ordinal watermark) is byte-identical to an
        un-resized run. A no-op once the stream has finished. Returns the
        applied target."""
        n = max(1, int(workers_count))
        dispatch = self._dispatch
        to_start = []
        with self._active_lock:
            if dispatch is None or self._active == 0:
                return self._target  # not started / already drained
            if n > self._target:
                grow = n - self._target
                cancelled = min(grow, self._retire)
                self._retire -= cancelled  # un-retire before spawning anew
                for _ in range(grow - cancelled):
                    to_start.append(self._next_idx)
                    self._next_idx += 1
                self._active += len(to_start)
            elif n < self._target:
                self._retire += self._target - n
            self._target = n
            next_idx = self._next_idx
        if to_start:
            dispatch.ensure_workers(next_idx)
            for idx in to_start:
                t = threading.Thread(
                    target=self._run_worker,
                    args=(self._worker_obj, dispatch, idx),
                    daemon=True, name="ptpu-worker-%d" % idx)
                t.start()
                self._threads.append(t)
        return n

    def _run_worker(self, worker, dispatch, idx):
        import time

        prefetch = getattr(worker, "prefetch", None)
        hb = None
        worker_fatal = False  # a fatal exit must never trigger the rescue gate
        try:
            while not self._stop_event.is_set():
                if self._should_retire():
                    # live shrink: hand the unprocessed claim back (others
                    # pick it up before the plan iterator) and drain out
                    dispatch.withdraw(idx)
                    break
                # health is resolved per pass, so a monitor attached after
                # start() (the loader wires the reader post-construction)
                # still instruments the rest of the stream
                monitor = self._health
                if monitor is not None and hb is None:
                    hb = monitor.register("worker.thread-%d" % idx, "worker")
                if hb is not None:
                    hb.wait("claim")  # an exhausted plan is idleness, not a stall
                claim = dispatch.next(idx)
                if claim is None:
                    break
                item, upcoming = claim
                if prefetch is not None and upcoming:
                    prefetch(upcoming)  # swallows its own failures (degradation-logged)
                if hb is not None:
                    hb.beat("working")
                t0 = time.perf_counter() if monitor is not None else 0.0
                recovery = self._recovery
                attempts = 0
                fatal = False
                result = None
                if _prov.ACTIVE is not None:
                    _prov.begin_item(item)
                try:
                    while True:  # item attempts (poison-quarantine policy)
                        try:
                            if _chaos.ACTIVE is not None:
                                _chaos.ACTIVE.hit("worker.item",
                                                  key=_chaos.item_key(item))
                            result = worker(item)
                        except Exception as e:  # noqa: BLE001 — classified
                            attempts += 1
                            if not recovery.quarantine:
                                self._put(_ExcResult(e))  # to the consumer
                                fatal = True
                                break
                            if attempts >= recovery.poison_attempts:
                                result = QuarantinedItem(item, e, attempts)
                                break
                            continue  # retry the item in place
                        break
                finally:
                    if _prov.ACTIVE is not None:
                        _prov.end_item()
                if fatal:
                    worker_fatal = True
                    break
                if monitor is not None:
                    # per-worker latency histogram: the straggler detector's input
                    monitor.observe_worker(idx, time.perf_counter() - t0)
                if hb is not None:
                    hb.wait("results_put")  # a full results queue = backpressure
                self._put(result)
        finally:
            if hb is not None:
                hb.done()
            self._retire_worker(worker, dispatch, worker_fatal)

    def _retire_worker(self, worker, dispatch, fatal):
        """One worker's exit gate: decrement the fleet count and post the
        end-of-stream marker when this was the LAST worker — unless the
        dispatcher still holds work. That happens in exactly one (rare) race:
        a retiring worker hands its claim back AFTER the surviving peers
        already saw an empty dispatcher and exited; the last decrementer is
        the only actor that observes the strand atomically (the withdraw
        precedes its decrement in program order), so it spawns a rescue
        worker instead of declaring the stream complete over undelivered
        rows."""
        rescue_idx = None
        with self._active_lock:
            self._active -= 1
            if self._active == 0 and not fatal \
                    and not self._stop_event.is_set() and dispatch.has_work():
                self._active += 1  # the rescue worker's slot, reserved now
                rescue_idx = self._next_idx
                self._next_idx += 1
            last = self._active == 0
        if rescue_idx is not None:
            try:
                dispatch.ensure_workers(rescue_idx + 1)
                t = threading.Thread(
                    target=self._run_worker, args=(worker, dispatch,
                                                   rescue_idx),
                    daemon=True, name="ptpu-worker-%d" % rescue_idx)
                t.start()
                self._threads.append(t)
                return
            except Exception as e:  # noqa: BLE001 — degrade to stream end
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "ctl_rescue_failed",
                    "stranded-claim rescue worker could not start (%s); the "
                    "handed-back items are LOST for this pass", e, once=False)
                with self._active_lock:
                    self._active -= 1
                    last = self._active == 0
        if last:
            # OUTSIDE the lock: _put blocks on a full results queue, and
            # a blocked holder would deadlock any reader of the fleet
            # gauges (the controller's collector) on the consumer thread
            self._put(_DONE)

    def dispatch_stats(self):
        """Work-stealing gauges for ``Reader.io_stats()``."""
        dispatch = self._dispatch
        return dispatch.stats() if dispatch is not None else {}

    def _put(self, value):
        # Even the _DONE marker yields to a SET stop event: the consumer is the one
        # who sets it, and it never reads results afterwards — spinning until the
        # full queue drains would park the last worker for join()'s whole timeout
        # (results_timeout_s) on every stop-mid-stream teardown.
        while True:
            try:
                self._results.put(value, timeout=0.1)
                return
            except queue.Full:
                if self._stop_event.is_set():
                    return

    def results(self):
        return self._drain_results()

    def stop(self):
        self._stop_event.set()
        # drain so blocked workers can exit
        try:
            while True:
                self._results.get_nowait()
        except (queue.Empty, AttributeError):
            pass

    def join(self):
        import time

        deadline = time.monotonic() + self._timeout  # shared across threads, not per-thread
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "thread_join_timeout",
                    "Worker thread %s still alive after %.0fs join (blocked in IO?); "
                    "it will exit at its next stop-event check without publishing",
                    t.name, self._timeout, once=False,
                )
        self._threads = []


# -- process pool ----------------------------------------------------------------------


class ProcessExecutor(ExecutorBase):
    """Multiprocess execution for CPU-bound workers (GIL-holding user transforms).

    Children are CLEAN interpreters started via ``python -m petastorm_tpu._child_worker``
    (reference design: exec_in_new_process + zmq, process_pool.py ~L60): no re-import of the
    user's ``__main__`` (multiprocessing spawn/forkserver fork-bombs unguarded scripts) and no
    fork of a threaded parent (JAX deadlock hazard). The worker is pickled once per child;
    per-task traffic is (item, result) over a unix socket. One driver thread per child gives
    one-item-in-flight-per-child backpressure plus the bounded results queue.

    With a ``serializer`` from the ``shm`` family the result payloads do NOT ride the
    socket: ``start()`` creates a :class:`petastorm_tpu.parallel.shm_ring.SlabRing`,
    each driver thread acquires a slab and grants it to its child together with the
    work item, and the child writes the serialized frames straight into the slab —
    only a small descriptor crosses the socket. Items whose payload exceeds the slab
    (or that find the ring momentarily empty) fall back to the socket wire per item;
    platforms without working shared memory degrade the whole pool to the socket
    wire with a warn-once. ``join()`` unlinks every slab — a pool can never leak
    ``/dev/shm`` segments, whatever its children did (SIGKILL mid-write included).
    """

    def __init__(self, workers_count=4, results_queue_size=16, results_timeout_s=300.0,
                 serializer="pickle", worker_respawns=None, shm_slab_bytes=None,
                 shm_slabs=None, lookahead=0, work_stealing=True, recovery=None,
                 transport=None, **_ignored):
        import os

        from petastorm_tpu.transport import normalize_transport

        self._workers_count = workers_count
        self._queue_size = results_queue_size
        self._timeout = results_timeout_s
        self._lookahead = lookahead
        self._stealing = work_stealing
        self._dispatch = None
        self._serializer_name = serializer
        from petastorm_tpu.serializers import make_serializer

        self._serializer = make_serializer(serializer)
        #: shm wire config (ignored for socket serializers): slab size defaults to
        #: 32 MB — comfortably a decoded row-group batch; oversized payloads fall
        #: back per item. PTPU_SHM_SLAB_BYTES tunes it through the reader factories
        #: without new kwargs at every layer.
        self._shm_slab_bytes = int(shm_slab_bytes
                                   or os.environ.get("PTPU_SHM_SLAB_BYTES", 0)
                                   or (32 << 20))
        self._shm_slabs = shm_slabs
        self._ring = None
        self._shm_unavailable = False
        self._tracer = None
        #: pool wire transport (ISSUE 15): 'pipe' (the default — today's unix
        #: socket, byte-identical) or 'tcp' (framed crc-trailered loopback/LAN
        #: sockets with reconnect + heartbeats; also via PTPU_TRANSPORT). The
        #: tcp hub and the shared authkey/token live for the pool's lifetime;
        #: a tcp setup failure degrades the pool back to 'pipe'.
        self._transport_name = normalize_transport(transport)
        self._hub = None
        self._tenant_label = None
        self._authkey = None
        self._session_counter = 0
        self._procs = []
        self._conns = []
        self._threads = []
        self._results = None
        self._stop_event = threading.Event()
        self._active = 0
        self._active_lock = threading.Lock()
        # live fleet-resize state (ISSUE 13), all under _active_lock
        self._target = workers_count
        self._retire = 0
        self._next_idx = workers_count
        self._tmpdir = None
        #: Elastic recovery (no reference analog — SURVEY §6: a worker death kills the
        #: read there): a child that dies mid-item is replaced by a fresh clean
        #: interpreter and the in-flight item re-dispatched, up to this many times per
        #: pool lifetime. 0 restores fail-fast. Bounded so a crash loop still
        #: surfaces; a single poison item (one that reliably kills children,
        #: e.g. OOM) can additionally be SKIPPED instead of burning the budget
        #: via ``RecoveryOptions(on_poison="quarantine")`` (ISSUE 7) — after
        #: ``poison_attempts`` failures of one plan item the pool emits a
        #: :class:`~petastorm_tpu.recovery.QuarantinedItem` marker, respawns
        #: the child WITHOUT charging the budget (the item will not be retried,
        #: so no crash-loop risk), and moves on.
        self._recovery = RecoveryOptions.normalize(recovery)
        self._respawn_budget = int(worker_respawns) if worker_respawns is not None \
            else self._recovery.worker_respawns
        self._respawn_lock = threading.Lock()
        self._spawn_counter = 0
        self._worker = None
        self._child_env = None
        #: driver idx -> live child Popen (maintained across respawns, under
        #: the respawn lock): the stall healer's kill target, and how a dead
        #: child's evidence is tied to the driver that owned it
        self._child_by_idx = {}
        #: driver idx -> failures of the CURRENT in-flight item so far — lets
        #: the healer predict whether a kill can be absorbed (quarantine
        #: threshold reached) before it pulls the trigger
        self._inflight_attempts = {}
        self._healer_handle = None
        #: health wiring (ISSUE 5): handle of the child-stack provider this
        #: pool registered, plus the exact monitor/scope it was registered ON
        #: (handles are per-monitor sequence numbers — removing with a handle
        #: issued by a DIFFERENT monitor could delete an unrelated provider)
        self._stack_provider_handle = None
        self._stack_provider_monitor = None
        #: idle children ping the control pipe at this cadence so a live-but-
        #: unemployed child is distinguishable from a dead one in the evidence
        #: (pings are drained by the driver before every result header)
        self._ping_interval_s = float(
            os.environ.get("PTPU_CHILD_PING_S", "") or 5.0)
        #: live cross-process knob actuation (ISSUE 14 satellite): pending
        #: control-frame payload + a version stamp per driver; drivers send
        #: the frame on the pool wire (beside the slab-grant protocol) before
        #: their next item dispatch, children apply and ack. All under
        #: _ctl_lock — broadcast_io_knobs() is called from the controller
        #: thread while drivers read concurrently.
        self._ctl_lock = threading.Lock()
        self._ctl_pending = {}
        self._ctl_version = 0
        self._ctl_seen = {}   # driver idx -> version last sent to its child
        self._ctl_acks = {}   # driver idx -> {knob: applied value}

    def start(self, worker, plan):
        import os
        import tempfile

        self._results = queue.Queue(maxsize=self._queue_size)
        self._stop_event.clear()
        self.truncated = False
        authkey = self._authkey = os.urandom(32)
        if self._transport_name == "tcp":
            self._setup_hub(authkey)  # degrades self._transport_name on failure
        self._setup_shm()
        with self._respawn_lock:
            self._tmpdir = tempfile.mkdtemp(prefix="ptpu-pool-")
        # children must find petastorm_tpu BEFORE the bootstrap handshake can hand them
        # the parent's sys.path — put the package root on PYTHONPATH explicitly (the
        # parent may have found it via sys.path.insert, which does not propagate)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child_pp = os.environ.get("PYTHONPATH", "")
        child_pp = pkg_root + ((os.pathsep + child_pp) if child_pp else "")
        self._worker = worker  # respawned replacements re-handshake the same worker
        self._child_env = {**os.environ, "PYTHONPATH": child_pp,
                           "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")}
        # host-wide cache arena handoff (ISSUE 17): children attach the
        # parent's mapped warm set at bootstrap. On _child_env — the SAME env
        # every respawn/resize spawn reuses (_popen_child) — so a replacement
        # child's first read of a warm piece is served from the arena, not a
        # cold store refill (the respawned-child cold-start satellite).
        from petastorm_tpu.io import arena as _arena_mod

        arena_token = _arena_mod.current_token()
        if arena_token is not None:
            self._child_env[_arena_mod.ENV_ATTACH] = arena_token
        # tenant propagation (ISSUE 18): the reader stamps its resolved
        # context on the worker; children adopt it as their process default
        # via attach_from_env at bootstrap — the SAME env every respawn/resize
        # spawn reuses, so replacements keep billing the right tenant
        tenant_ctx = getattr(worker, "tenant_context", None)
        if tenant_ctx is None:
            from petastorm_tpu.obs import tenant as _tenant_mod

            tenant_ctx = _tenant_mod.current()
        if tenant_ctx is not None:
            self._child_env.update(tenant_ctx.env())
            self._tenant_label = tenant_ctx.tenant
        else:
            self._tenant_label = None
        if self._transport_name == "tcp":
            # the child's link policy (redial backoff, heartbeat cadence,
            # half-open threshold) rides the environment: the transport must
            # bootstrap BEFORE any handshake payload could carry it
            rec = self._recovery
            self._child_env.update({
                "PTPU_LINK_HEARTBEAT_S": repr(rec.link_heartbeat_s),
                "PTPU_LINK_MISS_THRESHOLD": str(rec.link_miss_threshold),
                "PTPU_LINK_RECONNECT_S": repr(rec.link_reconnect_s),
                "PTPU_LINK_CONNECT_TIMEOUT_S":
                    repr(rec.link_connect_timeout_s),
                "PTPU_IO_RETRY_BACKOFF_S": repr(rec.io_retry_backoff_s),
                "PTPU_IO_RETRY_MAX_BACKOFF_S":
                    repr(rec.io_retry_max_backoff_s),
            })
            self._start_children_tcp(authkey)
        else:
            self._start_children_pipe(authkey)
        monitor = self._health
        self._dispatch = PullDispatcher(
            plan, self._workers_count, lookahead=self._lookahead,
            stealing=self._stealing,
            recorder=monitor.flight if monitor is not None else None)
        with self._active_lock:
            self._active = self._workers_count
            self._target = self._workers_count
            self._retire = 0
            self._next_idx = self._workers_count
        for i, conn in enumerate(self._conns):
            t = threading.Thread(target=self._drive_child,
                                 args=(conn, self._dispatch, i),
                                 daemon=True, name="ptpu-pdrv-%d" % i)
            t.start()
            self._threads.append(t)

    def _setup_hub(self, authkey):
        """Create the tcp listener hub (ISSUE 15). A setup failure — cannot
        bind/listen — is a CLASSIFIED degradation back to the local pipe
        pool, never a raise: the transport is an availability feature and
        must not cost any."""
        from petastorm_tpu.transport.tcp import TcpHub

        try:
            hub = TcpHub(self._recovery, token=authkey.hex())
        except Exception as e:  # noqa: BLE001 — degrade, never fail the pool
            from petastorm_tpu.obs.log import degradation

            degradation(
                "transport_link_down",
                "tcp transport unavailable (%s); falling back to the local "
                "pipe pool", e, once=False)
            hub = None
            self._transport_name = "pipe"
        with self._respawn_lock:  # join() hands the hub off under this lock
            self._hub = hub

    def _start_children_pipe(self, authkey):
        """Spawn + handshake the initial fleet over the unix-socket pipe wire
        (today's behavior, byte-identical)."""
        import os
        from multiprocessing.connection import Listener

        from petastorm_tpu.transport import PipeTransport

        with self._respawn_lock:
            address = os.path.join(self._tmpdir, "sock")
        listener = Listener(address, family="AF_UNIX", authkey=authkey)
        for _ in range(self._workers_count):
            child = self._popen_child(address, authkey)
            with self._respawn_lock:  # _spawn_one/join also touch the proc list
                self._procs.append(child)
        # accept on a helper thread + child liveness poll on this one: a child that dies
        # before connecting (import error, crash) must raise here, not hang Reader
        # construction forever. Public API only — no reaching into Listener internals
        # for socket timeouts (ADVICE r1: private attrs break across Python versions
        # and made every OSError look like a poll tick).
        accepted = queue.Queue()

        def _accept_loop():
            try:
                for _ in range(self._workers_count):
                    accepted.put(listener.accept())
            except Exception as e:  # noqa: BLE001 — surfaced to the main thread
                accepted.put(e)

        acceptor = threading.Thread(target=_accept_loop, name="ptpu-accept", daemon=True)
        acceptor.start()
        try:
            # two phases so children bootstrap CONCURRENTLY: send every
            # handshake payload as its connection arrives, then collect the
            # pid acks — awaiting each ack inline would serialize the pool's
            # startup behind every child's full import + worker unpickle
            # (sum of bootstraps instead of the slowest one)
            pending = []
            while len(pending) < self._workers_count:
                conn = PipeTransport(
                    self._await_accept(accepted, self._procs, "Pool child"))
                self._send_handshake(conn)
                pending.append(conn)
            for conn in pending:
                self._register_conn(conn)
        finally:
            listener.close()  # also unblocks the acceptor thread if we raised

    def _start_children_tcp(self, authkey):
        """Spawn + handshake the initial fleet over the framed tcp transport:
        one hub session per child, children dial back concurrently."""
        pending = []
        for _ in range(self._workers_count):
            with self._respawn_lock:
                sid = self._session_counter
                self._session_counter += 1
            transport = self._hub.create_session(sid)
            if self._tenant_label is not None:
                transport.set_tenant(self._tenant_label)
            child = self._popen_child(self._hub.address_for(sid), authkey)
            with self._respawn_lock:
                self._procs.append(child)
            pending.append(transport)
        # same two-phase shape as the pipe path: handshake each link as it
        # connects, then collect the pid acks
        for transport in pending:
            self._await_tcp_connected(transport, "Pool child")
            self._send_handshake(transport)
        for transport in pending:
            self._register_conn(transport)

    def _register_conn(self, conn):
        """Collect one child's pid ack and register its connection as the
        next driver slot. Accept order ≠ spawn order: the handshake's pid ack
        is what ties this connection (→ driver idx) to its OS process — the
        heal tier kills by exactly this mapping."""
        pid = self._await_pid_ack(conn)
        conn.mark_ready()  # steady-state link: chaos sites + heartbeats on
        with self._respawn_lock:
            idx = len(self._conns)
            self._conns.append(conn)
            for p in self._procs:
                if p.pid == pid:
                    self._child_by_idx[idx] = p
                    break

    def _await_tcp_connected(self, transport, what, procs=None,
                             check_stop=False, deadline=120.0):
        """Bounded wait for one tcp session's first adoption, polling child
        liveness every second — the tcp twin of :meth:`_await_accept` (same
        tolerance: a host slow enough to need start()'s full window must also
        be able to heal)."""
        waited = 0.0
        while not transport.wait_connected(1.0):
            waited += 1.0
            if check_stop and self._stop_event.is_set():
                raise RuntimeError("pool stopping during respawn")
            with self._respawn_lock:
                snapshot = list(self._procs if procs is None else procs)
            for p in snapshot:
                if p.poll() is not None:
                    raise RuntimeError(
                        "%s exited with code %s before connecting (run 'python "
                        "-m petastorm_tpu._child_worker' manually to debug)"
                        % (what, p.returncode))
            if waited > deadline:
                raise TimeoutWaitingForResultError(
                    "%s did not connect within %.0fs" % (what, deadline))

    def _should_retire(self):
        """Claim one pending retirement (live shrink): checked by drivers
        BETWEEN items only — a shrink drains, it never kills mid-item."""
        with self._active_lock:
            if self._retire > 0:
                self._retire -= 1
                return True
            return False

    def set_lookahead(self, lookahead):
        """Live dispatch-lookahead retune (parent side; a child's own
        readahead pool follows the hints it is sent)."""
        self._lookahead = max(0, int(lookahead))
        dispatch = self._dispatch
        if dispatch is not None:
            dispatch.set_lookahead(self._lookahead)

    @property
    def alive_workers(self):
        """Lock-free like ThreadExecutor.alive_workers (collector-safe)."""
        return self._active

    @property
    def target_workers(self):
        return self._target

    def resize(self, workers_count):
        """Grow/shrink the child fleet LIVE (ISSUE 13). Grow spawns clean
        interpreter children through the same handshake as the initial pool
        (and the elastic respawn path); shrink queues retirements — a
        retiring driver finishes its in-flight item, returns its unprocessed
        claim to the dispatcher, sends the orderly-shutdown ``None`` to its
        child and drains out. Never kills mid-item; the delivered ∪
        quarantined set is identical to an un-resized run. Returns the
        applied target (spawn failures leave the fleet smaller and are
        degradation-logged)."""
        n = max(1, int(workers_count))
        dispatch = self._dispatch
        grow_idxs = []
        with self._active_lock:
            if dispatch is None or self._active == 0:
                return self._target
            if n > self._target:
                grow = n - self._target
                cancelled = min(grow, self._retire)
                self._retire -= cancelled
                for _ in range(grow - cancelled):
                    grow_idxs.append(self._next_idx)
                    self._next_idx += 1
            elif n < self._target:
                self._retire += self._target - n
            self._target = n
            next_idx = self._next_idx
        if not grow_idxs:
            return n
        dispatch.ensure_workers(next_idx)
        from petastorm_tpu.obs.log import degradation

        # the slots were RESERVED in _active above (before the slow child
        # spawns): concurrent driver exits must not see a transient zero and
        # post _DONE while a grown child is mid-handshake
        with self._active_lock:
            self._active += len(grow_idxs)
        for idx in grow_idxs:
            try:
                conn, proc = self._spawn_one()
            except Exception as e:  # noqa: BLE001 — degrade, never fail the pool
                degradation(
                    "ctl_spawn_failed",
                    "live fleet grow could not spawn a pool child (%s); "
                    "running with %d fewer worker(s) than the target", e,
                    1, once=False)
                with self._active_lock:
                    self._target -= 1
                    self._active -= 1
                    last = self._active == 0
                if last:
                    self._put(_DONE)  # the reservation was the only holdout
                continue
            with self._respawn_lock:
                self._child_by_idx[idx] = proc
            t = threading.Thread(target=self._drive_child,
                                 args=(conn, dispatch, idx),
                                 daemon=True, name="ptpu-pdrv-%d" % idx)
            t.start()
            self._threads.append(t)
        with self._active_lock:
            return self._target

    def _await_accept(self, accepted, procs, what, check_stop=False, deadline=120.0):
        """Wait for one accepted connection (or the acceptor thread's exception),
        polling child liveness every second — ONE copy of the accept protocol shared
        by the initial pool spawn and elastic respawns (same tolerance both places: a
        host slow enough to need start()'s full window must also be able to heal)."""
        waited = 0.0
        while True:
            try:
                item = accepted.get(timeout=1.0)
                break
            except queue.Empty:
                waited += 1.0
                if check_stop and self._stop_event.is_set():
                    raise RuntimeError("pool stopping during respawn")
                for p in procs:
                    if p.poll() is not None:
                        raise RuntimeError(
                            "%s exited with code %s before connecting (run 'python "
                            "-m petastorm_tpu._child_worker' manually to debug)"
                            % (what, p.returncode))
                if waited > deadline:
                    raise TimeoutWaitingForResultError(
                        "%s did not connect within %.0fs" % (what, deadline))
        if isinstance(item, Exception):
            raise item
        return item

    def _popen_child(self, address, authkey):
        """Launch one clean-interpreter child pointed at ``address`` (shared by the
        initial pool spawn and elastic respawns — ONE copy of the protocol)."""
        import subprocess
        import sys

        p = subprocess.Popen(
            [sys.executable, "-m", "petastorm_tpu._child_worker", address],
            stdin=subprocess.PIPE, env=self._child_env,
        )
        p.stdin.write(authkey)
        p.stdin.close()
        return p

    def _setup_shm(self):
        """Create the slab ring when an shm-family serializer was requested.

        Graceful degradation is part of the contract: a platform without working
        shared memory (or a ring-creation failure, e.g. a tiny ``/dev/shm``)
        swaps the pool down to the inner socket serializer with a warn-once and a
        ``wire_stats()['shm_unavailable']`` marker — same results, socket copies.
        """
        from petastorm_tpu.serializers import ShmSerializer

        if not isinstance(self._serializer, ShmSerializer):
            return
        if self._transport_name == "tcp":
            # the tcp wire must behave as if the host boundary were real
            # (ROADMAP item 1: the same frames cross hosts tomorrow) — slab
            # grants cannot ride a network link, so payloads take the socket
            # frames. Classified, warn-once, and visible in wire_stats().
            from petastorm_tpu.obs.log import degradation

            degradation(
                "transport_shm_bypass",
                "shared-memory slab wire disabled over the tcp transport; "
                "result payloads ride the framed socket wire instead")
            self._shm_unavailable = True
            self._serializer_name = self._serializer.inner_name
            self._serializer = self._serializer.inner
            return
        from petastorm_tpu.parallel.shm_ring import SlabRing, shm_supported

        ring = None
        if shm_supported():
            try:
                ring = SlabRing(self._shm_slab_bytes,
                                self._shm_slabs or (self._workers_count + 2),
                                trace=self._tracer)
            except Exception as e:  # noqa: BLE001 — degrade, never fail the pool
                from petastorm_tpu.obs.log import degradation

                degradation("shm_ring_create_failed",
                            "shared-memory slab ring creation failed (%s); "
                            "falling back to the socket wire", e, once=False)
        if ring is None:
            self._shm_unavailable = True
            self._serializer_name = self._serializer.inner_name
            self._serializer = self._serializer.inner
            return
        self._serializer.bind_ring(ring)
        with self._respawn_lock:  # join() takes the ring under the same lock
            self._ring = ring

    def set_trace(self, tracer):
        """Attach a :class:`petastorm_tpu.trace.TraceRecorder`: the slab ring
        records ``shm.acquire_wait`` spans (driver threads starved for a slab)."""
        self._tracer = tracer
        if self._ring is not None:
            self._ring.set_trace(tracer)

    def set_health(self, monitor):
        """Attach a health monitor; additionally registers this pool's
        child-stack provider — on a stall the watchdog signals every live
        child (SIGUSR1 → faulthandler, see ``_child_worker.py``) and folds
        their thread stacks into the flight record."""
        super().set_health(monitor)
        if monitor is self._stack_provider_monitor:
            return
        # re-attach/detach: move the provider to the new monitor — the old
        # one must stop signaling this pool's children, and the handle is
        # only meaningful to the monitor that issued it
        old, self._stack_provider_monitor = self._stack_provider_monitor, None
        handle, self._stack_provider_handle = self._stack_provider_handle, None
        healer, self._healer_handle = self._healer_handle, None
        if old is not None:
            if handle is not None:
                old.remove_stack_provider(handle)
            if healer is not None:
                old.remove_healer(healer)
        if monitor is not None:
            self._stack_provider_handle = monitor.add_stack_provider(
                self._dump_child_stacks)
            # heal tier (ISSUE 7, escalation="heal"): on a stalled child actor
            # the watchdog asks this pool to kill the hung child — the driver's
            # dead-child machinery then respawns it and re-dispatches the item
            self._healer_handle = monitor.add_healer(self._heal_stalled)
            self._stack_provider_monitor = monitor

    def _dump_child_stacks(self):
        """Signal live children to faulthandler-dump their stacks and collect
        the files (the stall watchdog's cross-process evidence hook). Best
        effort: a child that cannot answer within ~2s is reported as such —
        which is itself evidence (SIGKILL'd? wedged in native code?)."""
        import os
        import signal
        import time

        if not hasattr(signal, "SIGUSR1"):
            return {}  # non-POSIX: driver stacks only
        with self._respawn_lock:
            procs = list(self._procs)
            tmpdir = self._tmpdir
        if not procs or not tmpdir:
            return {}
        # faulthandler APPENDS to the child's open dump file, so a second
        # stall must return only the bytes written AFTER this signal — a
        # previous capture's stack would send the operator to the WRONG hang
        def _size(pid):
            try:
                return os.path.getsize(
                    os.path.join(tmpdir, "stacks-%d.txt" % pid))
            except OSError:
                return 0

        alive = []
        offsets = {}
        for p in procs:
            if p.poll() is None:
                offsets[p.pid] = _size(p.pid)
                try:
                    p.send_signal(signal.SIGUSR1)
                    alive.append(p)
                except OSError:
                    pass
        out = {}
        pending = {p.pid for p in alive}
        partial = {}  # pid -> last read: accept only once the dump stops growing
        deadline = time.monotonic() + 2.0
        while pending and time.monotonic() < deadline:
            time.sleep(0.05)
            for pid in list(pending):
                try:
                    with open(os.path.join(tmpdir, "stacks-%d.txt" % pid)) as f:
                        f.seek(offsets[pid])
                        text = f.read()
                except OSError:
                    continue
                if not text.strip():
                    continue
                # faulthandler may still be mid-write (a child has several
                # threads): accepting the first non-empty read could cut the
                # dump off BEFORE the hung thread's frames — require one
                # stable re-read before shipping it as evidence
                if partial.get(pid) == text:
                    out["child-%d" % pid] = text
                    pending.discard(pid)
                else:
                    partial[pid] = text
        for pid in pending:
            # still growing (or silent) at the deadline: partial evidence
            # beats none, marked so the operator knows it may be cut off
            out["child-%d" % pid] = (
                partial[pid] + "\n<truncated: dump still growing at 2s>"
                if pid in partial else "<no faulthandler dump within 2s>")
        return out

    def _heal_stalled(self, stalled):
        """The ``escalation="heal"`` hook (ISSUE 7): kill the hung pool child
        behind each stalled ``worker.child-<idx>`` actor so the driver's
        dead-child machinery takes over — respawn against the budget, slab
        reclaim (lease-aware), and re-dispatch of the unfinished claim (or a
        quarantine skip once the item hits the poison threshold). Returns the
        actor names it acted on; actors it could NOT absorb (budget exhausted
        and the poison policy cannot eat the kill either) are left for the
        watchdog to escalate to :class:`StallError`.

        Called from the watchdog thread. Matching is by the FULL actor name
        this pool registered (scope-prefixed when the monitor is shared via a
        ``HealthScope``) — a suffix-only match would let one pool's healer
        kill ANOTHER pool's healthy child on a shared monitor, mask the real
        hang (the reported-stall debounce never re-arms for a child that
        never beats again), and burn a respawn for nothing."""
        import re

        healed = set()
        if self._stop_event.is_set():
            return healed
        from petastorm_tpu.obs.log import degradation

        for s in stalled:
            actor = s.get("actor", "")
            m = re.search(r"(?:^|/)worker\.child-(\d+)$", actor)
            if m is None:
                continue
            idx = int(m.group(1))
            if actor != self._child_actor_name(idx):
                continue  # a sibling pipeline's child on a shared monitor
            with self._respawn_lock:
                proc = self._child_by_idx.get(idx)
                budget = self._respawn_budget
                attempts = self._inflight_attempts.get(idx, 0)
            if proc is None or proc.poll() is not None:
                continue  # already dead: the driver is mid-respawn on its own
            # can the kill be absorbed? either the respawn budget pays for a
            # re-dispatch, or the poison policy quarantines the item (its
            # respawn is uncharged). If neither, do NOT pull the trigger —
            # killing would just turn the stall into WorkerDiedError; leaving
            # it lets the watchdog deliver StallError with the hang evidence.
            absorbable = budget > 0 or (
                self._recovery.quarantine
                and attempts + 1 >= self._recovery.poison_attempts)
            if not absorbable:
                continue
            try:
                proc.kill()
            except OSError:
                continue
            degradation(
                "stall_heal_kill",
                "Heal tier killed hung pool child pid=%s (actor %s, %.1fs past "
                "threshold); its item will be re-dispatched or quarantined",
                proc.pid, s["actor"], s.get("age_s", 0.0), once=False)
            healed.add(s["actor"])
        return healed

    def _child_actor_name(self, idx):
        """The full (scope-prefixed) actor name this pool's ``idx``-th child
        heartbeats under — exactly what ``_drive_child`` registers."""
        base = "worker.child-%d" % idx
        namer = getattr(self._health, "_name", None)
        return namer(base) if namer is not None else base

    def wire_stats(self):
        """Wire-transport gauges (shm slab occupancy/bytes/fallbacks/wait), or a
        degradation marker, or {} for plain socket serializers."""
        if self._ring is not None:
            return self._ring.stats()
        if self._shm_unavailable:
            return {"shm_unavailable": 1}
        return {}

    def dispatch_stats(self):
        """Work-stealing gauges for ``Reader.io_stats()`` (parent-side; the
        children's readahead counters live in their own processes)."""
        dispatch = self._dispatch
        return dispatch.stats() if dispatch is not None else {}

    @property
    def wire_views(self):
        """True when deserialized payloads are zero-copy READ-ONLY slab views
        (shm view mode) — consumers that buffer rows must detach them first."""
        from petastorm_tpu.serializers import ShmSerializer

        return (isinstance(self._serializer, ShmSerializer)
                and not self._serializer.writable)

    def _send_handshake(self, conn):
        """Bootstrap a connected child: parent sys.path, wire serializer (plus
        the slab-ring attach config in shm mode), health config, worker.

        The health slot is ALWAYS sent (ISSUE 5): the stack-dump hook costs
        nothing until signaled and the idle ping rides the existing control
        pipe, so child-side evidence capture works even when the monitor is
        attached after the pool started (the driver drains ping messages
        unconditionally — see ``_recv_result``)."""
        import sys

        conn.send(list(sys.path))
        conn.send(self._serializer_name)
        if self._ring is not None:
            conn.send((self._ring.names, self._ring.slab_bytes))
        with self._respawn_lock:
            dump_dir = self._tmpdir
        conn.send({"stack_dump_dir": dump_dir,
                   "ping_interval_s": self._ping_interval_s})
        conn.send(self._worker)

    def _await_pid_ack(self, conn):
        """Collect the child's ``("pid", pid)`` ack (sent right after it
        unpickles the worker): ties the connection to its OS process (accept
        order is not spawn order) — the heal tier and dead-child bookkeeping
        key on it. Bounded: _await_accept already proved the process is alive
        and connected."""
        deadline = 120.0
        waited = 0.0
        while not conn.poll(1.0):
            waited += 1.0
            if waited > deadline:
                raise TimeoutWaitingForResultError(
                    "pool child connected but never sent its pid ack "
                    "(worker unpickle wedged?)")
        ack = conn.recv()
        if not (isinstance(ack, tuple) and len(ack) == 2 and ack[0] == "pid"):
            raise RuntimeError("unexpected pool-child handshake ack %r" % (ack,))
        return ack[1]

    def _handshake(self, conn):
        """Send + collect in one call (the single-child respawn path)."""
        self._send_handshake(conn)
        return self._await_pid_ack(conn)

    def _spawn_one(self):
        """Spawn + handshake ONE replacement child (elastic respawn / live
        grow / strand rescue). Returns ``(connection, process)``; raises when
        the child cannot start/connect or the pool is stopping (the
        replacement is then killed, never leaked). On the tcp transport a
        spawn whose LINK cannot establish falls back to a pipe-connected
        local child — all-links-down degrades to the local pool as a
        classified degradation, never a hang or a hard failure."""
        if self._transport_name == "tcp" and self._hub is not None:
            try:
                return self._spawn_one_tcp()
            except Exception as e:  # noqa: BLE001 — degrade to the local pool
                if self._stop_event.is_set():
                    raise
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "transport_link_down",
                    "tcp child spawn could not establish a link (%s); "
                    "falling back to a pipe-connected local child", e,
                    once=False)
        return self._spawn_one_pipe()

    def _spawn_one_tcp(self):
        """One replacement child over a fresh tcp hub session."""
        with self._respawn_lock:
            if self._tmpdir is None:
                raise RuntimeError("pool stopping during respawn")
            sid = self._session_counter
            self._session_counter += 1
        transport = self._hub.create_session(sid)
        if getattr(self, "_tenant_label", None) is not None:
            transport.set_tenant(self._tenant_label)
        p = None
        try:
            p = self._popen_child(self._hub.address_for(sid), self._authkey)
            self._await_tcp_connected(transport, "respawned pool child",
                                      procs=[p], check_stop=True)
            self._send_handshake(transport)
            self._await_pid_ack(transport)
            transport.mark_ready()
            with self._respawn_lock:
                if self._stop_event.is_set():
                    raise RuntimeError("pool stopping during respawn")
                self._procs.append(p)
                self._conns.append(transport)
            return transport, p
        except BaseException:
            self._hub.drop_session(sid)
            transport.close()
            if p is not None:
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass  # graftlint: disable=GL-O002 (best-effort kill on the raising path)
            raise

    def _spawn_one_pipe(self):
        """One replacement child over the unix-socket pipe wire."""
        import os
        from multiprocessing.connection import Listener

        with self._respawn_lock:
            self._spawn_counter += 1
            address = os.path.join(self._tmpdir, "sock-r%d" % self._spawn_counter)
        authkey = os.urandom(32)
        listener = Listener(address, family="AF_UNIX", authkey=authkey)
        p = None
        conn = None
        try:
            p = self._popen_child(address, authkey)
            accepted = queue.Queue()

            def _accept():
                try:
                    accepted.put(listener.accept())
                except Exception as e:  # noqa: BLE001 — surfaced below
                    accepted.put(e)

            t = threading.Thread(target=_accept, daemon=True, name="ptpu-respawn-accept")
            t.start()
            from petastorm_tpu.transport import PipeTransport

            conn = PipeTransport(
                self._await_accept(accepted, [p], "respawned pool child",
                                   check_stop=True))
            self._handshake(conn)
            conn.mark_ready()
            with self._respawn_lock:
                # join()/stop() may have begun while we were mid-handshake:
                # registering into already-cleared lists would leak an unreaped
                # child and an open socket (join() holds this lock to clear them)
                if self._stop_event.is_set():
                    raise RuntimeError("pool stopping during respawn")
                self._procs.append(p)
                self._conns.append(conn)
            return conn, p
        except BaseException:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            if p is not None:
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass  # graftlint: disable=GL-O002 (best-effort kill on the raising path)
            raise
        finally:
            listener.close()

    def _respawn(self, err, idx, charged=True):
        """A replacement connection for a dead child (registered as driver
        ``idx``'s child), or None when the budget is exhausted / the pool is
        stopping / the spawn itself fails.

        ``charged=False`` is the quarantine path (ISSUE 7): the dead child's
        item reached the poison threshold and will be SKIPPED, so the respawn
        only restores pool capacity — it cannot crash-loop, and charging it
        would let one poison item eat the whole budget."""
        with self._respawn_lock:
            if self._stop_event.is_set():
                return None
            if charged:
                if self._respawn_budget <= 0:
                    return None
                self._respawn_budget -= 1
            budget_left = self._respawn_budget
        from petastorm_tpu.obs.log import degradation

        try:
            conn, proc = self._spawn_one()
        except Exception as e:  # noqa: BLE001 — degrade to the fatal path
            degradation("respawn_failed", "Pool child respawn failed: %s", e,
                        once=False)
            return None
        with self._respawn_lock:
            self._child_by_idx[idx] = proc
        degradation(
            "worker_died",
            "Pool worker died (%s); respawned a replacement and %s its "
            "item (remaining respawn budget: %d)", err,
            "re-dispatching" if charged else "quarantining", budget_left,
            once=False)
        return conn

    def _recv_result(self, conn, child_hb, idx=None):
        """Receive the next result/exc header, draining child heartbeat pings
        (``("hb", ts)`` — sent at item receipt and while idle) into the
        child's heartbeat stamp, and control-frame acks (``("ctl_ack",
        applied)``) into the pool's ack ledger. Children always ping; without
        a monitor the pings are simply dropped here (one tuple check per
        message).

        The receive is a bounded poll loop, not a bare ``recv()`` (GL-R001):
        once the pool is stopping this driver abandons the wait promptly —
        a child hung in native code used to pin its driver in ``recv`` for
        the full 10s thread-join timeout on every teardown."""
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.hit("pool.recv")
        while True:
            while not conn.poll(0.2):
                if self._stop_event.is_set():
                    raise EOFError("pool stopping while awaiting a child result")
            msg = conn.recv()  # graftlint: disable=GL-R001 (poll above bounds it)
            if isinstance(msg, tuple) and len(msg) == 2 and msg[0] == "hb":
                if child_hb is not None:
                    child_hb.beat("working")
                continue
            if isinstance(msg, tuple) and len(msg) == 2 and msg[0] == "ctl_ack":
                if idx is not None:
                    with self._ctl_lock:
                        self._ctl_acks.setdefault(idx, {}).update(msg[1] or {})
                continue
            return msg

    # -- live cross-process knobs (ISSUE 14 satellite) ----------------------------------

    def broadcast_io_knobs(self, knobs):
        """Queue a ``{knob: value}`` retune for every RUNNING child: each
        driver sends one ``("ctl", knobs)`` frame on its item pipe before its
        next dispatch (beside the slab-grant protocol), the child applies via
        its worker's ``apply_<knob>()`` seam and acks. Children spawned AFTER
        the retune inherit it through the worker pickle instead (the PR 13
        behavior, now the backstop rather than the only path)."""
        if not knobs:
            return
        with self._ctl_lock:
            self._ctl_pending.update(knobs)
            self._ctl_version += 1

    def _pending_ctl(self, idx):
        """The control frame driver ``idx`` still owes its child, or None."""
        with self._ctl_lock:
            if not self._ctl_pending \
                    or self._ctl_seen.get(idx, 0) == self._ctl_version:
                return None
            self._ctl_seen[idx] = self._ctl_version
            return dict(self._ctl_pending)

    def ctl_acks(self):
        """``{driver idx: {knob: applied value}}`` — which children confirmed
        a live retune (the autotune harness asserts a child-side retune lands
        WITHOUT a respawn)."""
        with self._ctl_lock:
            return {idx: dict(acks) for idx, acks in self._ctl_acks.items()}

    def _drive_child(self, conn, dispatch, idx):
        import time

        from petastorm_tpu.serializers import KIND_SHM

        # local snapshot: join() nulls self._ring (under the respawn lock) while a
        # straggling driver may still be mid-item past its 10s join timeout — the
        # ring object itself stays safe to call (close() makes release a no-op)
        ring = self._ring
        hb = None        # this driver thread's heartbeat (all wait states)
        child_hb = None  # the child's: stamped from pipe traffic, watchdogged
        try:
            fatal = False
            while not fatal and not self._stop_event.is_set():
                if self._should_retire():
                    # live shrink (ISSUE 13): hand the unprocessed claim back
                    # and drain out — the post-loop None send retires the
                    # child cleanly; its process is reaped by join()
                    dispatch.withdraw(idx)
                    with self._respawn_lock:
                        self._child_by_idx.pop(idx, None)
                    break
                monitor = self._health
                if monitor is not None and hb is None:
                    hb = monitor.register("pooldrv-%d" % idx, "worker")
                    child_hb = monitor.register("worker.child-%d" % idx, "child")
                if hb is not None:
                    hb.wait("claim")
                claim = dispatch.next(idx)
                if claim is None:
                    break
                item, upcoming = claim
                # readahead hint rides with the item: the child prefetches these
                # on ITS IO pool before working the item (they are this driver's
                # claimed pieces, so barring a steal the child reads its own future)
                hints = list(upcoming)
                prov = self._prov  # resolved per item, attachable mid-stream
                prov_id = _prov.item_identity(item) if prov is not None \
                    else None
                recovery = self._recovery
                attempts = 0       # failures of THIS item, across respawns/heals
                first_death = None  # the ORIGINAL child failure (ISSUE 7: budget
                #                     exhaustion must surface it, not a wrapper)
                self._inflight_attempts[idx] = 0
                while True:  # item attempts: survives child death via respawn
                    # slab grant per ATTEMPT: a respawned child gets a fresh grant,
                    # and a dead child's in-flight slab is reclaimed below
                    slab = None
                    if ring is not None:
                        t_slab = time.perf_counter() if prov is not None else 0.0
                        slab = ring.acquire()
                        if prov is not None:
                            prov.add_item_span(prov_id[0], prov_id[1],
                                               "wire.slab_wait", t_slab,
                                               time.perf_counter(),
                                               key=prov_id[2])
                        if slab is None:  # ring starved: socket wire for this item
                            ring.count_fallback()
                    try:
                        if child_hb is not None:
                            child_hb.beat("working")
                        if hb is not None:
                            # the driver is only WAITING here; the hang
                            # candidate is the child, and ITS heartbeat (stamped
                            # at send, from pings, and at the header) carries
                            # the stall detection
                            hb.wait("child")
                        t0 = time.perf_counter() if monitor is not None else 0.0
                        if _chaos.ACTIVE is not None:
                            _chaos.ACTIVE.hit("pool.dispatch",
                                              key=_chaos.item_key(item))
                        ctl = self._pending_ctl(idx)
                        if ctl is not None:
                            # live knob control frame (ISSUE 14 satellite):
                            # the retune rides the item pipe ahead of the
                            # next dispatch — the child applies + acks, no
                            # respawn involved
                            conn.send(("ctl", ctl))
                        t_send = time.perf_counter() if prov is not None else 0.0
                        # in-flight ledger (ISSUE 15): the item is tracked on
                        # its link until the result conversation completes —
                        # whatever is still tracked at a link death is exactly
                        # what re-dispatches (no-op on the pipe transport)
                        conn.track(item)
                        conn.send((slab, item, hints) if ring is not None
                                  else (item, hints))
                        header = self._recv_result(conn, child_hb, idx=idx)
                        if prov is not None:
                            # the child's own spans nest INSIDE this roundtrip
                            # once merged — the flame fold charges the wire the
                            # residual, not the child's work
                            prov.add_item_span(prov_id[0], prov_id[1],
                                               "wire.roundtrip", t_send,
                                               time.perf_counter(),
                                               key=prov_id[2])
                        if monitor is not None:
                            monitor.observe_worker(idx, time.perf_counter() - t0)
                        if child_hb is not None:
                            child_hb.wait("idle")
                        if header[0] == "exc":
                            conn.settle()  # the conversation completed
                            if slab is not None:
                                ring.release(slab)
                            attempts += 1
                            self._inflight_attempts[idx] = attempts
                            if not recovery.quarantine:
                                self._put(_ExcResult(header[1]))
                                fatal = True
                                break
                            if attempts >= recovery.poison_attempts:
                                # poison by exception: skip it, keep the pool
                                self._put(QuarantinedItem(item, header[1],
                                                          attempts))
                                break  # the child is alive: next item
                            continue  # retry on the same live child
                        _, kind, nframes, trace_blob = header
                        if trace_blob is not None:
                            # cross-process merge: the child's per-item spans,
                            # clock-aligned onto the parent recorder's timeline.
                            # Slot 5 (when present) is the provenance piggyback
                            # (ISSUE 10) riding the same anchors.
                            child_pid, wall0, perf0, spans = trace_blob[:4]
                            if self._tracer is not None:
                                self._tracer.add_child(child_pid, spans,
                                                       wall0, perf0)
                            if prov is not None and len(trace_blob) > 4 \
                                    and trace_blob[4] is not None:
                                prov.absorb_child(trace_blob[4], child_pid,
                                                  wall0, perf0)
                        frames = [conn.recv_bytes() for _ in range(nframes)]
                        conn.settle()  # result fully received off the link
                        if slab is not None and kind != KIND_SHM:
                            # granted but unused (oversized payload): reclaim first
                            # so a deserialize error cannot leak the slab
                            ring.release(slab)
                            ring.count_fallback()
                            slab = None
                        # kind == KIND_SHM transfers slab ownership to deserialize
                        # HERE (released there on its own failure, or leased to
                        # the consumer in view mode) — `slab` must be cleared
                        # BEFORE the call: a decode error below must never
                        # double-release a slab the lease contract already owns
                        # (the free list would hand one slab to two children,
                        # silently corrupting a consumer-retained batch). The
                        # one exception: a failure BEFORE deserialize could
                        # even parse the descriptor (slab_released=False on
                        # the exception) leaves the grant with this driver.
                        granted, slab = slab, None
                        t_dec = time.perf_counter() if prov is not None else 0.0
                        if _chaos.ACTIVE is not None:
                            frames = _chaos.ACTIVE.hit(
                                "wire.decode", key=_chaos.item_key(item),
                                payload=frames)
                        try:
                            result = self._serializer.deserialize(kind, frames)
                            if prov is not None:
                                # covers the chaos wire.decode injection site
                                # too, so an injected wire stall lands in this
                                # span's self time
                                prov.add_item_span(prov_id[0], prov_id[1],
                                                   "wire.decode", t_dec,
                                                   time.perf_counter(),
                                                   key=prov_id[2])
                        except Exception as e:  # noqa: BLE001 — policy-classified
                            if granted is not None and \
                                    not getattr(e, "slab_released", True):
                                ring.release(granted)
                            # wire-decode failure (corrupt bytes, truncated
                            # descriptor): the child is ALIVE and the pipe is
                            # intact. An EOFError out of pickle.loads used to
                            # masquerade as a child death here — blind slab
                            # release (double free) plus a pointless respawn of
                            # a live child. Classify it like a worker exception
                            # instead: poison policy applies, the item re-runs
                            # on the same child.
                            attempts += 1
                            self._inflight_attempts[idx] = attempts
                            if not recovery.quarantine:
                                self._put(_ExcResult(e))
                                fatal = True
                                break
                            if attempts >= recovery.poison_attempts:
                                self._put(QuarantinedItem(item, e, attempts,
                                                          kind="wire_decode"))
                                break
                            continue  # re-dispatch the item on the same child
                    except (EOFError, BrokenPipeError, ConnectionResetError) as e:
                        if slab is not None:
                            # dead child's in-flight slab: reclaim is lease-aware
                            # (revokes any outstanding consumer lease instead of
                            # re-inserting a still-leased slab into the free list)
                            ring.reclaim(slab)
                        if self._stop_event.is_set():
                            fatal = True  # teardown abandon, not a child failure
                            break
                        attempts += 1
                        self._inflight_attempts[idx] = attempts
                        if first_death is None:
                            first_death = e
                        # poison policy: an item that keeps killing children is
                        # skipped after poison_attempts; its respawn restores
                        # pool capacity WITHOUT charging the budget (the item
                        # will not be retried — no crash-loop risk)
                        poison = (recovery.quarantine
                                  and attempts >= recovery.poison_attempts)
                        # transport-level link death (ISSUE 15): when the
                        # child PROCESS is alive only the LINK died — the
                        # child redials with jittered backoff and the hub
                        # re-adopts; re-dispatch on the same child then. An
                        # attempt is charged (the poison policy applies — a
                        # frame that reliably kills its link quarantines like
                        # any poison item) but the respawn budget is not.
                        # Under on_poison='raise' the fast-path is BOUNDED by
                        # the poison threshold too: past it we fall through
                        # to the respawn path, whose budget (and then
                        # WorkerDiedError) bounds a deterministic link-killer
                        # exactly like the pipe wire's child-death contract —
                        # never an unbounded reconnect spin. PipeTransport
                        # has no reconnect: a dead pipe IS a dead child.
                        reconnect = getattr(conn, "reconnect", None)
                        if reconnect is not None \
                                and (recovery.quarantine
                                     or attempts < recovery.poison_attempts) \
                                and not self._stop_event.is_set():
                            with self._respawn_lock:
                                proc = self._child_by_idx.get(idx)
                            if proc is not None and proc.poll() is None \
                                    and reconnect():
                                with self._ctl_lock:
                                    # a knob frame may have died with the old
                                    # link: re-arm the pending-control send so
                                    # the retune rides the fresh one (applies
                                    # are idempotent)
                                    if self._ctl_pending:
                                        self._ctl_seen[idx] = 0
                                if poison:
                                    self._put(QuarantinedItem(
                                        item, e, attempts, kind="link_death"))
                                    break
                                continue  # re-dispatch on the healed link
                        replacement = self._respawn(e, idx, charged=not poison)
                        if poison:
                            self._put(QuarantinedItem(item, e, attempts,
                                                      kind="child_death"))
                        if replacement is None:
                            if not self._stop_event.is_set():
                                err = WorkerDiedError(
                                    "worker process died%s and no replacement "
                                    "could be spawned (respawn budget "
                                    "exhausted, or the spawn itself failed — "
                                    "see the respawn_failed degradation): %s"
                                    % (" %d time(s) on one item" % attempts
                                       if attempts > 1 else "", first_death),
                                    original=first_death)
                                self._put(_ExcResult(err))
                            fatal = True
                            break
                        try:
                            conn.close()
                        except OSError:
                            pass
                        if self._hub is not None \
                                and hasattr(conn, "session"):
                            # a zombie child redialing its DEAD session must
                            # find it gone, not adopt into a closed transport
                            self._hub.drop_session(conn.session)
                        conn = replacement
                        with self._ctl_lock:
                            # the fresh child inherited current knob overrides
                            # through the worker pickle — no frame owed
                            self._ctl_seen[idx] = self._ctl_version
                        if poison:
                            break  # quarantined: the fresh child takes the NEXT item
                        continue  # re-dispatch the SAME item on the fresh child
                    except Exception as e:  # noqa: BLE001 — a bad frame must surface,
                        self._put(_ExcResult(e))  # not silently truncate the dataset
                        fatal = True
                        break
                    if hb is not None:
                        hb.wait("results_put")  # full results queue = backpressure
                    self._put(result)
                    break
            try:
                conn.send(None)  # orderly shutdown
            except (BrokenPipeError, OSError):
                pass
        finally:
            if hb is not None:
                hb.done()
            if child_hb is not None:
                child_hb.done()
            self._retire_driver(dispatch, fatal)

    def _retire_driver(self, dispatch, fatal):
        """The drivers' exit gate — same strand-rescue contract as
        :meth:`ThreadExecutor._retire_worker`: the last decrementer finding
        handed-back claims in the dispatcher spawns a rescue child (this
        driver's own child already received its orderly shutdown) instead of
        posting ``_DONE`` over undelivered rows."""
        rescue_idx = None
        with self._active_lock:
            self._active -= 1
            if self._active == 0 and not fatal \
                    and not self._stop_event.is_set() and dispatch.has_work():
                self._active += 1
                rescue_idx = self._next_idx
                self._next_idx += 1
            last = self._active == 0
        if rescue_idx is not None:
            try:
                conn, proc = self._spawn_one()
                with self._respawn_lock:
                    self._child_by_idx[rescue_idx] = proc
                dispatch.ensure_workers(rescue_idx + 1)
                t = threading.Thread(target=self._drive_child,
                                     args=(conn, dispatch, rescue_idx),
                                     daemon=True,
                                     name="ptpu-pdrv-%d" % rescue_idx)
                t.start()
                self._threads.append(t)
                return
            except Exception as e:  # noqa: BLE001 — degrade to stream end
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "ctl_rescue_failed",
                    "stranded-claim rescue child could not start (%s); the "
                    "handed-back items are LOST for this pass", e, once=False)
                with self._active_lock:
                    self._active -= 1
                    last = self._active == 0
        if last:
            # OUTSIDE the lock (see ThreadExecutor._retire_worker)
            self._put(_DONE)

    def _put(self, value):
        # Even the _DONE marker yields to a SET stop event: the consumer is the one
        # who sets it, and it never reads results afterwards — spinning until the
        # full queue drains would park the last worker for join()'s whole timeout
        # (results_timeout_s) on every stop-mid-stream teardown.
        while True:
            try:
                self._results.put(value, timeout=0.1)
                return
            except queue.Full:
                if self._stop_event.is_set():
                    return

    def results(self):
        return self._drain_results()

    def stop(self):
        self._stop_event.set()
        try:
            while True:
                self._results.get_nowait()
        except (queue.Empty, AttributeError):
            pass

    def join(self):
        import shutil

        # join == no more results wanted: setting the stop event aborts any in-flight
        # respawn within ~1s (otherwise a driver stuck in the 60s connect wait would
        # outlive the 10s thread join and register a child into cleared lists)
        self._stop_event.set()
        monitor = self._stack_provider_monitor
        self._stack_provider_monitor = None
        handle, self._stack_provider_handle = self._stack_provider_handle, None
        healer, self._healer_handle = self._healer_handle, None
        if monitor is not None:
            # a stall fired after this point must not signal (or heal-kill)
            # reaped children; removal goes to the monitor that ISSUED the handle
            if handle is not None:
                monitor.remove_stack_provider(handle)
            if healer is not None:
                monitor.remove_healer(healer)
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        with self._respawn_lock:  # excludes a racing _spawn_one registration
            conns, self._conns = self._conns, []
            procs, self._procs = self._procs, []
            # taking the tmpdir under the same lock keeps a straggling
            # _spawn_one from creating its socket in a directory this method is
            # about to rmtree (it fails cleanly on None instead)
            tmpdir, self._tmpdir = self._tmpdir, None
            ring, self._ring = self._ring, None
            hub, self._hub = self._hub, None
            self._child_by_idx = {}
            self._inflight_attempts = {}
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        if hub is not None:
            # after the per-link closes, before reaping: a child mid-redial
            # sees connection-refused and exits on its own ceiling; stragglers
            # are killed below either way
            hub.close()
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                p.kill()
        if ring is not None:
            # AFTER children are reaped (no live writer) and BEFORE returning:
            # every slab is unlinked here, so /dev/shm is clean even if a consumer
            # abandoned leased batches mid-stream
            ring.close()
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


def make_executor(reader_pool_type="thread", workers_count=4, results_queue_size=16,
                  results_timeout_s=300.0, serializer="pickle", worker_respawns=None,
                  shm_slab_bytes=None, shm_slabs=None, io_options=None,
                  recovery=None, transport=None):
    """Factory matching the reference's ``reader_pool_type`` kwarg ('thread'|'process'|'dummy').

    ``serializer`` selects the process-pool wire format: 'pickle'|'arrow' (reference
    Pickle/ArrowTable serializer parity, socket frames) or the shared-memory slab
    family 'shm'/'shm-arrow' (+ '-view' variants — zero-copy read-only delivery; see
    petastorm_tpu/serializers.py); thread/dummy pools share memory and ignore it.
    ``worker_respawns`` bounds the process pool's elastic recovery (dead children are
    replaced and their item re-dispatched up to this many times; 0 = fail fast).
    ``shm_slab_bytes``/``shm_slabs`` size the slab ring (defaults: 32 MB ×
    (workers_count + 2); also tunable via the PTPU_SHM_SLAB_BYTES env var).
    ``io_options`` (:class:`petastorm_tpu.io.IoOptions`) configures the dispatch
    side of the async read path: the per-worker lookahead claim (= readahead
    depth) and work stealing.
    ``recovery`` (:class:`petastorm_tpu.recovery.RecoveryOptions`) is the unified
    recovery policy (ISSUE 7): the process pool's respawn budget defaults from it
    (an explicit ``worker_respawns`` still wins), and every pool applies its
    ``on_poison``/``poison_attempts`` quarantine policy to failing items.
    ``transport`` selects the process pool's wire (ISSUE 15): ``'pipe'`` (the
    default — today's unix-socket connection, byte-identical) or ``'tcp'``
    (framed crc-trailered loopback/LAN sockets that survive link death with
    exactly-once-or-quarantined re-dispatch; also via ``PTPU_TRANSPORT``).
    Thread/dummy pools share memory and ignore it.
    """
    from petastorm_tpu.io import IoOptions
    from petastorm_tpu.transport import normalize_transport

    # validated for EVERY pool type: a typo'd transport (or PTPU_TRANSPORT)
    # must fail loudly at the factory, not be silently ignored because the
    # pool happened to be thread/dummy
    transport = normalize_transport(transport)
    io_options = IoOptions.normalize(io_options)
    lookahead = io_options.lookahead
    stealing = io_options.work_stealing
    if reader_pool_type in ("dummy", "sync"):
        return SyncExecutor(lookahead=lookahead, recovery=recovery)
    if reader_pool_type == "thread":
        return ThreadExecutor(workers_count, results_queue_size, results_timeout_s,
                              lookahead=lookahead, work_stealing=stealing,
                              recovery=recovery)
    if reader_pool_type == "process":
        return ProcessExecutor(workers_count, results_queue_size, results_timeout_s,
                               serializer=serializer, worker_respawns=worker_respawns,
                               shm_slab_bytes=shm_slab_bytes, shm_slabs=shm_slabs,
                               lookahead=lookahead, work_stealing=stealing,
                               recovery=recovery, transport=transport)
    raise ValueError(
        "Unknown reader_pool_type %r (expected 'thread', 'process' or 'dummy')"
        % reader_pool_type
    )
