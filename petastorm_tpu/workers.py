"""Worker executors: pull row-group work items from a plan, run a worker, stream results.

Functional parity with the reference worker-pool layer (petastorm/workers_pool/: ``ThreadPool``
thread_pool.py ~L60, ``ProcessPool`` process_pool.py ~L60 + ZeroMQ sockets, ``DummyPool``
dummy_pool.py ~L30, ``ConcurrentVentilator`` ventilator.py ~L60), redesigned per SURVEY.md §3.2:

- No ZeroMQ and no ventilator thread. Backpressure is a bounded results queue; the "ventilator"
  is the (possibly infinite, resumable) :class:`petastorm_tpu.plan.EpochPlan` pulled lazily
  under a lock. Threads are the default pool — Arrow IO and cv2 decode release the GIL, and the
  heavy decode moves on-device anyway (Pallas), so forked processes buy little and cost pickling.
- ``ProcessPoolExecutor`` is kept for CPU-hungry user ``TransformSpec`` functions: workers are
  initialized once per child (no per-task worker pickling) and in-flight tasks are capped for
  backpressure, mirroring the reference's ``max_ventilation_queue_size``.

Contract: ``executor.start(worker, plan)`` then iterate ``executor.results()``; worker is a
picklable callable ``worker(item) -> result``; exceptions in workers propagate to the consumer;
``stop()``/``join()`` mirror the reference pool API.
"""
from __future__ import annotations

import logging
import queue
import threading

from petastorm_tpu.errors import TimeoutWaitingForResultError

logger = logging.getLogger(__name__)

_DONE = object()


class _ExcResult:
    def __init__(self, exc):
        self.exc = exc


class ExecutorBase:
    def start(self, worker, plan):
        raise NotImplementedError

    def results(self):
        """Generator of worker results; raises worker exceptions; ends when plan exhausted."""
        raise NotImplementedError

    def stop(self):
        pass

    def join(self):
        pass


class SyncExecutor(ExecutorBase):
    """Synchronous in-process execution (reference DummyPool): deterministic, for tests/debug."""

    def __init__(self, **_ignored):
        self._worker = None
        self._plan = None
        self._stopped = False

    def start(self, worker, plan):
        self._worker = worker
        self._plan = plan

    def results(self):
        for item in self._plan:
            if self._stopped:
                return
            yield self._worker(item)

    def stop(self):
        self._stopped = True


class ThreadExecutor(ExecutorBase):
    """N threads pulling work items from the shared plan; bounded results queue = backpressure."""

    def __init__(self, workers_count=4, results_queue_size=16, results_timeout_s=300.0,
                 **_ignored):
        self._workers_count = workers_count
        self._queue_size = results_queue_size
        self._timeout = results_timeout_s
        self._threads = []
        self._results = None
        self._stop_event = threading.Event()
        self._plan_lock = threading.Lock()
        self._active = 0
        self._active_lock = threading.Lock()

    def start(self, worker, plan):
        self._results = queue.Queue(maxsize=self._queue_size)
        self._stop_event.clear()
        plan_iter = iter(plan)
        self._active = self._workers_count
        for i in range(self._workers_count):
            t = threading.Thread(
                target=self._run_worker, args=(worker, plan_iter), daemon=True,
                name="ptpu-worker-%d" % i,
            )
            t.start()
            self._threads.append(t)

    def _run_worker(self, worker, plan_iter):
        try:
            while not self._stop_event.is_set():
                with self._plan_lock:
                    try:
                        item = next(plan_iter)
                    except StopIteration:
                        break
                try:
                    result = worker(item)
                except Exception as e:  # noqa: BLE001 - propagate to consumer
                    self._put(_ExcResult(e))
                    break
                self._put(result)
        finally:
            with self._active_lock:
                self._active -= 1
                if self._active == 0:
                    self._put(_DONE, force=True)

    def _put(self, value, force=False):
        while True:
            try:
                self._results.put(value, timeout=0.1)
                return
            except queue.Full:
                if self._stop_event.is_set() and not force:
                    return

    def results(self):
        while True:
            try:
                value = self._results.get(timeout=self._timeout)
            except queue.Empty:
                raise TimeoutWaitingForResultError(
                    "No worker result within %.0fs" % self._timeout
                ) from None
            if value is _DONE:
                return
            if isinstance(value, _ExcResult):
                self.stop()
                raise value.exc
            yield value

    def stop(self):
        self._stop_event.set()
        # drain so blocked workers can exit
        try:
            while True:
                self._results.get_nowait()
        except (queue.Empty, AttributeError):
            pass

    def join(self):
        import time

        deadline = time.monotonic() + self._timeout  # shared across threads, not per-thread
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                logger.warning(
                    "Worker thread %s still alive after %.0fs join (blocked in IO?); "
                    "it will exit at its next stop-event check without publishing",
                    t.name, self._timeout,
                )
        self._threads = []


# -- process pool ----------------------------------------------------------------------


class ProcessExecutor(ExecutorBase):
    """Multiprocess execution for CPU-bound workers (GIL-holding user transforms).

    Children are CLEAN interpreters started via ``python -m petastorm_tpu._child_worker``
    (reference design: exec_in_new_process + zmq, process_pool.py ~L60): no re-import of the
    user's ``__main__`` (multiprocessing spawn/forkserver fork-bombs unguarded scripts) and no
    fork of a threaded parent (JAX deadlock hazard). The worker is pickled once per child;
    per-task traffic is (item, result) over a unix socket. One driver thread per child gives
    one-item-in-flight-per-child backpressure plus the bounded results queue.
    """

    def __init__(self, workers_count=4, results_queue_size=16, results_timeout_s=300.0,
                 serializer="pickle", **_ignored):
        self._workers_count = workers_count
        self._queue_size = results_queue_size
        self._timeout = results_timeout_s
        self._serializer_name = serializer
        from petastorm_tpu.serializers import make_serializer

        self._serializer = make_serializer(serializer)
        self._procs = []
        self._conns = []
        self._threads = []
        self._results = None
        self._stop_event = threading.Event()
        self._plan_lock = threading.Lock()
        self._active = 0
        self._active_lock = threading.Lock()
        self._tmpdir = None

    def start(self, worker, plan):
        import os
        import subprocess
        import sys
        import tempfile
        from multiprocessing.connection import Listener

        self._results = queue.Queue(maxsize=self._queue_size)
        self._stop_event.clear()
        self._tmpdir = tempfile.mkdtemp(prefix="ptpu-pool-")
        address = os.path.join(self._tmpdir, "sock")
        authkey = os.urandom(32)
        listener = Listener(address, family="AF_UNIX", authkey=authkey)
        # children must find petastorm_tpu BEFORE the bootstrap handshake can hand them
        # the parent's sys.path — put the package root on PYTHONPATH explicitly (the
        # parent may have found it via sys.path.insert, which does not propagate)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child_pp = os.environ.get("PYTHONPATH", "")
        child_pp = pkg_root + ((os.pathsep + child_pp) if child_pp else "")
        for _ in range(self._workers_count):
            p = subprocess.Popen(
                [sys.executable, "-m", "petastorm_tpu._child_worker", address],
                stdin=subprocess.PIPE,
                env={**os.environ, "PYTHONPATH": child_pp,
                     "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu")},
            )
            p.stdin.write(authkey)
            p.stdin.close()
            self._procs.append(p)
        # accept on a helper thread + child liveness poll on this one: a child that dies
        # before connecting (import error, crash) must raise here, not hang Reader
        # construction forever. Public API only — no reaching into Listener internals
        # for socket timeouts (ADVICE r1: private attrs break across Python versions
        # and made every OSError look like a poll tick).
        accepted = queue.Queue()

        def _accept_loop():
            try:
                for _ in range(self._workers_count):
                    accepted.put(listener.accept())
            except Exception as e:  # noqa: BLE001 — surfaced to the main thread
                accepted.put(e)

        acceptor = threading.Thread(target=_accept_loop, name="ptpu-accept", daemon=True)
        acceptor.start()
        deadline = 120.0
        waited = 0.0
        try:
            while len(self._conns) < self._workers_count:
                try:
                    item = accepted.get(timeout=1.0)
                except queue.Empty:
                    waited += 1.0
                    for p in self._procs:
                        if p.poll() is not None:
                            raise RuntimeError(
                                "Pool child exited with code %s before connecting (run "
                                "'python -m petastorm_tpu._child_worker' manually to "
                                "debug)" % p.returncode
                            )
                    if waited > deadline:
                        raise TimeoutWaitingForResultError(
                            "Pool children did not connect within %.0fs" % deadline
                        )
                    continue
                if isinstance(item, Exception):
                    raise item
                conn = item
                conn.send(list(sys.path))
                conn.send(self._serializer_name)
                conn.send(worker)
                self._conns.append(conn)
        finally:
            listener.close()  # also unblocks the acceptor thread if we raised
        plan_iter = iter(plan)
        self._active = self._workers_count
        for i, conn in enumerate(self._conns):
            t = threading.Thread(target=self._drive_child, args=(conn, plan_iter),
                                 daemon=True, name="ptpu-pdrv-%d" % i)
            t.start()
            self._threads.append(t)

    def _drive_child(self, conn, plan_iter):
        try:
            while not self._stop_event.is_set():
                with self._plan_lock:
                    try:
                        item = next(plan_iter)
                    except StopIteration:
                        break
                try:
                    conn.send(item)
                    header = conn.recv()
                    if header[0] == "exc":
                        self._put(_ExcResult(header[1]))
                        break
                    _, kind, nframes = header
                    frames = [conn.recv_bytes() for _ in range(nframes)]
                    result = self._serializer.deserialize(kind, frames)
                except (EOFError, BrokenPipeError, ConnectionResetError) as e:
                    self._put(_ExcResult(RuntimeError("worker process died: %s" % e)))
                    break
                except Exception as e:  # noqa: BLE001 — a bad frame must surface, not
                    self._put(_ExcResult(e))  # silently truncate the dataset
                    break
                self._put(result)
            try:
                conn.send(None)  # orderly shutdown
            except (BrokenPipeError, OSError):
                pass
        finally:
            with self._active_lock:
                self._active -= 1
                if self._active == 0:
                    self._put(_DONE, force=True)

    def _put(self, value, force=False):
        while True:
            try:
                self._results.put(value, timeout=0.1)
                return
            except queue.Full:
                if self._stop_event.is_set() and not force:
                    return

    def results(self):
        while True:
            try:
                value = self._results.get(timeout=self._timeout)
            except queue.Empty:
                raise TimeoutWaitingForResultError(
                    "No worker result within %.0fs" % self._timeout
                ) from None
            if value is _DONE:
                return
            if isinstance(value, _ExcResult):
                self.stop()
                raise value.exc
            yield value

    def stop(self):
        self._stop_event.set()
        try:
            while True:
                self._results.get_nowait()
        except (queue.Empty, AttributeError):
            pass

    def join(self):
        import shutil

        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        self._conns = []
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                p.kill()
        self._procs = []
        if self._tmpdir:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None


def make_executor(reader_pool_type="thread", workers_count=4, results_queue_size=16,
                  results_timeout_s=300.0, serializer="pickle"):
    """Factory matching the reference's ``reader_pool_type`` kwarg ('thread'|'process'|'dummy').

    ``serializer`` ('pickle'|'arrow') selects the process-pool wire format (reference
    Pickle/ArrowTable serializer parity); thread/dummy pools share memory and ignore it.
    """
    if reader_pool_type in ("dummy", "sync"):
        return SyncExecutor()
    if reader_pool_type == "thread":
        return ThreadExecutor(workers_count, results_queue_size, results_timeout_s)
    if reader_pool_type == "process":
        return ProcessExecutor(workers_count, results_queue_size, results_timeout_s,
                               serializer=serializer)
    raise ValueError(
        "Unknown reader_pool_type %r (expected 'thread', 'process' or 'dummy')"
        % reader_pool_type
    )
