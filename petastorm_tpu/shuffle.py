"""Host-side shuffling buffers: decorrelate row order beyond row-group shuffling.

Capability parity with petastorm/shuffling_buffer.py (``ShufflingBufferBase``,
``NoopShufflingBuffer`` ~L40, ``RandomShufflingBuffer`` ~L80) plus a batched variant that
operates on whole column batches (the reference's torch-specific
petastorm/reader_impl/pytorch_shuffling_buffer.py ~L90 generalized to numpy — framework-neutral,
so the JAX, torch and tf adapters all share it).

The on-device (HBM) shuffle lives in petastorm_tpu/ops/device_shuffle.py; these host buffers
are the portable path and the one used below batch-assembly granularity.
"""
from __future__ import annotations

import numpy as np


class ShufflingBufferBase:
    def add_many(self, items):
        raise NotImplementedError

    def retrieve(self):
        raise NotImplementedError

    @property
    def can_add(self):
        raise NotImplementedError

    @property
    def can_retrieve(self):
        raise NotImplementedError

    @property
    def size(self):
        raise NotImplementedError

    def finish(self):
        """Signal no more items will be added; drain remaining."""
        raise NotImplementedError


class NoopShufflingBuffer(ShufflingBufferBase):
    """FIFO passthrough (reference ~L40)."""

    def __init__(self):
        from collections import deque

        self._items = deque()
        self._done = False

    def add_many(self, items):
        self._items.extend(items)

    def retrieve(self):
        return self._items.popleft()

    @property
    def can_add(self):
        return not self._done

    @property
    def can_retrieve(self):
        return len(self._items) > 0

    @property
    def size(self):
        return len(self._items)

    def finish(self):
        self._done = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Bounded reservoir: add until capacity, retrieve uniformly at random once past the
    retrieval threshold (reference ~L80: capacity + ``min_after_retrieve`` semantics).
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve, extra_capacity=1000,
                 seed=None):
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError("min_after_retrieve must be <= capacity")
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._items = []
        self._done = False
        self._rng = np.random.Generator(np.random.PCG64(seed))

    def add_many(self, items):
        items = list(items)
        if self._done:
            raise RuntimeError("Cannot add to a finished shuffling buffer")
        if len(self._items) + len(items) > self._capacity + self._extra_capacity:
            raise RuntimeError(
                "Attempt to add %d items to a buffer at %d/%d capacity; honor can_add "
                "backpressure" % (len(items), len(self._items), self._capacity)
            )
        self._items.extend(items)

    def retrieve(self):
        if not self.can_retrieve:
            raise RuntimeError("Buffer below retrieval threshold and not finished")
        idx = int(self._rng.integers(len(self._items)))
        self._items[idx], self._items[-1] = self._items[-1], self._items[idx]
        return self._items.pop()

    @property
    def can_add(self):
        return len(self._items) < self._capacity and not self._done

    @property
    def can_retrieve(self):
        if self._done:
            return len(self._items) > 0
        return len(self._items) > self._min_after_retrieve

    @property
    def size(self):
        return len(self._items)

    def finish(self):
        self._done = True


class BatchedRandomShufflingBuffer(ShufflingBufferBase):
    """Columnar shuffle: holds {name: ndarray} column batches, retrieves random fixed-size
    batches by index-select — one vectorized gather instead of per-row python shuffling.

    Generalizes the reference's torch-only batched buffer
    (petastorm/reader_impl/pytorch_shuffling_buffer.py ~L90) to numpy.
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve, batch_size, seed=None):
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError("min_after_retrieve must be <= capacity")
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._batch_size = batch_size
        self._staged = None  # {name: list of arrays} awaiting consolidation
        self._store = None  # {name: preallocated ndarray}; rows [0, _store_rows) valid
        self._store_rows = 0  # consolidated rows currently in _store
        self._num_rows = 0  # total rows (consolidated + staged)
        self._done = False
        self._rng = np.random.Generator(np.random.PCG64(seed))

    def add_many(self, column_batch):
        """column_batch: {name: np.ndarray} with equal leading dims."""
        if self._done:
            raise RuntimeError("Cannot add to a finished shuffling buffer")
        names = list(column_batch.keys())
        n = len(column_batch[names[0]])
        if self._staged is None:
            self._staged = {name: [] for name in names}
        for name in names:
            if len(column_batch[name]) != n:
                raise ValueError("Ragged column batch: %r" % name)
            self._staged[name].append(np.asarray(column_batch[name]))
        self._num_rows += n

    def retrieve(self):
        """Return a {name: ndarray} batch of up to batch_size random rows.

        O(batch) data movement per call: selected rows are copied out and the holes are
        back-filled from the buffer tail in place (the previous full-buffer gather of
        the kept rows copied the entire buffer's bytes on every retrieve)."""
        if not self.can_retrieve:
            raise RuntimeError("Buffer below retrieval threshold and not finished")
        self._consolidate()
        n = self._num_rows
        take = min(self._batch_size, n)
        # keep chosen UNSORTED: the gather order is the intra-batch shuffle (sorting
        # would emit rows in buffer-insertion order — FIFO when take ≈ n)
        chosen = self._rng.choice(n, size=take, replace=False)
        out = {}
        tail_start = n - take
        # tail rows that were NOT chosen backfill the holes chosen left below tail_start
        chosen_in_tail = chosen[chosen >= tail_start]
        holes = chosen[chosen < tail_start]
        tail_mask = np.ones(take, dtype=bool)
        tail_mask[chosen_in_tail - tail_start] = False
        for name, store in self._store.items():
            out[name] = store[chosen]  # fancy indexing already allocates fresh rows
            if len(holes):
                store[holes] = store[tail_start:n][tail_mask]
        self._num_rows -= take
        self._store_rows = self._num_rows
        return out

    def _consolidate(self):
        """Move staged chunks into the preallocated store (grown geometrically)."""
        if not self._staged:
            return
        base = self._store_rows
        for name, chunks in self._staged.items():
            if not chunks:
                continue
            add = sum(len(c) for c in chunks)
            store = None if self._store is None else self._store.get(name)
            need = base + add
            if store is None or len(store) < need:
                # grow geometrically toward (not eagerly to) the capacity ceiling: a
                # small dataset must not allocate capacity-sized buffers up front
                limit = max(need, self._capacity + self._batch_size)
                grown = need if store is None else max(need, 2 * len(store))
                grown = min(grown, limit)
                first = chunks[0]
                if self._store is None:
                    self._store = {}
                new = np.empty((grown,) + first.shape[1:], dtype=first.dtype)
                if store is not None:
                    new[:base] = store[:base]
                self._store[name] = store = new
            pos = base
            for c in chunks:
                store[pos:pos + len(c)] = c
                pos += len(c)
            self._staged[name] = []
        self._store_rows = self._num_rows

    @property
    def can_add(self):
        return self._num_rows < self._capacity and not self._done

    @property
    def can_retrieve(self):
        if self._done:
            return self._num_rows > 0
        return self._num_rows >= self._min_after_retrieve + self._batch_size

    @property
    def size(self):
        return self._num_rows

    def finish(self):
        self._done = True
