"""Probabilistic mixing of several readers (reference: petastorm/weighted_sampling_reader.py
~L30 ``WeightedSamplingReader``): each ``next()`` draws one of the underlying readers with the
given probabilities — dataset mixing for multi-corpus training."""
from __future__ import annotations

import numpy as np


class WeightedSamplingReader:
    def __init__(self, readers, probabilities, seed=None):
        if len(readers) != len(probabilities):
            raise ValueError("readers and probabilities must have equal length")
        p = np.asarray(probabilities, dtype=np.float64)
        if (p < 0).any() or p.sum() <= 0:
            raise ValueError("probabilities must be non-negative and sum to > 0")
        self._readers = list(readers)
        self._p = p / p.sum()
        self._rng = np.random.Generator(np.random.PCG64(seed))
        # mixing readers must agree on ngram-ness (reference behavior)
        self.ngram = readers[0].ngram if hasattr(readers[0], "ngram") else None
        # downstream consumers (adapters, the JAX loader) read these off the reader;
        # expose the first reader's and require the others to agree where it matters
        self.schema = getattr(readers[0], "schema", None)
        self.transform_spec = getattr(readers[0], "transform_spec", None)
        self.is_batched_reader = getattr(readers[0], "is_batched_reader", False)
        for r in readers[1:]:
            if getattr(r, "is_batched_reader", False) != self.is_batched_reader:
                raise ValueError(
                    "Cannot mix per-row and batched readers in WeightedSamplingReader"
                )
        fields = getattr(readers[0], "device_decode_fields", frozenset())
        for r in readers[1:]:
            if getattr(r, "device_decode_fields", frozenset()) != fields:
                raise ValueError(
                    "All mixed readers must stage the same device-decode fields; got "
                    "%r vs %r" % (sorted(fields),
                                  sorted(getattr(r, "device_decode_fields", ()))))
        self.device_decode_fields = fields

    def __iter__(self):
        return self

    def __next__(self):
        alive = [i for i, r in enumerate(self._readers) if r is not None]
        while alive:
            p = self._p[alive] / self._p[alive].sum()
            pick = int(self._rng.choice(alive, p=p))
            try:
                return next(self._readers[pick])
            except StopIteration:
                self._readers[pick] = None
                alive = [i for i, r in enumerate(self._readers) if r is not None]
        raise StopIteration

    def stop(self):
        for r in self._readers:
            if r is not None:
                r.stop()

    def join(self):
        for r in self._readers:
            if r is not None:
                r.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()