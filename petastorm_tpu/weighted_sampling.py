"""Probabilistic mixing of several readers (reference: petastorm/weighted_sampling_reader.py
~L30 ``WeightedSamplingReader``): each ``next()`` draws one of the underlying readers with the
given probabilities — dataset mixing for multi-corpus training."""
from __future__ import annotations

import numpy as np


class WeightedSamplingReader:
    def __init__(self, readers, probabilities, seed=None):
        if len(readers) != len(probabilities):
            raise ValueError("readers and probabilities must have equal length")
        p = np.asarray(probabilities, dtype=np.float64)
        if (p < 0).any() or p.sum() <= 0:
            raise ValueError("probabilities must be non-negative and sum to > 0")
        self._readers = list(readers)
        self._p = p / p.sum()
        self._rng = np.random.Generator(np.random.PCG64(seed))
        # mixing readers must agree on ngram-ness (reference behavior)
        self.ngram = readers[0].ngram if hasattr(readers[0], "ngram") else None
        # downstream consumers (adapters, the JAX loader) read these off the reader;
        # expose the first reader's and require the others to agree where it matters
        self.schema = getattr(readers[0], "schema", None)
        self.transform_spec = getattr(readers[0], "transform_spec", None)
        self.is_batched_reader = getattr(readers[0], "is_batched_reader", False)
        for r in readers[1:]:
            if getattr(r, "is_batched_reader", False) != self.is_batched_reader:
                raise ValueError(
                    "Cannot mix per-row and batched readers in WeightedSamplingReader"
                )
        fields = getattr(readers[0], "device_decode_fields", frozenset())
        for r in readers[1:]:
            if getattr(r, "device_decode_fields", frozenset()) != fields:
                raise ValueError(
                    "All mixed readers must stage the same device-decode fields; got "
                    "%r vs %r" % (sorted(fields),
                                  sorted(getattr(r, "device_decode_fields", ()))))
        self.device_decode_fields = fields

        #: final cursor of each exhausted sub-reader (captured at exhaustion so a
        #: later ``state_dict()`` can still checkpoint it as fully-consumed)
        self._final_states = {}

    def __iter__(self):
        return self

    def __next__(self):
        alive = [i for i, r in enumerate(self._readers) if r is not None]
        while alive:
            p = self._p[alive] / self._p[alive].sum()
            pick = int(self._rng.choice(alive, p=p))
            try:
                return next(self._readers[pick])
            except StopIteration:
                exhausted = self._readers[pick]
                if hasattr(exhausted, "state_dict"):
                    self._final_states[pick] = exhausted.state_dict()
                self._readers[pick] = None
                alive = [i for i, r in enumerate(self._readers) if r is not None]
        raise StopIteration

    # -- exact resume -------------------------------------------------------------------

    def state_dict(self):
        """Exact-resume state for the stochastic mixer: the mixing RNG's full state
        plus every sub-reader's cursor (the final cursor for already-exhausted
        ones). Restoring into a same-config mixer continues the SAME draw sequence
        with each sub-reader at its own cursor — sub-reader semantics are the
        usual at-least-once at row-group granularity, so a replayed in-flight
        group may shift which rows later draws return; the mix proportions and
        coverage guarantees are unchanged. (A sub-reader that was exhausted at
        save time restores as empty and is re-discovered exhausted on its first
        draw, which costs extra RNG draws relative to the uninterrupted run —
        draw-for-draw equality holds while every sub-reader is live.)
        Duck-types for :mod:`petastorm_tpu.checkpoint` like every other
        reader/loader."""
        states = []
        for i, r in enumerate(self._readers):
            if r is None:
                final = self._final_states.get(i)
                if final is None:
                    # exhausted BEFORE capture was possible: the sub-reader never
                    # had a state_dict — restoring it fresh would silently replay
                    # its whole corpus, so refuse exactly like the live case
                    raise AttributeError(
                        "sub-reader %d was exhausted without a capturable state "
                        "(no state_dict); WeightedSamplingReader can only "
                        "checkpoint checkpointable readers" % i)
                states.append(final)
            elif hasattr(r, "state_dict"):
                states.append(r.state_dict())
            else:
                raise AttributeError(
                    "sub-reader %d (%s) has no state_dict; WeightedSamplingReader "
                    "can only checkpoint checkpointable readers"
                    % (i, type(r).__name__))
        return {
            "weighted": True,
            "rng_state": self._rng.bit_generator.state,
            "readers": states,
        }

    def load_state_dict(self, state):
        """Restore into a mixer built over FRESH same-config sub-readers."""
        if not state.get("weighted"):
            raise ValueError(
                "not a WeightedSamplingReader state (single-reader checkpoint? "
                "restore it into that reader instead)")
        saved = state["readers"]
        if len(saved) != len(self._readers):
            raise ValueError(
                "saved state mixes %d readers, this mixer has %d — rebuild with "
                "the original composition" % (len(saved), len(self._readers)))
        for i, (sub_state, r) in enumerate(zip(saved, self._readers)):
            if r is None:
                raise ValueError(
                    "sub-reader %d of this mixer is already exhausted — restore "
                    "requires a FRESHLY built mixer over unconsumed same-config "
                    "sub-readers" % i)
            if sub_state is None:
                raise ValueError(
                    "saved state for sub-reader %d is empty (checkpoint from an "
                    "incompatible version?)" % i)
            r.load_state_dict(sub_state)
        self._rng.bit_generator.state = state["rng_state"]
        self._final_states = {}
        return self

    def stop(self):
        for r in self._readers:
            if r is not None:
                r.stop()

    def join(self):
        for r in self._readers:
            if r is not None:
                r.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()