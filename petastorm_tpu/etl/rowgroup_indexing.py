"""Row-group indexing: build value→row-group indexes enabling ``rowgroup_selector`` pruning.

Capability parity with petastorm/etl/rowgroup_indexing.py (``build_rowgroup_index`` ~L40,
``get_row_group_indexes`` ~L100) and petastorm/etl/row_group_indexers.py
(``SingleFieldIndexer`` ~L30). The reference builds indexes with a Spark job and stores them
pickled+zlib in ``_metadata``; here the build is a plain pyarrow scan (no cluster needed for
the datasets this targets) and storage is zlib'd JSON under our own KV key.
"""
from __future__ import annotations

import json
import posixpath
import zlib

from petastorm_tpu.metadata import (
    PTPU_ROW_GROUPS_KEY,
    PTPU_SCHEMA_KEY,
    _read_kv_metadata,
    load_row_groups,
)

PTPU_INDEX_KEY = b"petastorm_tpu.rowgroup_index.json.zlib.v1"


class SingleFieldIndexer:
    """Maps each distinct value of one field to the set of row-group ordinals containing it."""

    def __init__(self, index_name, index_field):
        self.index_name = index_name
        self.index_field = index_field
        self._index = {}

    def add(self, value, row_group_ordinal):
        self._index.setdefault(_key(value), set()).add(int(row_group_ordinal))

    def get_row_group_indexes(self, value=None):
        if value is None:
            return sorted(set().union(*self._index.values())) if self._index else []
        return sorted(self._index.get(_key(value), set()))

    @property
    def indexed_values(self):
        return sorted(self._index.keys())

    def to_jsonable(self):
        return {
            "field": self.index_field,
            "values": {k: sorted(v) for k, v in self._index.items()},
        }

    @classmethod
    def from_jsonable(cls, index_name, payload):
        idx = cls(index_name, payload["field"])
        idx._index = {k: set(v) for k, v in payload["values"].items()}
        return idx


def _key(value):
    return str(value)


def build_rowgroup_index(dataset_url, indexers, storage_options=None, filesystem=None):
    """Scan the dataset once and persist the requested indexes in ``_common_metadata``.

    ``indexers``: list of :class:`SingleFieldIndexer` (empty ``_index``; filled here).
    """
    import pyarrow.parquet as pq

    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    from petastorm_tpu.metadata import get_schema

    fs, path = get_filesystem_and_path_or_paths(dataset_url, storage_options, filesystem)
    schema = get_schema(fs, path)
    pieces = load_row_groups(fs, path)
    fields = sorted({ix.index_field for ix in indexers})
    for name in fields:
        if name not in schema.fields:
            raise ValueError("Cannot index unknown field %r" % name)
    for ordinal, piece in enumerate(pieces):
        with fs.open_input_file(piece.path) as f:
            table = pq.ParquetFile(f).read_row_group(piece.row_group, columns=fields)
        for ix in indexers:
            field = schema.fields[ix.index_field]
            stored = table.column(ix.index_field).to_pylist()
            for v in stored:
                if field.codec is not None:
                    v = field.codec.decode(field, v)
                ix.add(v, ordinal)
    _write_index_metadata(fs, path, {ix.index_name: ix for ix in indexers})
    return indexers


def _write_index_metadata(fs, path, index_dict):
    import pyarrow.parquet as pq

    kv = _read_kv_metadata(fs, path) or {}
    payload = {name: ix.to_jsonable() for name, ix in index_dict.items()}
    kv[PTPU_INDEX_KEY] = zlib.compress(json.dumps(payload).encode("utf-8"))
    meta_path = posixpath.join(path, "_common_metadata")
    with fs.open_input_file(meta_path) as f:
        arrow_schema = pq.read_schema(f)
    with fs.open_output_stream(meta_path) as sink:
        pq.write_metadata(arrow_schema.with_metadata(kv), sink)


def get_row_group_indexes(fs, path):
    """Load {index_name: SingleFieldIndexer} from dataset metadata."""
    kv = _read_kv_metadata(fs, path)
    if not kv or PTPU_INDEX_KEY not in kv:
        raise ValueError(
            "Dataset at %r has no row-group index; run build_rowgroup_index first" % path
        )
    payload = json.loads(zlib.decompress(kv[PTPU_INDEX_KEY]).decode("utf-8"))
    return {
        name: SingleFieldIndexer.from_jsonable(name, body) for name, body in payload.items()
    }