"""etl subpackage."""
