"""Column codecs: encode tensor/scalar fields into Parquet-storable values and back.

Capability parity with the reference codec set (petastorm/codecs.py: ``DataframeColumnCodec``
~L30, ``ScalarCodec`` ~L60, ``NdarrayCodec`` ~L130, ``CompressedNdarrayCodec`` ~L170,
``CompressedImageCodec`` ~L200), redesigned for a TPU pipeline:

- The storage half (``encode``/``decode``) is host-side and Spark-free: codecs speak numpy and
  pyarrow types; Spark types are derived on demand (``spark_dtype`` needs pyspark only when
  actually writing through Spark).
- Codecs that admit an on-device decode path advertise it via ``device_decodable`` — the JAX
  loader batches the *encoded* bytes to the host staging area and runs the heavy half of the
  decode (dequant+IDCT+color for JPEG) as a Pallas kernel instead of per-row cv2 calls
  (see petastorm_tpu/ops/jpeg.py). ``decode`` always remains available as the portable path.
"""
from __future__ import annotations

import io
import zlib

import numpy as np

from petastorm_tpu import types as ptypes


class DataframeColumnCodec:
    """Base codec contract (reference: petastorm/codecs.py ~L30)."""

    #: True when ops/ has a Pallas decode kernel for this codec's payload.
    device_decodable = False

    def encode(self, unischema_field, value):
        """Encode ``value`` into a Parquet-storable python value (scalar or bytes)."""
        raise NotImplementedError

    def decode(self, unischema_field, encoded):
        """Decode a stored value back into the numpy value declared by the field."""
        raise NotImplementedError

    def host_stage_decode(self, unischema_field, encoded):
        """On-device decode path, host half: stored value → staging object the reader
        pool produces in parallel (e.g. JPEG entropy decode → coefficient planes).
        Only meaningful when :attr:`device_decodable` is True."""
        raise NotImplementedError(
            "%s does not support on-device decode" % type(self).__name__
        )

    def device_decode_batch(self, unischema_field, staged, resize_to=None,
                            sharding=None):
        """On-device decode path, device half: list of staging objects (one per row) →
        one batched device array matching :meth:`decode`'s per-row output contract.
        ``resize_to=(h, w)`` (image codecs) asks for an on-device resize to one
        static shape so mixed-size stores can batch. ``sharding`` (optional batch-axis
        sharding) asks the decode to run SPMD — one batch shard per device."""
        raise NotImplementedError(
            "%s does not support on-device decode" % type(self).__name__
        )

    def arrow_dtype(self, unischema_field=None):
        """pyarrow storage type for this codec's column."""
        raise NotImplementedError

    def spark_dtype(self):
        """pyspark storage type (requires pyspark; only needed on the Spark write path)."""
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash(type(self).__name__)


class ScalarCodec(DataframeColumnCodec):
    """Stores a scalar in a typed Parquet column (reference: petastorm/codecs.py ~L60).

    Accepts either a :mod:`petastorm_tpu.types` tag or (when pyspark is installed) a
    ``pyspark.sql.types`` instance, which is converted to the equivalent tag.
    """

    def __init__(self, scalar_type):
        if not isinstance(scalar_type, ptypes.ScalarType):
            scalar_type = _tag_from_spark_type(scalar_type)
        self._scalar_type = scalar_type

    @property
    def scalar_type(self):
        return self._scalar_type

    def encode(self, unischema_field, value):
        if isinstance(value, np.ndarray):
            if value.ndim != 0 and value.size != 1:
                raise ValueError(
                    "Expected a scalar for field %r, got array of shape %r"
                    % (unischema_field.name, value.shape)
                )
            value = value.reshape(())[()]
        t = self._scalar_type
        if isinstance(t, (ptypes.StringType,)):
            return str(value)
        if isinstance(t, ptypes.BinaryType):
            return bytes(value)
        if isinstance(t, ptypes.BooleanType):
            return bool(value)
        if isinstance(t, ptypes.DecimalType):
            import decimal

            return decimal.Decimal(str(value))
        if isinstance(t, (ptypes.DateType, ptypes.TimestampType)):
            return value
        np_dtype = t.to_numpy_dtype()
        if np_dtype.kind in "iu":
            return int(value)
        if np_dtype.kind == "f":
            return float(value)
        return value

    def decode(self, unischema_field, encoded):
        import decimal

        if isinstance(self._scalar_type, ptypes.DecimalType) or isinstance(
            encoded, decimal.Decimal
        ):
            # Reference keeps Decimal as decimal.Decimal on decode (petastorm/codecs.py ~L110)
            return decimal.Decimal(encoded) if not isinstance(encoded, decimal.Decimal) else encoded
        np_dtype = np.dtype(unischema_field.numpy_dtype)
        if np_dtype.kind in ("U", "S", "O"):
            return encoded
        return np_dtype.type(encoded)

    def arrow_dtype(self, unischema_field=None):
        return self._scalar_type.arrow_type()

    def spark_dtype(self):
        return self._scalar_type.spark_type()

    def __setstate__(self, state):
        # Reference ScalarCodec pickles as {'_spark_type': <pyspark type>} (petastorm/codecs.py
        # ~L60); the compat unpickler maps pyspark type classes onto our tags already.
        if "_spark_type" in state and "_scalar_type" not in state:
            spark_type = state["_spark_type"]
            if isinstance(spark_type, ptypes.ScalarType):
                self._scalar_type = spark_type
            else:
                self._scalar_type = _tag_from_spark_type(spark_type)
        else:
            self.__dict__.update(state)

    def __repr__(self):
        return "ScalarCodec(%r)" % (self._scalar_type,)

    def __eq__(self, other):
        return type(self) is type(other) and self._scalar_type == other._scalar_type

    def __hash__(self):
        return hash((type(self).__name__, self._scalar_type))


class NdarrayCodec(DataframeColumnCodec):
    """Stores an ndarray as npy bytes in a binary column (reference: petastorm/codecs.py ~L130)."""

    def encode(self, unischema_field, value):
        expected = np.dtype(unischema_field.numpy_dtype)
        if not isinstance(value, np.ndarray):
            raise ValueError(
                "Expected numpy ndarray for field %r, got %r"
                % (unischema_field.name, type(value))
            )
        if value.dtype != expected:
            raise ValueError(
                "Field %r expected dtype %r, got %r"
                % (unischema_field.name, expected, value.dtype)
            )
        _check_shape(unischema_field, value)
        memfile = io.BytesIO()
        # allow_pickle=False so object-dtype arrays fail fast at write time instead of
        # becoming unreadable at decode time (decode also forbids pickle).
        np.save(memfile, value, allow_pickle=False)
        return bytearray(memfile.getvalue())

    def decode(self, unischema_field, encoded):
        memfile = io.BytesIO(encoded)
        return np.load(memfile, allow_pickle=False)

    def arrow_dtype(self, unischema_field=None):
        import pyarrow as pa

        return pa.binary()

    def spark_dtype(self):
        import pyspark.sql.types as T

        return T.BinaryType()


class CompressedNdarrayCodec(DataframeColumnCodec):
    """npy bytes + zlib (reference: petastorm/codecs.py ~L170)."""

    def encode(self, unischema_field, value):
        raw = NdarrayCodec().encode(unischema_field, value)
        return bytearray(zlib.compress(bytes(raw)))

    def decode(self, unischema_field, encoded):
        return NdarrayCodec().decode(unischema_field, zlib.decompress(encoded))

    def arrow_dtype(self, unischema_field=None):
        import pyarrow as pa

        return pa.binary()

    def spark_dtype(self):
        import pyspark.sql.types as T

        return T.BinaryType()


class CompressedImageCodec(DataframeColumnCodec):
    """PNG/JPEG image bytes (reference: petastorm/codecs.py ~L200, cv2 imencode/imdecode).

    TPU note: for ``jpeg`` payloads the loader can route decode through the two-stage path —
    host entropy decode to quantized DCT coefficients, then a Pallas dequant+IDCT+upsample+YCbCr
    kernel on device (petastorm_tpu/ops/jpeg.py). ``decode`` here is the portable host path.
    """

    def __init__(self, image_codec="png", quality=80):
        if image_codec not in ("png", "jpeg", "jpg"):
            raise ValueError("Unsupported image codec %r" % image_codec)
        self._image_codec = "jpeg" if image_codec == "jpg" else image_codec
        self._quality = int(quality)

    @property
    def image_codec(self):
        return self._image_codec

    @property
    def device_decodable(self):
        return self._image_codec == "jpeg"

    def encode(self, unischema_field, value):
        if not isinstance(value, np.ndarray):
            raise ValueError("Expected ndarray image for field %r" % unischema_field.name)
        if np.dtype(unischema_field.numpy_dtype) != value.dtype:
            raise ValueError(
                "Field %r expected dtype %r, got %r"
                % (unischema_field.name, unischema_field.numpy_dtype, value.dtype)
            )
        _check_shape(unischema_field, value)
        import cv2

        if self._image_codec == "png":
            ok, contents = cv2.imencode(".png", value)
        else:
            ok, contents = cv2.imencode(
                ".jpeg", value, [int(cv2.IMWRITE_JPEG_QUALITY), self._quality]
            )
        if not ok:
            raise ValueError("cv2.imencode failed for field %r" % unischema_field.name)
        return bytearray(contents.tobytes())

    def decode(self, unischema_field, encoded):
        import cv2

        from petastorm_tpu.errors import DecodeFieldError

        # np.frombuffer reads bytes/bytearray/memoryview alike — no intermediate copy
        img = cv2.imdecode(np.frombuffer(encoded, dtype=np.uint8), cv2.IMREAD_UNCHANGED)
        if img is None:
            raise DecodeFieldError(
                "cv2.imdecode failed for field %r (stream is corrupt or uses a JPEG "
                "family cv2 does not support, e.g. lossless)" % unischema_field.name)
        return img.astype(np.dtype(unischema_field.numpy_dtype), copy=False)

    def host_stage_decode(self, unischema_field, encoded):
        """JPEG bytes → quantized DCT coefficient planes (native C++ entropy decode,
        GIL-released — the reader pool's parallel half of the two-stage decode).

        Streams the two-stage path cannot handle (lossless/arithmetic, CMYK, corrupt-for-us)
        fall back to the full host decode per row; the loader stacks those alongside
        the device-decoded rows."""
        if not self.device_decodable:
            raise NotImplementedError("on-device decode is only available for jpeg")
        from petastorm_tpu.errors import DecodeFieldError
        from petastorm_tpu.ops.jpeg import entropy_decode_jpeg_fast

        try:
            return entropy_decode_jpeg_fast(bytes(encoded))
        except ValueError as stage_err:
            try:
                return self.decode(unischema_field, encoded)
            except DecodeFieldError as host_err:
                # neither path can decode this stream (e.g. lossless or
                # arithmetic-coded JPEG): surface ONE error naming the field and
                # both failures instead of an opaque cv2 message from the pool
                raise DecodeFieldError(
                    "Field %r: stream is decodable by neither the two-stage device "
                    "path (%s) nor host cv2 (%s)"
                    % (unischema_field.name, stage_err, host_err)) from host_err

    def host_stage_decode_batch(self, unischema_field, values):
        """Sequence of encoded blobs (``None`` entries preserved) → list of staging
        payloads, one native call per row group when possible.

        The batched stage 1 (petastorm_tpu/ops/jpeg.py ``entropy_decode_jpeg_batch``)
        entropy-decodes every same-layout stream into stacked buffers in one
        GIL-released native call; streams it cannot handle (lossless/arithmetic, corrupt,
        layout differs from the group) fall back to :meth:`host_stage_decode`
        individually, so the output mixes ``JpegPlanes`` and host-decoded ndarrays
        exactly like the per-row path."""
        if not self.device_decodable:
            raise NotImplementedError("on-device decode is only available for jpeg")
        idx = [i for i, v in enumerate(values) if v is not None]
        out = [None] * len(values)
        if not idx:
            return out
        blobs = [bytes(values[i]) for i in idx]
        planes = None
        try:
            from petastorm_tpu.ops.jpeg import entropy_decode_jpeg_batch

            planes = entropy_decode_jpeg_batch(blobs)
        except (ValueError, RuntimeError):
            planes = None
        if planes is None:
            for i in idx:
                out[i] = self.host_stage_decode(unischema_field, values[i])
            return out
        for j, i in enumerate(idx):
            p = planes[j]
            out[i] = p if p is not None \
                else self.host_stage_decode(unischema_field, blobs[j])
        return out

    def device_decode_batch(self, unischema_field, staged, resize_to=None,
                            sharding=None):
        """Coefficient planes (one per row) → (n, ...) uint8 device array, one batched
        Pallas dispatch. Matches :meth:`decode`'s per-row contract: cv2 returns images
        in stored (BGR for color) channel order and 2-D for grayscale fields, so the
        RGB device output is flipped / channel-stripped accordingly. Rows that fell
        back to host decode in :meth:`host_stage_decode` arrive as ndarrays and are
        merged in at their original positions.

        ``resize_to=(h, w)`` enables mixed-size stores: device rows resize on device
        after decode (:func:`petastorm_tpu.ops.jpeg.resize_image_batch`), host
        fallbacks via ``cv2.resize`` INTER_LINEAR — the matching sampling.

        ``sharding``: optional batch-axis sharding; the coefficient slabs are placed
        across its devices before the stage-2 jit so decode runs SPMD (one batch
        shard per device) instead of serializing on the default device."""
        if not self.device_decodable:
            raise NotImplementedError("on-device decode is only available for jpeg")
        import jax.numpy as jnp

        from petastorm_tpu.ops.jpeg import JpegPlanes, decode_jpeg_batch

        staged = list(staged)
        plane_idx = [i for i, s in enumerate(staged) if isinstance(s, JpegPlanes)]
        host_idx = [i for i in range(len(staged)) if i not in set(plane_idx)]
        shape = unischema_field.shape
        grayscale = shape is not None and len(shape) == 2

        parts = []
        order = []
        if plane_idx:
            img = decode_jpeg_batch([staged[i] for i in plane_idx],
                                    resize_to=resize_to, sharding=sharding)
            img = img[..., 0] if grayscale else img[..., ::-1]
            parts.append(img)
            order.extend(plane_idx)
        if host_idx:
            # host-decoded fallbacks are already in stored order; no flip
            fallbacks = [staged[i] for i in host_idx]
            if resize_to is not None:
                import cv2

                h, w = int(resize_to[0]), int(resize_to[1])
                fallbacks = [
                    f if f.shape[0] == h and f.shape[1] == w
                    else cv2.resize(f, (w, h), interpolation=cv2.INTER_LINEAR)
                    for f in fallbacks
                ]
            parts.append(jnp.asarray(np.stack(fallbacks)))
            order.extend(host_idx)
        if len(parts) == 1:
            out = parts[0]
        else:
            out = jnp.concatenate(parts, axis=0)
        inverse = np.argsort(np.asarray(order))
        if not np.array_equal(inverse, np.arange(len(staged))):
            out = out[jnp.asarray(inverse)]
        return out

    def arrow_dtype(self, unischema_field=None):
        import pyarrow as pa

        return pa.binary()

    def spark_dtype(self):
        import pyspark.sql.types as T

        return T.BinaryType()

    def __setstate__(self, state):
        # Reference CompressedImageCodec stores the cv2 extension string ('.png'/'.jpeg',
        # petastorm/codecs.py ~L200); normalize on unpickle.
        codec = state.get("_image_codec", "png").lstrip(".")
        codec = "jpeg" if codec == "jpg" else codec
        if codec not in ("png", "jpeg"):
            raise ValueError("Unsupported image codec %r in pickled state" % codec)
        self._image_codec = codec
        self._quality = int(state.get("_quality", 80))

    def __repr__(self):
        return "CompressedImageCodec(%r, quality=%d)" % (self._image_codec, self._quality)


def _check_shape(unischema_field, value):
    shape = unischema_field.shape
    if shape is None:
        return
    if len(shape) != value.ndim:
        raise ValueError(
            "Field %r declares rank %d, got array rank %d"
            % (unischema_field.name, len(shape), value.ndim)
        )
    for declared, actual in zip(shape, value.shape):
        if declared is not None and declared != actual:
            raise ValueError(
                "Field %r declares shape %r, got %r"
                % (unischema_field.name, shape, value.shape)
            )


def _tag_from_spark_type(spark_type):
    """Map a pyspark.sql.types instance onto our ScalarType tag (pyspark optional)."""
    name = type(spark_type).__name__
    if name == "DecimalType":
        return ptypes.DecimalType(spark_type.precision, spark_type.scale)
    tag_cls = getattr(ptypes, name, None)
    if tag_cls is None or not issubclass(tag_cls, ptypes.ScalarType):
        raise ValueError("Unsupported scalar type %r" % (spark_type,))
    return tag_cls()
