"""Lightweight scalar type tags.

The reference (petastorm/codecs.py ~L60 ``ScalarCodec``) parameterizes scalar codecs with
``pyspark.sql.types`` instances, dragging a Spark dependency into the core data model. Here the
core is Spark-free: these tags carry the (numpy dtype, arrow dtype, spark name) triple and are the
single place all three type systems meet. When pyspark *is* installed the tags convert losslessly
via :meth:`ScalarType.spark_type`; when it is not, everything else still works.

These classes are also the unpickling shim for reference-written datasets: pickled petastorm
unischemas embed ``pyspark.sql.types`` instances inside ``ScalarCodec``; our compat unpickler maps
those module paths onto these classes (see petastorm_tpu/compat/reference.py).
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa


class ScalarType:
    """Base scalar type tag. Subclasses define numpy/arrow/spark equivalents."""

    #: numpy dtype string
    numpy_dtype: str = None
    #: pyarrow DataType factory result
    _arrow: "pa.DataType" = None
    #: pyspark class name (for as_spark_schema / compat unpickling)
    spark_name: str = None

    def arrow_type(self) -> "pa.DataType":
        return self._arrow

    def to_numpy_dtype(self):
        return np.dtype(self.numpy_dtype)

    def spark_type(self):
        """Return the equivalent pyspark.sql.types instance (requires pyspark)."""
        import pyspark.sql.types as T  # deferred; pyspark optional

        return getattr(T, self.spark_name)()

    def simpleString(self):  # noqa: N802 - matches pyspark API
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self):
        return "%s()" % type(self).__name__

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self).__name__)

    # pyspark type instances pickle as empty-state objects; accept that on unpickle.
    def __setstate__(self, state):
        pass

    def __getstate__(self):
        return {}


class BooleanType(ScalarType):
    numpy_dtype = "bool_"
    _arrow = pa.bool_()
    spark_name = "BooleanType"


class ByteType(ScalarType):
    numpy_dtype = "int8"
    _arrow = pa.int8()
    spark_name = "ByteType"


class ShortType(ScalarType):
    numpy_dtype = "int16"
    _arrow = pa.int16()
    spark_name = "ShortType"


class IntegerType(ScalarType):
    numpy_dtype = "int32"
    _arrow = pa.int32()
    spark_name = "IntegerType"


class LongType(ScalarType):
    numpy_dtype = "int64"
    _arrow = pa.int64()
    spark_name = "LongType"


class FloatType(ScalarType):
    numpy_dtype = "float32"
    _arrow = pa.float32()
    spark_name = "FloatType"


class DoubleType(ScalarType):
    numpy_dtype = "float64"
    _arrow = pa.float64()
    spark_name = "DoubleType"


class StringType(ScalarType):
    numpy_dtype = "object"
    _arrow = pa.string()
    spark_name = "StringType"


class BinaryType(ScalarType):
    numpy_dtype = "object"
    _arrow = pa.binary()
    spark_name = "BinaryType"


class DateType(ScalarType):
    numpy_dtype = "datetime64[D]"
    _arrow = pa.date32()
    spark_name = "DateType"


class TimestampType(ScalarType):
    numpy_dtype = "datetime64[us]"
    _arrow = pa.timestamp("us")
    spark_name = "TimestampType"


class DecimalType(ScalarType):
    """Decimal(precision, scale); decodes to python decimal.Decimal (reference behavior)."""

    numpy_dtype = "object"
    spark_name = "DecimalType"

    def __init__(self, precision=10, scale=0):
        self.precision = precision
        self.scale = scale

    def arrow_type(self):
        return pa.decimal128(self.precision, self.scale)

    def spark_type(self):
        import pyspark.sql.types as T

        return T.DecimalType(self.precision, self.scale)

    def simpleString(self):  # noqa: N802
        return "decimal(%d,%d)" % (self.precision, self.scale)

    def __repr__(self):
        return "DecimalType(%d,%d)" % (self.precision, self.scale)

    def __eq__(self, other):
        return (
            type(self) is type(other)
            and self.precision == other.precision
            and self.scale == other.scale
        )

    def __hash__(self):
        return hash((self.precision, self.scale))

    def __setstate__(self, state):
        # pyspark DecimalType pickles its __dict__ {precision, scale, hasPrecisionInfo}
        self.precision = state.get("precision", 10)
        self.scale = state.get("scale", 0)

    def __getstate__(self):
        return {"precision": self.precision, "scale": self.scale}


_NUMPY_TO_TAG = {
    np.dtype("bool"): BooleanType,
    np.dtype("int8"): ByteType,
    np.dtype("int16"): ShortType,
    np.dtype("int32"): IntegerType,
    np.dtype("int64"): LongType,
    np.dtype("float32"): FloatType,
    np.dtype("float64"): DoubleType,
    np.dtype("uint8"): ShortType,  # parquet has no uint8 logical in spark land; widen
    np.dtype("uint16"): IntegerType,
    np.dtype("uint32"): LongType,
}


def tag_for_numpy_dtype(dtype, string_ok=True):
    """Best-effort ScalarType tag for a numpy dtype (used by plain/scalar columns)."""
    dtype = np.dtype(dtype)
    if dtype in _NUMPY_TO_TAG:
        return _NUMPY_TO_TAG[dtype]()
    if dtype.kind in ("U", "S", "O") and string_ok:
        return StringType()
    if dtype.kind == "M":
        return TimestampType()
    raise ValueError("No scalar type tag for numpy dtype %r" % dtype)
