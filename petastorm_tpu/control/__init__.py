"""Closed-loop pipeline control (ISSUE 13; ROADMAP item 4).

Three layers:

- :mod:`petastorm_tpu.control.knobs` — :class:`Knob`/:class:`KnobSet`, the
  sanctioned bounded live-retune seam over the components' ``apply_*()``
  setters (options structs stay frozen; graftlint GL-C004 enforces it), and
  :func:`build_knobset` wiring the standard knobs for a running reader.
- :mod:`petastorm_tpu.control.controller` — the :class:`Controller` policy
  engine riding the PR 12 window cadence: declarative :class:`PolicyRule`\\ s
  with hysteresis, debounce, cooldowns, step limits, warmup, and the global
  revert-and-freeze no-gain guard.
- the acceptance harness lives in :mod:`petastorm_tpu.benchmark.autotune`
  (``petastorm-tpu-bench autotune``): injected bottlenecks with wrong initial
  knobs must converge live; a clean run must see zero actuations.

``DataLoader(controller=True, metrics=..., provenance=True)`` wires all of it.
"""
from petastorm_tpu.control.controller import (  # noqa: F401
    ControlOptions,
    Controller,
    Decision,
    PolicyRule,
    WindowContext,
    default_rules,
)
from petastorm_tpu.control.knobs import Knob, KnobSet, build_knobset  # noqa: F401

__all__ = ["Knob", "KnobSet", "build_knobset", "Controller", "ControlOptions",
           "Decision", "PolicyRule", "WindowContext", "default_rules"]
