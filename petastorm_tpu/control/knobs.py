"""Live pipeline knobs: the sanctioned, bounded, thread-safe actuation seam.

PRs 8–10 froze the tuning surface at construction: ``IoOptions`` /
``RemoteIoOptions`` travel to the workers as picklable structs and every
component (readahead pool, ranged-GET engine, cache tiers, executors) reads
its knobs once and never again. That is the right contract for *config* —
options stay immutable, shareable and picklable — but it leaves a running
pipeline tuned for yesterday's bottleneck. This module adds the one sanctioned
mutation seam (ISSUE 13):

- Components grew ``apply_*()`` setters (``ReadaheadPool.apply_depth``,
  ``RemoteReadEngine.apply_max_inflight``, ``ThreadExecutor.resize``, ...)
  that retune LIVE state under the component's own lock. The ``*Options``
  structs are never mutated — graftlint GL-C004 flags any post-construction
  options-field assignment outside this seam.
- :class:`Knob` describes one tunable: bounds, default, and the getter/setter
  closures binding it to a live component.
- :class:`KnobSet` is the registry the controller actuates through:
  ``apply()`` clamps into the knob's bounds, calls the setter, and records
  the change; ``describe()``/``collect()`` expose the LIVE values (satellite:
  dashboards and the controller's own feedback must read the truth after a
  retune, not the construction-time configuration).

:func:`build_knobset` wires the standard knobs for a running
:class:`~petastorm_tpu.reader.Reader`: worker-fleet size on every resizable
pool; the IO knobs (readahead depth/bytes, GET pool width, hedge quantile,
mem-tier budget, disk admission) when the worker runs in-process (thread/
dummy pools — a process pool's children own their IO runtimes in other
processes, where a parent-side setter cannot reach; their knobs bind at the
next spawn via the worker's pickled overrides).
"""
from __future__ import annotations

import threading

#: enum knobs export their value as the index into ``values`` (Prometheus
#: gauges are numeric); ``describe()`` carries the string
ENUM = "enum"
NUMERIC = "numeric"


class Knob:
    """One live tunable: bounds + the closures binding it to a component.

    ``get()`` returns the live value; ``apply(value)`` retunes the component
    and returns the value actually applied (a component may quantize). For
    ``kind="enum"`` the domain is ``values`` instead of ``[lo, hi]``.
    """

    __slots__ = ("name", "kind", "get", "apply_fn", "lo", "hi", "default",
                 "values", "integer", "unit")

    def __init__(self, name, get, apply_fn, lo=None, hi=None, default=None,
                 values=None, integer=True, unit=""):
        self.name = name
        self.get = get
        self.apply_fn = apply_fn
        self.kind = ENUM if values is not None else NUMERIC
        self.values = tuple(values) if values is not None else None
        self.lo = lo
        self.hi = hi
        self.default = default if default is not None else get()
        self.integer = bool(integer)
        self.unit = unit

    def clamp(self, value):
        """The in-bounds value closest to ``value`` (identity for enums that
        are already members; ValueError otherwise — an enum has no nearest
        neighbor to guess)."""
        if self.kind == ENUM:
            if value not in self.values:
                raise ValueError("knob %r accepts %s, got %r"
                                 % (self.name, self.values, value))
            return value
        value = float(value)
        if self.lo is not None:
            value = max(float(self.lo), value)
        if self.hi is not None:
            value = min(float(self.hi), value)
        if self.integer:
            value = int(round(value))
        return value

    def numeric_value(self, value=None):
        """The knob's value as a number (enum -> index): the export shape."""
        value = self.get() if value is None else value
        if self.kind == ENUM:
            try:
                return self.values.index(value)
            except ValueError:
                return -1
        return value


class KnobSet:
    """Thread-safe registry of live knobs — the controller's actuation seam.

    All mutation goes through :meth:`apply` (bounded, serialized under one
    lock, recorded); reads (:meth:`get`/:meth:`describe`/:meth:`collect`)
    return LIVE component state. ``checkpoint()``/``restore()`` are the
    controller's revert mechanism.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._knobs = {}

    # -- registration -------------------------------------------------------------------

    def add(self, knob):
        with self._lock:
            if knob.name in self._knobs:
                raise ValueError("knob %r already registered" % knob.name)
            self._knobs[knob.name] = knob
        return knob

    def numeric(self, name, get, apply_fn, lo, hi, default=None, integer=True,
                unit=""):
        return self.add(Knob(name, get, apply_fn, lo=lo, hi=hi,
                             default=default, integer=integer, unit=unit))

    def enum(self, name, get, apply_fn, values, default=None):
        return self.add(Knob(name, get, apply_fn, values=values,
                             default=default))

    # -- reads --------------------------------------------------------------------------

    def __contains__(self, name):
        with self._lock:
            return name in self._knobs

    def names(self):
        with self._lock:
            return sorted(self._knobs)

    def knob(self, name):
        with self._lock:
            return self._knobs[name]

    def get(self, name):
        """The LIVE value of ``name`` (reads the component, not a cache)."""
        return self.knob(name).get()

    def describe(self):
        """``{name: {"value", "default", "lo", "hi"/"values", "unit"}}`` —
        live values beside their configured defaults (the stats panel's knob
        table)."""
        with self._lock:
            knobs = dict(self._knobs)
        out = {}
        for name, knob in knobs.items():
            entry = {"value": knob.get(), "default": knob.default,
                     "unit": knob.unit}
            if knob.kind == ENUM:
                entry["values"] = knob.values
            else:
                entry["lo"] = knob.lo
                entry["hi"] = knob.hi
            out[name] = entry
        return out

    # -- actuation ----------------------------------------------------------------------

    def apply(self, name, value):
        """Retune ``name`` to (the clamped) ``value``. Returns
        ``(before, after)`` — equal when the clamp or the component made the
        call a no-op. The ONLY sanctioned way to change a knob (GL-C004
        enforces that options structs are not mutated around it)."""
        with self._lock:
            knob = self._knobs[name]
            before = knob.get()
            target = knob.clamp(value)
            if target == before:
                return before, before
            after = knob.apply_fn(target)
            if after is None:
                after = knob.get()
        return before, after

    def checkpoint(self):
        """``{name: live value}`` — the revert target the controller snapshots
        before an actuation experiment."""
        with self._lock:
            return {name: knob.get() for name, knob in self._knobs.items()}

    def restore(self, snapshot):
        """Re-apply a :meth:`checkpoint`. Returns the ``[(name, before,
        after)]`` list of knobs that actually moved (the revert decisions)."""
        moved = []
        for name, value in snapshot.items():
            if name not in self:
                continue
            before, after = self.apply(name, value)
            if after != before:
                moved.append((name, before, after))
        return moved

    # -- export -------------------------------------------------------------------------

    def collect(self):
        """Pull-collector payload: per-knob LIVE value + default (numeric;
        enums as value index) — exported as ``ptpu_ctl_knob_*`` so dashboards
        and the controller's own feedback read post-retune truth."""
        with self._lock:
            knobs = dict(self._knobs)
        out = {}
        for name, knob in knobs.items():
            out["knob_%s" % name] = knob.numeric_value()
            out["knob_%s_default" % name] = knob.numeric_value(knob.default)
        return out


def _process_arena():
    from petastorm_tpu.io import arena as arena_mod

    return arena_mod.process_arena()


def _arena_budget():
    arena_obj = _process_arena()
    return arena_obj.budget if arena_obj is not None else 0


def build_knobset(reader):
    """The standard :class:`KnobSet` over a running reader's live components.

    Always included (when the executor supports it): ``workers`` — the
    fleet-size knob actuating :meth:`~petastorm_tpu.reader.Reader
    .resize_workers` (grow spawns, shrink drains — never kills mid-item).

    The IO knobs bind for in-process pools (thread/dummy — the worker object
    is shared, components directly actuable) AND for process pools whose
    executor supports the pool control frame (ISSUE 14 satellite: retunes
    reach already-running children live; spawned-later children inherit via
    the worker pickle as before). The frame rides whatever transport the
    pool runs (ISSUE 15): over ``transport="tcp"`` it crosses the framed
    link like any result conversation — acked, seen-version stamped, and
    respawn-free — and a frame that dies with a link is re-armed on the
    reconnected one, so live retunes reach remote fleets the day a
    dispatcher exists:

    - ``readahead_depth`` / ``readahead_bytes`` — the prefetcher's in-flight
      and held-byte bounds (depth also resizes the dispatch lookahead and the
      IO thread pool so a deeper window actually overlaps);
    - ``remote_max_inflight`` / ``hedge_quantile`` — the ranged-GET engine's
      pool width and hedge deadline quantile (bound only when the remote tier
      is active for the reader's filesystem);
    - ``pagedec`` — the compressed-page pass-through mode enum (ISSUE 14):
      the controller's live revert-to-host-inflate lever;
    - ``mem_cache_bytes`` — the mem tier's byte budget (the hot-row-group
      promotion lever) when a mem tier exists (in-process only);
    - ``arena_bytes`` — the host-wide shared cache arena budget (ISSUE 17):
      bound for EVERY pool type because the budget lives in the arena's
      shared control segment — one parent-side actuation governs admissions
      in all attached processes, and the shrink path evicts host-wide;
    - ``disk_admit`` — the tiered admission policy enum (in-process only —
      a process pool's cache tiers live in the children with no parent-side
      truth to read back).
    """
    ks = KnobSet()
    worker = getattr(reader, "_worker", None)
    opts = getattr(reader, "_io_options", None)
    pool_args = getattr(reader, "_pool_args", None)
    pool_type = pool_args[0] if pool_args else "thread"
    configured_workers = pool_args[1] if pool_args else 4

    if getattr(reader, "resize_workers", None) is not None \
            and pool_type not in ("dummy", "sync"):
        def _workers_target():
            # the knob's value is the applied TARGET, not the instantaneous
            # alive count: retiring workers drain with a lag, and a finished
            # stream has zero alive — both would feed the controller (and
            # the revert checkpoints) phantom values
            target = getattr(reader._executor, "target_workers", None)
            return target if target is not None else configured_workers

        ks.numeric(
            "workers",
            get=_workers_target,
            apply_fn=reader.resize_workers,
            lo=1, hi=max(2 * configured_workers, 8),
            default=configured_workers)

    in_process = pool_type in ("thread", "dummy", "sync")
    # process pools: parent-side setters cannot reach the children's IO
    # runtimes, but the pool CONTROL FRAME can (ISSUE 14 satellite) — the
    # Reader.apply_* seam records the override (future spawns inherit it via
    # the worker pickle) AND broadcasts it to already-running children, so
    # the IO knobs bind for every pool whose executor supports the frame.
    # The getter reads the parent worker's applied TARGET (live_io_knobs
    # consults the override ledger) — the same convention as the workers
    # knob, which reads the applied target rather than a per-child census.
    can_broadcast = hasattr(getattr(reader, "_executor", None),
                            "broadcast_io_knobs")
    if worker is None or opts is None or not (in_process or can_broadcast):
        return ks

    if opts.readahead:
        ks.numeric("readahead_depth",
                   get=lambda: worker.live_io_knobs()["readahead_depth"],
                   apply_fn=reader.apply_readahead_depth,
                   lo=1, hi=64, default=opts.readahead_depth)
        # lo=0: 0 IS a legal value (the construction convention for
        # "uncapped") — a tighter floor would let a checkpoint restore()
        # re-clamp an uncapped budget into a hard cap, and a default that
        # disagrees with the live getter would flag [RETUNED] forever
        ks.numeric("readahead_bytes",
                   get=lambda: worker.live_io_knobs()["readahead_bytes"],
                   apply_fn=reader.apply_readahead_bytes,
                   lo=0, hi=4 << 30,
                   default=opts.readahead_bytes, unit="bytes")
    if opts.remote.active_for(worker._fs):
        ks.numeric("remote_max_inflight",
                   get=lambda: worker.live_io_knobs()["remote_max_inflight"],
                   apply_fn=reader.apply_remote_max_inflight,
                   lo=1, hi=64, default=opts.remote.max_inflight)
        ks.numeric("hedge_quantile",
                   get=lambda: worker.live_io_knobs()["hedge_quantile"],
                   apply_fn=reader.apply_hedge_quantile,
                   lo=0.5, hi=0.999, default=opts.remote.hedge_quantile,
                   integer=False)
    if getattr(worker, "_pagedec_supported", False) \
            and getattr(opts, "pagedec", "off") != "off":
        # the compressed-page pass-through mode (ISSUE 14): the controller's
        # revert-to-host-inflate lever when decode.device_inflate dominates
        ks.enum("pagedec",
                get=worker.live_pagedec,
                apply_fn=reader.apply_pagedec,
                values=("auto", "on", "off"), default=opts.pagedec)
    if _process_arena() is not None and getattr(opts, "arena_bytes", 0):
        # the host-wide arena budget (ISSUE 17): registered for process pools
        # too — the budget lives in the SHARED control segment (the parent is
        # the creator), so this parent-side actuation governs every attached
        # child's admissions without needing the broadcast frame
        ks.numeric("arena_bytes",
                   get=_arena_budget,
                   apply_fn=worker.apply_arena_bytes,
                   lo=8 << 20, hi=64 << 30, default=opts.arena_bytes,
                   unit="bytes")
    if not in_process:
        # the cache tiers live only in the children for process pools —
        # budget/admission stay construction-time there (their retunes have
        # no parent-side truth to read back)
        return ks
    cache = getattr(worker, "_cache", None)
    mem = getattr(cache, "mem", None) if cache is not None else None
    if mem is not None:
        ks.numeric("mem_cache_bytes",
                   get=lambda: mem.budget,
                   apply_fn=worker.apply_mem_cache_bytes,
                   lo=8 << 20, hi=16 << 30, default=mem.budget, unit="bytes")
    if cache is not None and hasattr(cache, "apply_disk_admit"):
        ks.enum("disk_admit",
                get=lambda: cache.disk_admit,
                apply_fn=cache.apply_disk_admit,
                values=("always", "scan-resistant"))
    return ks
