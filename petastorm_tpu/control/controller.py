"""Closed-loop pipeline controller: PR 12's sensors actuating PR 13's knobs.

The temporal plane (:mod:`petastorm_tpu.obs.timeseries`) already produces the
windowed series and the attribution snapshot names the critical-path culprit
site; this module closes ROADMAP item 4's loop: a :class:`Controller` rides
the same sampling cadence (attach it to the registry's
:class:`~petastorm_tpu.obs.timeseries.TimelineStore` like the SLO engine) and
applies declarative :class:`PolicyRule`\\ s against the
:class:`~petastorm_tpu.control.knobs.KnobSet`:

- grow readahead when ``io.readahead_wait`` dominates the slow decile;
- widen the ranged-GET pool against the learned per-(store, size-class)
  latency model (Little's law: desired inflight ≈ GET rate × learned p50),
  and arm hedges earlier, when ``io.remote`` owns the slow decile;
- promote hot row groups into the mem tier (grow its budget) when the remote
  re-fetch share stays high;
- shrink the worker fleet when the pipeline is consumer-bound (sustained
  producer put-wait share — at fleet scale, unused producer CPU is the bill).

**Anti-oscillation contract** (every clause enforced in :meth:`evaluate`, all
pinned by tests):

1. *Hysteresis*: a rule fires above ``fire_above`` and its streak only clears
   below ``clear_below`` — the band between cannot flap it.
2. *Debounce*: the signal must exceed ``fire_above`` for ``windows``
   CONSECUTIVE windows before the first actuation.
3. *Cooldown*: after actuating a knob, that knob is frozen for
   ``cooldown_windows`` windows — one knob cannot chatter.
4. *Step limits*: one actuation moves a knob by at most ``max_step_factor``
   (multiplicative) or the rule's additive step; bounds come from the knob.
5. *Warmup*: the first ``warmup_windows`` windows are observe-only (pool
   spin-up and first-epoch cold starts must not trigger spurious retunes).
6. *Global no-gain guard*: the first actuation opens an **experiment** —
   the knob state is checkpointed and the objective (delivered rows/s from
   the windowed ``ptpu_pipeline_rows`` delta) is baselined. If
   ``max_steps_without_gain`` settled windows pass without the objective
   improving by ``min_gain``, every knob reverts to the checkpoint and the
   controller FREEZES (no further actuation until :meth:`reset`). A
   controller that cannot help provably stops touching the pipeline.

Every decision is a first-class event: ``cause=ctl_actuate`` /
``ctl_revert`` / ``ctl_freeze`` degradations (counted, warn-logged, mirrored
into live flight recorders) carrying before/after knob values and the
triggering window, a full ``ctl_decision`` flight event, and
``ptpu_ctl_*`` counter families on the registry.
"""
from __future__ import annotations

import dataclasses
import math
import threading
import time


@dataclasses.dataclass
class Decision:
    """One controller action (actuation, revert, or freeze)."""

    t: float            # anchored window time
    window: int         # controller window index at decision time
    cause: str          # ctl_actuate | ctl_revert | ctl_freeze
    rule: str
    knob: str | None
    before: object = None
    after: object = None
    #: what fired: the signal description with its window value/culprit site
    trigger: str = ""
    #: the objective (rows/s) in the triggering window, when known
    rows_per_s: float | None = None

    def to_dict(self):
        return dataclasses.asdict(self)


class WindowContext:
    """One window's read surface for rule signals: the sampled series, the
    window length, and a lazily-resolved attribution snapshot."""

    def __init__(self, window, window_s, attribution=None):
        self.window = window
        self.window_s = window_s
        self._attribution = attribution
        self._report = None
        self._report_resolved = False

    def point(self, name):
        return self.window.get(name)

    def stat(self, name, stat):
        point = self.window.get(name)
        return None if point is None else point.get(stat)

    def delta(self, name):
        return self.stat(name, "delta")

    def rate(self, name):
        return self.stat(name, "rate")

    def time_share(self, name):
        """delta(name) / window seconds — e.g. the producer's put-wait share
        of the window (cumulative-seconds collector series)."""
        delta = self.delta(name)
        if delta is None or not self.window_s:
            return None
        return max(0.0, delta) / self.window_s

    def report(self):
        """The attribution snapshot (memoized per window; None without an
        attribution source or when it fails — rules then skip)."""
        if not self._report_resolved:
            self._report_resolved = True
            if self._attribution is not None:
                try:
                    self._report = self._attribution()
                except Exception:  # noqa: BLE001 — a broken source skips rules
                    from petastorm_tpu.obs.log import degradation

                    degradation("ctl_attribution_error",
                                "controller attribution snapshot failed; "
                                "attribution-driven rules skip this window")
        return self._report

    def slow_share(self, site):
        """``site``'s share of the slow-decile critical path, or None when no
        attribution is available."""
        report = self.report()
        if report is None:
            return None
        return report.slow_share.get(site, 0.0) if report.slow_share else None

    def tier_share(self, tier, min_hits=8):
        """``tier``'s share of this window's cache-tier serves (None below
        ``min_hits`` total — a quiet window proves nothing)."""
        total = 0.0
        part = None
        for t in ("mem", "disk", "remote"):
            delta = self.delta('ptpu_io_tier_hits_total{tier="%s"}' % t)
            if delta:
                total += delta
            if t == tier:
                part = delta or 0.0
        if part is None or total < min_hits:
            return None
        return part / total

    def tenant_share(self, tenant, resource="worker_s", min_total=1e-3):
        """``tenant``'s share of this window's per-tenant resource deltas
        (the ISSUE 18 ``ptpu_tenant_*`` twins) — the fleet-fairness signal
        behind :func:`tenant_qos_rules`. None when the window's total charge
        across all tenants is below ``min_total`` (an idle fleet proves
        nothing about fairness)."""
        from petastorm_tpu.obs.tenant import RESOURCES

        family = RESOURCES[resource][0]
        prefix = family + '{tenant="'
        total = 0.0
        part = 0.0
        for name in self.window:
            if not name.startswith(prefix):
                continue
            delta = self.delta(name) or 0.0
            total += max(0.0, delta)
            if name == '%s%s"}' % (prefix, tenant):
                part = max(0.0, delta)
        if total < min_total:
            return None
        return part / total

    def model_latency_s(self):
        """The learned remote-GET p50 of the busiest (store, size-class)
        histogram — the latency-model input to the Little's-law pool sizing.
        None while the model has too few samples to trust."""
        from petastorm_tpu.io.remote import shared_latency_model

        best = None
        model = shared_latency_model()
        with model._lock:
            hists = list(model._hists.values())
        for hist in hists:
            if hist.count >= 20 and (best is None
                                     or hist.count > best.count):
                best = hist
        return None if best is None else best.percentile(0.5)


class PolicyRule:
    """One declarative control rule: a windowed signal moving one knob.

    ``signal(ctx)`` returns the watched statistic (None = sparse window,
    neither fires nor clears the streak). When it has exceeded ``fire_above``
    for ``windows`` consecutive windows, ``propose(ctx, current)`` computes
    the target value; the controller step-limits, bound-clamps (via the
    knob), cools down and logs the actuation.
    """

    def __init__(self, name, knob, signal, fire_above, clear_below,
                 propose, windows=2, cooldown=3, max_step_factor=2.0,
                 guarded=True, description=""):
        if clear_below > fire_above:
            raise ValueError("clear_below must be <= fire_above (hysteresis)")
        self.name = name
        self.knob = knob
        self.signal = signal
        self.fire_above = float(fire_above)
        self.clear_below = float(clear_below)
        self.propose = propose
        self.windows = max(1, int(windows))
        self.cooldown = max(0, int(cooldown))
        self.max_step_factor = float(max_step_factor)
        #: guarded rules seek THROUGHPUT: their actuations open the global
        #: no-gain experiment (no improvement -> revert + freeze). Unguarded
        #: rules seek EFFICIENCY (shrink-workers: rows/s should stay FLAT);
        #: they bypass the no-gain experiment and instead carry the safety
        #: guard — the knob reverts if the objective DROPS after the step.
        self.guarded = bool(guarded)
        self.description = description


def _slow_share_signal(site):
    return lambda ctx: ctx.slow_share(site)


#: read-path sites whose slow-decile time is EXPOSED latency a deeper
#: prefetch window can hide: synchronous reads (misses) and residual waits on
#: in-flight prefetches. ``io.readahead`` itself is excluded — the background
#: read span is charged to items even when fully overlapped, so a healthy
#: deep pipeline still shows it; only the exposed remainder is actionable.
_READ_EXPOSED_SITES = ("reader.read", "reader.read_run",
                       "io.readahead_wait", "io.wait")


def _exposed_read_signal(ctx):
    """Slow-decile share of EXPOSED read latency, gated on a measured time
    scale: the window's exposed read seconds — foreground waits on in-flight
    prefetches plus miss-fallback sync reads
    (``ptpu_io_readahead_exposed_s`` deltas) — as a share of wall time.
    Share-based signals alone carry no scale — a healthy fast pipeline's
    slow decile is trivially owned by its largest µs-level site (usually the
    read), which would look identical to an injected 20 ms bottleneck. A
    pipeline that spends under a quarter of wall-clock actually BLOCKED on
    in-flight prefetches has its reads hidden; the signal clears."""
    wait_share = ctx.time_share("ptpu_io_readahead_exposed_s")
    if wait_share is None:
        return None
    if wait_share < 0.25:
        return 0.0
    shares = [ctx.slow_share(site) for site in _READ_EXPOSED_SITES]
    if all(s is None for s in shares):
        return None
    return sum(s or 0.0 for s in shares)


def _grow(factor):
    def propose(ctx, current):
        return current * factor if current else 1
    return propose


def _propose_inflight(ctx, current):
    """Little's law against the learned latency model: a GET stream of λ/s
    at p50 service time W wants ~λ·W slots busy; 1.5× headroom covers the
    tail the hedges then clip. Falls back to doubling while the model is
    still learning."""
    rate = ctx.rate("ptpu_io_remote_gets_total")
    latency = ctx.model_latency_s()
    if rate and latency:
        return max(current + 1, int(math.ceil(rate * latency * 1.5)))
    return current * 2


def _propose_hedge_quantile(ctx, current):
    return current - 0.04


def _shrink_one(ctx, current):
    return current - 1


def default_rules():
    """The built-in rule table (docs/performance.md renders it). Rules whose
    knob is absent from the KnobSet are skipped."""
    return [
        PolicyRule(
            "grow-readahead", "readahead_depth",
            signal=_exposed_read_signal,
            fire_above=0.4, clear_below=0.15, windows=2, cooldown=2,
            propose=_grow(2),
            description="exposed read latency (sync reads + "
                        "io.readahead_wait) dominates the slow decile while "
                        "the consumer starves -> double the prefetch window "
                        "(and its IO threads)"),
        PolicyRule(
            "widen-get-pool", "remote_max_inflight",
            signal=_slow_share_signal("io.remote"),
            fire_above=0.35, clear_below=0.15, windows=2, cooldown=2,
            propose=_propose_inflight,
            description="io.remote dominates the slow decile -> size the GET "
                        "pool to GET-rate x learned p50 (Little's law)"),
        PolicyRule(
            "hedge-sooner", "hedge_quantile",
            signal=_slow_share_signal("io.remote"),
            fire_above=0.45, clear_below=0.2, windows=3, cooldown=3,
            propose=_propose_hedge_quantile,
            description="io.remote still dominates after widening -> arm the "
                        "hedge deadline at a lower latency quantile"),
        PolicyRule(
            "promote-hot-rows", "mem_cache_bytes",
            signal=lambda ctx: ctx.tier_share("remote"),
            fire_above=0.5, clear_below=0.2, windows=3, cooldown=3,
            propose=_grow(2),
            description="remote re-fetch share stays high -> grow the mem "
                        "tier budget so hot row groups stay resident"),
        PolicyRule(
            "shrink-workers", "workers",
            signal=lambda ctx: ctx.time_share("ptpu_pipeline_put_wait_s"),
            fire_above=0.5, clear_below=0.2, windows=3, cooldown=3,
            propose=_shrink_one, guarded=False,
            description="producer blocked on a full host queue most of the "
                        "window (consumer-bound) -> drain one worker; unused "
                        "producer CPU is the bill"),
        PolicyRule(
            "pagedec-host-inflate", "pagedec",
            signal=_slow_share_signal("decode.device_inflate"),
            fire_above=0.5, clear_below=0.2, windows=3, cooldown=6,
            propose=lambda ctx, current: "off", guarded=False,
            description="the device inflate stage owns the slow decile -> "
                        "flip the compressed-page pass-through back to host "
                        "inflate live (efficiency rule, guarded like "
                        "shrink-workers: its own guard reverts on a rows/s "
                        "drop)"),
    ]


def _tenant_share_signal(tenant, resource):
    def signal(ctx):
        return ctx.tenant_share(tenant, resource=resource)
    return signal


def _halve(ctx, current):
    return current / 2.0


def tenant_qos_rules(tenants, resource="worker_s", fire_above=0.6,
                     clear_below=0.35, windows=2, cooldown=3):
    """Per-tenant fleet-fairness rules for the data service (ISSUE 19): when
    one tenant's share of the fleet's ``resource`` charge (default decode
    worker-seconds) stays above ``fire_above``, halve that tenant's
    ``svc_weight:<tenant>`` stride-scheduling weight. Efficiency rules
    (``guarded=False``): fairness is the objective, not throughput — the
    safety guard still reverts a weight cut that tanks delivered rows/s.

    Attach the knobs with
    :meth:`petastorm_tpu.service.server.DataService.register_knobs`; rules
    whose knob is absent are skipped, so the table composes with
    :func:`default_rules` unconditionally."""
    rules = []
    for tenant in tenants:
        rules.append(PolicyRule(
            "throttle-tenant-%s" % tenant, "svc_weight:%s" % tenant,
            signal=_tenant_share_signal(tenant, resource),
            fire_above=fire_above, clear_below=clear_below,
            windows=windows, cooldown=cooldown,
            propose=_halve, guarded=False,
            description="tenant %r eats >%d%% of the fleet's %s -> halve "
                        "its stride-scheduling weight so the other jobs "
                        "stop queueing behind it"
                        % (tenant, int(fire_above * 100), resource)))
    return rules


class ControlOptions:
    """Controller-wide policy (the per-rule thresholds live on the rules)."""

    __slots__ = ("warmup_windows", "cooldown_windows", "max_steps_without_gain",
                 "min_gain", "settle_windows", "max_decisions")

    def __init__(self, warmup_windows=5, cooldown_windows=None,
                 max_steps_without_gain=6, min_gain=0.05, settle_windows=2,
                 max_decisions=256):
        self.warmup_windows = max(0, int(warmup_windows))
        #: overrides every rule's cooldown when set (tests/benches)
        self.cooldown_windows = cooldown_windows
        self.max_steps_without_gain = max(1, int(max_steps_without_gain))
        self.min_gain = float(min_gain)
        #: windows after the last actuation before its objective is judged
        self.settle_windows = max(0, int(settle_windows))
        self.max_decisions = int(max_decisions)


class Controller:
    """The closed-loop policy engine over one :class:`KnobSet`.

    Attach to a :class:`~petastorm_tpu.obs.timeseries.TimelineStore`
    (:meth:`attach`) so every Reporter/``sample_timelines()`` window drives
    one :meth:`evaluate` pass, exactly like the SLO engine — zero hot-path
    cost. ``attribution`` is a zero-arg callable returning an
    :class:`~petastorm_tpu.obs.critical_path.AttributionReport` (or None);
    ``DataLoader(controller=...)`` wires ``attribution_report`` when
    provenance is on.
    """

    #: objective series: delivered rows (collector gauge; windows carry deltas)
    OBJECTIVE = "ptpu_pipeline_rows"

    def __init__(self, knobs, rules=None, registry=None, attribution=None,
                 options=None):
        self.knobs = knobs
        self._rules = list(rules) if rules is not None else default_rules()
        self._registry = registry
        self._attribution = attribution
        self._opts = options if options is not None else ControlOptions()
        self._lock = threading.Lock()
        self._decisions = []
        self._streaks = {}        # rule name -> consecutive firing windows
        self._cooldowns = {}      # knob name -> windows left frozen
        self._frozen = False
        self._last_t = None
        self.windows_evaluated = 0
        self._rate_history = []   # recent objective rows/s (bounded)
        #: the open actuation experiment (no-gain guard), or None
        self._experiment = None
        #: open efficiency-actuation watches (safety guard: revert on a
        #: throughput DROP; flat is success)
        self._efficiency = []
        self._store = None
        self._listener = None

    # -- wiring -------------------------------------------------------------------------

    def set_attribution(self, fn):
        with self._lock:
            self._attribution = fn

    def attach(self, store):
        """Ride a TimelineStore's sampling cadence (idempotent per store);
        :meth:`detach` unsubscribes (loader ``__exit__``)."""
        self.detach()
        self._store = store
        self._listener = store.add_listener(self._on_window)
        return self

    def detach(self):
        store, self._store = self._store, None
        if store is not None and self._listener is not None:
            store.remove_listener(self._listener)
        self._listener = None

    def _on_window(self, window, t):
        self.evaluate(window, t)

    # -- evaluation ---------------------------------------------------------------------

    def evaluate(self, window, t=None):
        """One control pass over a sampled window; returns the decisions this
        window produced (possibly empty)."""
        t = time.time() if t is None else t
        with self._lock:
            window_s = None if self._last_t is None \
                else max(0.0, t - self._last_t)
            self._last_t = t
            self.windows_evaluated += 1
            idx = self.windows_evaluated
            ctx = WindowContext(window, window_s, self._attribution)
            rate = self._objective_rate(ctx)
            if rate is not None:
                self._rate_history.append(rate)
                del self._rate_history[:-64]
            for knob in list(self._cooldowns):
                self._cooldowns[knob] = max(0, self._cooldowns[knob] - 1)
            if self._frozen:
                return []
            decisions = []
            if idx > self._opts.warmup_windows:
                decisions.extend(self._run_rules(ctx, t, idx, rate))
            decisions.extend(self._no_gain_guard(t, idx, rate))
            decisions.extend(self._efficiency_guard(t, idx, rate))
        for decision in decisions:
            self._publish(decision)
        return decisions

    def _objective_rate(self, ctx):
        delta = ctx.delta(self.OBJECTIVE)
        if delta is None or not ctx.window_s:
            return None
        return max(0.0, delta) / ctx.window_s

    def _run_rules(self, ctx, t, idx, rate):
        decisions = []
        for rule in self._rules:
            if rule.knob not in self.knobs:
                continue
            value = rule.signal(ctx)
            if value is None:
                continue  # sparse window: streak untouched (like SLO specs)
            if value >= rule.fire_above:
                streak = self._streaks.get(rule.name, 0) + 1
            elif value <= rule.clear_below:
                streak = 0
            else:
                streak = self._streaks.get(rule.name, 0)  # hysteresis band
            self._streaks[rule.name] = streak
            if streak < rule.windows or self._cooldowns.get(rule.knob, 0):
                continue
            current = self.knobs.get(rule.knob)
            try:
                target = rule.propose(ctx, current)
            except Exception:  # noqa: BLE001 — a broken proposer skips
                from petastorm_tpu.obs.log import degradation

                degradation("ctl_rule_error",
                            "controller rule %r propose() raised; skipped",
                            rule.name)
                continue
            target = self._step_limit(rule, current, target)
            # checkpoint BEFORE the actuation: the experiment's revert target
            checkpoint = self.knobs.checkpoint() \
                if rule.guarded and self._experiment is None else None
            before, after = self.knobs.apply(rule.knob, target)
            if after == before:
                continue  # at a bound / quantized away: not an actuation
            if rule.guarded:
                if self._experiment is None:
                    self._experiment = {  # graftlint: disable=GL-C001 (caller holds self._lock)
                        "checkpoint": checkpoint,
                        "baseline": self._baseline_rate(),
                        "opened": idx,
                        "steps": 0,
                        "stale_windows": 0,
                    }
                self._experiment["steps"] += 1
                self._experiment["last_actuation"] = idx
            else:
                # efficiency actuation (e.g. shrink-workers): rows/s should
                # stay FLAT — watched by the safety guard, not the no-gain
                # experiment (flat throughput is its success, not a failure)
                self._efficiency.append({  # graftlint: disable=GL-C001 (caller holds self._lock)
                    "knob": rule.knob, "revert_to": before,
                    "baseline": self._baseline_rate(), "applied": idx})
            cooldown = self._opts.cooldown_windows \
                if self._opts.cooldown_windows is not None else rule.cooldown
            self._cooldowns[rule.knob] = cooldown
            trigger = "%s=%.3f >= %.3f for %d windows" \
                % (_signal_label(rule), value, rule.fire_above, streak)
            decisions.append(self._record(Decision(
                t=t, window=idx, cause="ctl_actuate", rule=rule.name,
                knob=rule.knob, before=before, after=after, trigger=trigger,
                rows_per_s=rate)))
            self._streaks[rule.name] = 0  # re-debounce after acting
        return decisions

    def _step_limit(self, rule, current, target):
        """Bound one actuation's movement: at most ``max_step_factor``
        multiplicative (and never less than one integer step, so a rule can
        always make progress toward its bound)."""
        try:
            cur = float(current)
            tgt = float(target)
        except (TypeError, ValueError):
            return target  # enum knob: propose() picks a member directly
        if cur > 0:
            hi = cur * rule.max_step_factor
            lo = cur / rule.max_step_factor
            tgt = min(max(tgt, lo), hi)
            if abs(tgt - cur) < 1.0 and self.knobs.knob(rule.knob).integer:
                tgt = cur + (1 if target > current else -1)
        return tgt

    def _baseline_rate(self):
        """The objective before the experiment: median of the recent settled
        windows (robust to one noisy window)."""
        recent = [r for r in self._rate_history[-8:] if r is not None]
        if not recent:
            return None
        recent.sort()
        n = len(recent)
        return recent[n // 2] if n % 2 \
            else 0.5 * (recent[n // 2 - 1] + recent[n // 2])

    def _no_gain_guard(self, t, idx, rate):
        """The revert-and-freeze clause: judge the open experiment on settled
        windows only; commit on ``min_gain`` improvement, revert + freeze
        after ``max_steps_without_gain`` settled windows without it."""
        exp = self._experiment
        if exp is None or rate is None:
            return []
        if idx - exp.get("last_actuation", exp["opened"]) \
                < self._opts.settle_windows:
            return []  # the actuation has not settled into the windows yet
        baseline = exp["baseline"]
        if baseline is None:
            exp["baseline"] = rate  # first measurable window IS the baseline
            return []
        # judge the BEST settled window since the experiment opened, not just
        # the current one: a converged pipeline plateaus, and judging the
        # plateau window against an already-improved baseline would revert a
        # retune that genuinely helped (window phasing also makes single
        # windows noisy — one good window is proof the knob moved the needle)
        exp["best"] = max(exp.get("best", 0.0), rate)
        if baseline <= 0 or exp["best"] >= baseline * (1.0 + self._opts.min_gain):
            self._experiment = None  # graftlint: disable=GL-C001 (caller holds self._lock) — improvement: commit
            return []
        exp["stale_windows"] += 1
        if exp["stale_windows"] < self._opts.max_steps_without_gain:
            return []
        # no improvement after K settled windows: revert every knob to the
        # pre-experiment checkpoint and freeze
        decisions = []
        for name, before, after in self.knobs.restore(exp["checkpoint"]):
            decisions.append(self._record(Decision(
                t=t, window=idx, cause="ctl_revert", rule="no-gain-guard",
                knob=name, before=before, after=after,
                trigger="rows/s %.1f never improved >= %d%% over the "
                        "pre-actuation baseline %.1f"
                        % (rate, round(100 * self._opts.min_gain), baseline),
                rows_per_s=rate)))
        self._frozen = True  # graftlint: disable=GL-C001 (caller holds self._lock)
        self._experiment = None  # graftlint: disable=GL-C001 (caller holds self._lock)
        decisions.append(self._record(Decision(
            t=t, window=idx, cause="ctl_freeze", rule="no-gain-guard",
            knob=None,
            trigger="%d settled windows without gain after %d actuation "
                    "step(s); controller frozen until reset()"
                    % (exp["stale_windows"], exp["steps"]),
            rows_per_s=rate)))
        return decisions

    def _efficiency_guard(self, t, idx, rate):
        """Safety guard for unguarded (efficiency) actuations: if the
        objective DROPPED materially after the step, revert that knob (no
        freeze — the rule misjudged one window shape, it is not broken);
        a settled flat window confirms the step and closes the watch."""
        if not self._efficiency or rate is None:
            return []
        decisions = []
        keep = []
        confirm_after = self._opts.settle_windows + 2
        for watch in self._efficiency:
            age = idx - watch["applied"]
            if age < self._opts.settle_windows:
                keep.append(watch)
                continue
            baseline = watch["baseline"]
            if baseline is None:
                # no pre-step history (step landed in the first windows):
                # the first settled rate becomes the reference — a LATER
                # drop against it still reverts
                watch["baseline"] = rate
                keep.append(watch)
                continue
            if rate < baseline * (1.0 - 2.0 * self._opts.min_gain):
                before, after = self.knobs.apply(watch["knob"],
                                                 watch["revert_to"])
                if after != before:
                    decisions.append(self._record(Decision(
                        t=t, window=idx, cause="ctl_revert",
                        rule="efficiency-guard", knob=watch["knob"],
                        before=before, after=after,
                        trigger="rows/s %.1f dropped >%d%% below the "
                                "pre-step baseline %.1f"
                                % (rate,
                                   round(200 * self._opts.min_gain),
                                   baseline),
                        rows_per_s=rate)))
                self._cooldowns[watch["knob"]] = max(
                    self._cooldowns.get(watch["knob"], 0), 3)
                continue  # watch closed by the revert
            if age < confirm_after:
                keep.append(watch)  # settled flat so far: watch a bit longer
            # past the horizon: confirmed — flat throughput IS the success
        self._efficiency = keep  # graftlint: disable=GL-C001 (caller holds self._lock)
        return decisions

    # -- decision plumbing --------------------------------------------------------------

    def _record(self, decision):
        # caller MUST hold self._lock (evaluate's helpers run inside it)
        self._decisions.append(decision)  # graftlint: disable=GL-C001
        del self._decisions[:-self._opts.max_decisions]
        return decision

    def _publish(self, decision):
        """Count + log + flight-mirror one decision (outside the lock)."""
        from petastorm_tpu.obs import flight as _flight
        from petastorm_tpu.obs.log import degradation

        if self._registry is not None:
            if decision.cause == "ctl_actuate":
                self._registry.counter(
                    "ptpu_ctl_actuations_total",
                    help="controller knob actuations",
                    knob=decision.knob).inc()
            elif decision.cause == "ctl_revert":
                self._registry.counter(
                    "ptpu_ctl_reverts_total",
                    help="knobs reverted by the no-gain guard").inc()
            else:
                self._registry.counter(
                    "ptpu_ctl_freezes_total",
                    help="controller freezes (no-gain guard)").inc()
        degradation(
            decision.cause,
            "controller %s: rule %s knob %s %r -> %r (window %d: %s)",
            decision.cause, decision.rule, decision.knob, decision.before,
            decision.after, decision.window, decision.trigger, once=False,
            level=20)  # INFO: actuation is the controller working, not failing
        for recorder in _flight.active_recorders():
            recorder.record("ctl_decision", cause=decision.cause,
                            rule=decision.rule, knob=decision.knob,
                            before=decision.before, after=decision.after,
                            window=decision.window, trigger=decision.trigger)

    # -- reads / lifecycle --------------------------------------------------------------

    @property
    def frozen(self):
        return self._frozen

    def decisions(self):
        """All decisions so far (oldest first, bounded)."""
        with self._lock:
            return list(self._decisions)

    def actuations(self):
        return [d for d in self.decisions() if d.cause == "ctl_actuate"]

    def reset(self):
        """Un-freeze and clear streak/experiment state (knobs stay where they
        are — restore a checkpoint explicitly to rewind them)."""
        with self._lock:
            self._frozen = False
            self._experiment = None
            self._efficiency = []
            self._streaks.clear()
            self._cooldowns.clear()

    def state(self):
        """The stats-panel payload: knob table + recent decisions + freeze
        state."""
        with self._lock:
            decisions = [d.to_dict() for d in self._decisions[-16:]]
            frozen = self._frozen
            windows = self.windows_evaluated
        return {"frozen": frozen, "windows": windows,
                "knobs": self.knobs.describe(), "decisions": decisions}

    def collect(self):
        """Pull-collector payload (``ptpu_ctl_*``): live knob values +
        defaults, decision totals, freeze state."""
        with self._lock:
            out = {
                "decisions": len(self._decisions),
                "actuations": sum(1 for d in self._decisions
                                  if d.cause == "ctl_actuate"),
                "reverts": sum(1 for d in self._decisions
                               if d.cause == "ctl_revert"),
                "freezes": sum(1 for d in self._decisions
                               if d.cause == "ctl_freeze"),
                "frozen": 1 if self._frozen else 0,
                "windows": self.windows_evaluated,
            }
        out.update(self.knobs.collect())
        return out


def _signal_label(rule):
    """A stable, human-readable name for what the rule watches (rides in the
    decision trigger so the operator sees the culprit, not a lambda repr)."""
    return {
        "grow-readahead": "slow_share(exposed reads: reader.read + "
                          "io.readahead_wait)",
        "widen-get-pool": "slow_share(io.remote)",
        "hedge-sooner": "slow_share(io.remote)",
        "promote-hot-rows": "tier_share(remote)",
        "shrink-workers": "time_share(put_wait)",
        "pagedec-host-inflate": "slow_share(decode.device_inflate)",
    }.get(rule.name, rule.name)
