"""Hive-partitioned dataset support: ``key=value`` directory layouts.

Reference behavior (petastorm/reader.py ~L330): ``pq.ParquetDataset`` over a
hive-partitioned store transparently (a) materializes the partition-directory columns as
row values and (b) prunes whole directories from ``filters=`` before any row group is
scheduled (SURVEY.md §4.2; the §5 TestSchema includes a partition-by column). Here the
same three capabilities are explicit, TPU-first functions over the piece list:

- :func:`partition_values_for_path` — parse ``key=value`` segments out of a file path
  relative to the dataset root (hive URL-encoding and ``__HIVE_DEFAULT_PARTITION__``
  null markers included).
- :func:`build_partition_info` — infer one typed :class:`PartitionInfo` for the whole
  dataset (key order from the directory depth; value dtype int64 → float64 → string by
  the narrowest type every observed value parses as — pyarrow's inference rule).
- :func:`prune_pieces` — drop whole pieces whose partition values cannot satisfy the
  DNF ``filters`` BEFORE scheduling (directory-level pruning; the remaining row-level
  clauses still run as vectorized masks in the workers).
- :func:`attach_partition_columns` — append the constant partition columns to a
  row-group table after the (column-pruned) file read, so delivered rows/batches carry
  the partition values like any other column.
"""
from __future__ import annotations

import posixpath
from urllib.parse import unquote

import numpy as np

#: Hive's marker for a null partition value.
HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


class PartitionInfo:
    """Typed description of a dataset's hive partitioning.

    Attributes
    ----------
    keys : tuple of str
        Partition column names in directory order (outermost first).
    converters : dict
        ``{key: callable(str) -> value}`` applying the inferred type.
    numpy_dtypes : dict
        ``{key: numpy dtype}`` of the materialized columns.
    """

    def __init__(self, keys, converters, numpy_dtypes):
        self.keys = tuple(keys)
        self.converters = dict(converters)
        self.numpy_dtypes = dict(numpy_dtypes)

    def __bool__(self):
        return bool(self.keys)

    def typed_values(self, raw_values):
        """Apply the inferred types to one piece's raw string values."""
        out = {}
        for key in self.keys:
            raw = raw_values.get(key)
            out[key] = None if raw is None else self.converters[key](raw)
        return out


def partition_values_for_path(file_path, root):
    """Ordered ``{key: raw-string-value}`` parsed from ``key=value`` path segments of
    ``file_path`` relative to ``root`` (empty dict for flat layouts). Values are
    URL-unquoted (hive percent-encodes special characters); the hive null marker maps
    to ``None``."""
    root = root.rstrip("/")
    path = file_path
    if not path.startswith(root):
        return {}
    rel = path[len(root):].lstrip("/")
    values = {}
    for segment in rel.split("/")[:-1]:  # last segment is the file name
        if "=" not in segment:
            continue
        key, _, raw = segment.partition("=")
        raw = unquote(raw)
        values[unquote(key)] = None if raw == HIVE_NULL else raw
    return values


def _infer_converter(raw_values):
    """Narrowest of int64/float64/string that every observed value parses as."""
    non_null = [v for v in raw_values if v is not None]
    try:
        for v in non_null:
            int(v)
        return int, np.dtype(np.int64)
    except ValueError:
        pass
    try:
        for v in non_null:
            float(v)
        return float, np.dtype(np.float64)
    except ValueError:
        pass
    return str, np.dtype("O")


def build_partition_info(per_piece_raw):
    """One :class:`PartitionInfo` from every piece's raw partition values.

    ``per_piece_raw``: iterable of ``{key: raw string}`` dicts (one per piece). Key sets
    must agree across pieces (a store mixing partitioned and flat files is malformed);
    raises ValueError otherwise. Returns a falsy PartitionInfo for flat datasets."""
    per_piece_raw = list(per_piece_raw)
    if not per_piece_raw or not any(per_piece_raw):
        return PartitionInfo((), {}, {})
    keys = tuple(per_piece_raw[0].keys())
    keyset = set(keys)
    for values in per_piece_raw:
        if set(values.keys()) != keyset:
            raise ValueError(
                "Inconsistent hive partitioning: saw partition keys %s and %s in the "
                "same dataset" % (sorted(keyset), sorted(values.keys()))
            )
    converters = {}
    dtypes = {}
    for key in keys:
        conv, dtype = _infer_converter([v.get(key) for v in per_piece_raw])
        converters[key] = conv
        dtypes[key] = dtype
    return PartitionInfo(keys, converters, dtypes)


def partition_fields(info, nullable=False):
    """Partition columns as codec-less :class:`UnischemaField` scalars (decode is a
    plain dtype coercion — see ``utils.decode_row`` codec-None branch)."""
    from petastorm_tpu.unischema import UnischemaField

    fields = []
    for key in info.keys:
        dtype = info.numpy_dtypes[key]
        np_type = str if dtype == np.dtype("O") else dtype.type
        fields.append(UnischemaField(key, np_type, (), None, nullable))
    return fields


def normalize_filters(filters, info):
    """Coerce filter values on partition columns to the columns' inferred types.

    Directory values arrive as strings but infer to int64/float64; a user writing the
    legacy pyarrow/petastorm convention ``filters=[('chunk', '=', '1')]`` against an
    int-typed ``chunk`` would otherwise silently match nothing (``1 == '1'`` is False)
    both at directory-prune time and in the row-level mask over the attached typed
    column. Uncoercible values are left as-is (the term can then never match — the
    reader's no-data error surfaces the mismatch rather than wrong results)."""
    if not filters or not info:
        return filters
    keyset = set(info.keys)

    def coerce(name, val):
        conv = info.converters[name]
        try:
            if isinstance(val, (list, tuple, set, frozenset)):
                return type(val)(conv(v) for v in val) if not isinstance(val, (set, frozenset)) \
                    else set(conv(v) for v in val)
            return conv(val)
        except (TypeError, ValueError):
            return val

    def norm_clause(clause):
        return [(name, op, coerce(name, val)) if name in keyset else (name, op, val)
                for name, op, val in clause]

    if isinstance(filters[0][0], str):
        return norm_clause(filters)
    return [norm_clause(c) for c in filters]


def _term_matches(value, op, filter_val):
    if op in ("=", "=="):
        return value == filter_val
    if op == "!=":
        return value != filter_val
    if op == "<":
        return value < filter_val
    if op == "<=":
        return value <= filter_val
    if op == ">":
        return value > filter_val
    if op == ">=":
        return value >= filter_val
    if op == "in":
        return value in set(filter_val)
    if op in ("not in", "not-in"):
        return value not in set(filter_val)
    raise ValueError("Unsupported filter op %r" % op)


def piece_matches_filters(typed_values, filters, keys):
    """Can a piece with these partition values satisfy the DNF ``filters``?

    Terms over non-partition columns are treated as satisfiable (they become row-level
    masks later); a piece is dropped only when EVERY or-clause contains a partition
    term its values fail — pruning is conservative-correct."""
    if not filters:
        return True
    clauses = [filters] if isinstance(filters[0][0], str) else filters
    keyset = set(keys)
    for clause in clauses:
        ok = True
        for name, op, val in clause:
            if name not in keyset:
                continue
            value = typed_values.get(name)
            try:
                if value is None:
                    # __HIVE_DEFAULT_PARTITION__ directory: null values MATCH the
                    # negative operators (same convention as the row-level mask and
                    # _prune_by_stats' nulls==0 guard), and an 'in' list may name
                    # None explicitly; ordering/equality ops never match null.
                    if op in ("!=", "not in", "not-in"):
                        matched = True
                    elif op == "in":
                        matched = None in set(val)
                    else:
                        matched = False
                else:
                    matched = _term_matches(value, op, val)
            except TypeError:  # uncoercible filter value vs typed partition value
                matched = False
            if not matched:
                ok = False
                break
        if ok:
            return True
    return False


def prune_pieces(pieces, info, filters):
    """Directory-level pruning: drop pieces whose partition values cannot satisfy
    ``filters`` — their files are never opened, never scheduled."""
    if not info or not filters:
        return pieces
    kept = []
    for piece in pieces:
        typed = info.typed_values(piece.partition_values or {})
        if piece_matches_filters(typed, filters, info.keys):
            kept.append(piece)
    return kept


def attach_partition_columns(table, piece, info, wanted=None):
    """Append this piece's partition values as constant columns to a row-group table.

    ``wanted``: only attach these columns (None = all partition keys). Columns already
    present in the file win (a writer may also store the partition column inline)."""
    import pyarrow as pa

    if not info:
        return table
    typed = info.typed_values(piece.partition_values or {})
    existing = set(table.column_names)
    for key in info.keys:
        if key in existing or (wanted is not None and key not in wanted):
            continue
        value = typed[key]
        dtype = info.numpy_dtypes[key]
        if value is None:
            arr = pa.nulls(table.num_rows)
        elif dtype == np.dtype("O"):
            arr = pa.array([value] * table.num_rows, type=pa.string())
        else:
            arr = pa.array(np.full(table.num_rows, value, dtype=dtype))
        table = table.append_column(key, arr)
    return table
