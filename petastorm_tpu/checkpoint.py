"""Orbax-integrated checkpointing of the data-plane state.

TPU-native upgrade over the reference (SURVEY.md §6: petastorm has no resumable
cursor at all): ``Reader.state_dict()`` already gives exact mid-epoch resume; this
module makes that state a first-class item in an **orbax** checkpoint next to the
model params/optimizer — one atomic step directory, one restore call, the workflow
preemption-prone pods actually use.

    import orbax.checkpoint as ocp
    from petastorm_tpu import checkpoint as ptck

    mngr = ocp.CheckpointManager(ckpt_dir)
    ...
    mngr.save(step, args=ocp.args.Composite(
        params=ocp.args.StandardSave(params),
        reader=ptck.save_args(reader),
    ))
    ...
    restored = mngr.restore(step, args=ocp.args.Composite(
        params=ocp.args.StandardRestore(params_template),
        reader=ptck.restore_args(),
    ))
    ptck.apply(reader, restored["reader"])

For scripts that only need the data-plane state, :func:`save` / :func:`restore`
write/read a standalone orbax checkpoint directory.

Multi-process (VERDICT r3 #3): ``Reader.state_dict()`` is per-process (each process
owns its shard's plan), but orbax's JSON item is written by process 0 only — so
:func:`save_args` ALLGATHERS every process's state into one global payload before
the write, and :func:`apply` routes each process its own shard entry on restore
(keyed by ``cur_shard``, falling back to process index). Pod preemption therefore
resumes EVERY process at its exact cursor from the one checkpoint directory — no
row lost or duplicated on any shard, no hand-rolled per-process paths.

When batches flow through a :class:`petastorm_tpu.loader.DataLoader`, pass the
LOADER to these entry points instead of the reader — it duck-types as a reader
(``state_dict``/``load_state_dict``/``cur_shard``) and checkpoints at the
CONSUMER watermark, so rows prefetched into loader buffers but not yet yielded
replay after restore instead of being skipped (``DataLoader.state_dict`` docs).
"""
from __future__ import annotations

import json

#: Marker key for an allgathered multi-process payload (a plain per-process state
#: never contains it — `Reader.state_dict` keys are fixed).
_GLOBAL_KEY = "ptpu_per_process"


def save_args(reader):
    """COLLECTIVE under multi-process JAX — every process must call this (it
    allgathers the pod's states); calling it on process 0 only deadlocks the pod.

    ``ocp.args.JsonSave`` of the reader's exact-resume state — pass as one item of
    an ``ocp.args.Composite`` alongside params/opt-state. Under multi-process JAX the
    payload carries EVERY process's state (small JSON, one allgather) so the single
    orbax item is pod-exact. For a non-collective per-process payload (old callers
    that checkpoint each process separately), use
    ``ocp.args.JsonSave(reader.state_dict())`` directly."""
    import orbax.checkpoint as ocp

    return ocp.args.JsonSave(global_state_dict(reader))


def restore_args():
    """``ocp.args.JsonRestore`` matching :func:`save_args`."""
    import orbax.checkpoint as ocp

    return ocp.args.JsonRestore()


def global_state_dict(reader):
    """COLLECTIVE under multi-process JAX — every process must call it in the same
    order (one allgather); a subset-of-processes call deadlocks.

    This pod's complete data-plane state: ``{_GLOBAL_KEY: {shard_key: state}}``
    with one entry per process under multi-process JAX, or the plain per-process
    state dict single-process."""
    import jax

    state = reader.state_dict()
    if jax.process_count() == 1:
        return state
    return {_GLOBAL_KEY: _allgather_states(_shard_key(reader), state)}


def apply(reader, restored_state):
    """Load a restored state dict into a freshly-built reader (same dataset/config).

    Global (multi-process) payloads are routed: each process picks its own shard's
    entry by ``cur_shard`` (process index when unsharded). The reader resumes at the
    consumed-work watermark: fully-delivered row groups are skipped; in-flight ones
    replay in full (at-least-once at row-group granularity —
    ``Reader.state_dict`` docs)."""
    state = dict(restored_state)
    per_process = state.get(_GLOBAL_KEY)
    if per_process is not None:
        key = _shard_key(reader)
        if key not in per_process:
            raise ValueError(
                "Global checkpoint has no entry for shard %r (available: %s); was the "
                "pod resharded? Rebuild readers with the original cur_shard/"
                "shard_count, or re-shard the dataset and start a fresh epoch."
                % (key, sorted(per_process)))
        state = per_process[key]
    reader.load_state_dict(_denormalize(state))
    return reader


def save(path, reader):
    """Standalone orbax checkpoint of just the data-plane state at ``path``
    (pod-exact under multi-process JAX, see :func:`save_args`; COLLECTIVE — all
    processes must call it)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.JsonCheckpointHandler())
    ckptr.save(_epath(path), args=save_args(reader))


def restore(path, reader):
    """Restore a standalone :func:`save` checkpoint into ``reader``."""
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.JsonCheckpointHandler())
    state = ckptr.restore(_epath(path))
    return apply(reader, state)


def _shard_key(reader):
    """Stable identity of this process's shard in a global payload."""
    import jax

    cur = getattr(reader, "cur_shard", None)
    return str(cur if cur is not None else jax.process_index())


def _allgather_states(key, state):
    """Exchange each process's small JSON state; returns {shard_key: state} for the
    whole pod. Two collectives (max-length, then padded bytes) — the states are a few
    hundred bytes each, so this is noise next to any params save."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    payload = json.dumps([key, state]).encode("utf-8")
    lens = multihost_utils.process_allgather(np.int32(len(payload)))
    maxlen = int(np.max(lens))
    buf = np.zeros(maxlen, np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    gathered = np.asarray(gathered).reshape(jax.process_count(), maxlen)
    pairs = [json.loads(bytes(gathered[i, : int(lens[i])]).decode("utf-8"))
             for i in range(gathered.shape[0])]
    return _merge_states(pairs)


def _merge_states(pairs):
    """Merge gathered ``[shard_key, state]`` pairs into ``{shard_key: state}``.

    Replica groups (several processes reading the SAME shard, e.g. dp replication
    over a 2-way-sharded store) gather duplicate keys, possibly with timing skew
    between replicas' consumed sets. The merged entry INTERSECTS the replicas'
    consumed sets per epoch (and takes the min resume epoch): restore then skips
    only work EVERY replica fully delivered, so each replica resumes at-least-once
    — a least-consumed-count pick could keep a set some replica never delivered
    and silently lose its rows. Same-key states must share a plan (same seed/
    shuffle/epochs): differently-configured readers are not replicas, and routing
    one of their cursors to the other would replay the wrong rows."""
    out = {}
    for k, st in pairs:
        k = str(k)
        if k in out and out[k] != st:
            prev = out[k]
            if prev.get("plan") != st.get("plan"):
                raise ValueError(
                    "Shard key %r was checkpointed by readers with different plans "
                    "(%r vs %r) — replicas of one shard must share seed/shuffle/"
                    "epoch config, or use distinct cur_shard values"
                    % (k, prev.get("plan"), st.get("plan")))
            out[k] = _intersect_states(prev, st)
            continue
        out[k] = st
    return out


def _intersect_states(a, b):
    merged = dict(a)
    merged["resume_epoch"] = min(int(a["resume_epoch"]), int(b["resume_epoch"]))
    ca = {int(e): set(v) for e, v in a.get("consumed", {}).items()}
    cb = {int(e): set(v) for e, v in b.get("consumed", {}).items()}
    merged["consumed"] = {
        e: sorted(ca[e] & cb[e]) for e in (set(ca) & set(cb)) if ca[e] & cb[e]}
    return merged


def _epath(path):
    from etils import epath

    return epath.Path(path)


def _denormalize(state):
    """JSON round trips stringify the integer epoch keys in ``consumed``; restore
    them (load_state_dict casts defensively, but keep the contract explicit)."""
    state = dict(state)
    if "consumed" in state:
        state["consumed"] = {int(k): v for k, v in state["consumed"].items()}
    return state
