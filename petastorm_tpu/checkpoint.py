"""Orbax-integrated checkpointing of the data-plane state.

TPU-native upgrade over the reference (SURVEY.md §6: petastorm has no resumable
cursor at all): ``Reader.state_dict()`` already gives exact mid-epoch resume; this
module makes that state a first-class item in an **orbax** checkpoint next to the
model params/optimizer — one atomic step directory, one restore call, the workflow
preemption-prone pods actually use.

    import orbax.checkpoint as ocp
    from petastorm_tpu import checkpoint as ptck

    mngr = ocp.CheckpointManager(ckpt_dir)
    ...
    mngr.save(step, args=ocp.args.Composite(
        params=ocp.args.StandardSave(params),
        reader=ptck.save_args(reader),
    ))
    ...
    restored = mngr.restore(step, args=ocp.args.Composite(
        params=ocp.args.StandardRestore(params_template),
        reader=ptck.restore_args(),
    ))
    ptck.apply(reader, restored["reader"])

For scripts that only need the data-plane state, :func:`save` / :func:`restore`
write/read a standalone orbax checkpoint directory.

Multi-process: ``Reader.state_dict()`` is per-process (each process owns its shard's
plan); orbax's managers coordinate the multi-host write. Save the reader item from
EVERY process (orbax Composite handles per-process payloads via ``JsonSave`` on
process 0 — for per-shard exactness use :func:`save` with a per-process path, or
embed ``state_dict()`` in your own per-host payload).
"""
from __future__ import annotations


def save_args(reader):
    """``ocp.args.JsonSave`` of the reader's exact-resume state — pass as one item of
    an ``ocp.args.Composite`` alongside params/opt-state."""
    import orbax.checkpoint as ocp

    return ocp.args.JsonSave(reader.state_dict())


def restore_args():
    """``ocp.args.JsonRestore`` matching :func:`save_args`."""
    import orbax.checkpoint as ocp

    return ocp.args.JsonRestore()


def apply(reader, restored_state):
    """Load a restored state dict into a freshly-built reader (same dataset/config).

    The reader resumes at the consumed-work watermark: fully-delivered row groups
    are skipped; in-flight ones replay in full (at-least-once at row-group
    granularity — ``Reader.state_dict`` docs)."""
    reader.load_state_dict(_denormalize(restored_state))
    return reader


def save(path, reader):
    """Standalone orbax checkpoint of just the data-plane state at ``path``."""
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.JsonCheckpointHandler())
    ckptr.save(_epath(path), args=save_args(reader))


def restore(path, reader):
    """Restore a standalone :func:`save` checkpoint into ``reader``."""
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.JsonCheckpointHandler())
    state = ckptr.restore(_epath(path))
    return apply(reader, state)


def _epath(path):
    from etils import epath

    return epath.Path(path)


def _denormalize(state):
    """JSON round trips stringify the integer epoch keys in ``consumed``; restore
    them (load_state_dict casts defensively, but keep the contract explicit)."""
    state = dict(state)
    if "consumed" in state:
        state["consumed"] = {int(k): v for k, v in state["consumed"].items()}
    return state
