"""HDFS namenode HA: config-driven namenode resolution + retry-on-failover client.

Reference parity (petastorm/hdfs/namenode.py ~L40 ``HdfsNamenodeResolver``, ~L200
``HAHdfsClient`` / ``MaxFailoversExceeded``): a high-availability nameservice lists
several namenodes of which one is active; a flip mid-epoch turns the standby's client
into a brick. The reference wraps every client call with rotate-and-reconnect retry —
this module provides the same contract around ``pyarrow.fs.HadoopFileSystem``.

Layering with libhdfs: when the URL authority is a *nameservice id* and the Hadoop
config is visible to libhdfs, ``HadoopFileSystem('nameservice1')`` already fails over
internally — that remains the preferred path (zero copies of the config logic). This
wrapper adds the reference's app-level guarantee for the cases libhdfs does not cover:
explicit ``host:port`` URLs pointing at what may be a standby, nameservices resolved
from ``HADOOP_CONF_DIR`` XML when libhdfs itself is pointed elsewhere, and flips that
surface as connection errors between calls.
"""
from __future__ import annotations

import logging
import os
import threading
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

from petastorm_tpu.errors import PERMANENT_IO_ERRORS as _NON_RETRYABLE  # noqa: E402
# OSError subclasses that are REAL answers, not connection trouble — never failover.


class MaxFailoversExceeded(RuntimeError):
    """Every namenode was tried the configured number of times; all failed.

    Attributes mirror the reference (petastorm/hdfs/namenode.py ~L200):
    ``failed_exceptions`` (every error seen), ``max_failover_attempts``, ``func_name``.
    """

    def __init__(self, failed_exceptions, max_failover_attempts, func_name):
        self.failed_exceptions = list(failed_exceptions)
        self.max_failover_attempts = max_failover_attempts
        self.func_name = func_name
        last = self.failed_exceptions[-1] if self.failed_exceptions else None
        super().__init__(
            "Failover attempts exhausted (%d) calling %r; last error: %r"
            % (max_failover_attempts, func_name, last))
        self.__cause__ = last


def _hadoop_conf_dirs():
    """Candidate Hadoop config directories, reference discovery order
    (HADOOP_CONF_DIR, then <HADOOP_HOME|PREFIX|INSTALL>/etc/hadoop)."""
    dirs = []
    if os.environ.get("HADOOP_CONF_DIR"):
        dirs.append(os.environ["HADOOP_CONF_DIR"])
    for var in ("HADOOP_HOME", "HADOOP_PREFIX", "HADOOP_INSTALL"):
        root = os.environ.get(var)
        if root:
            dirs.append(os.path.join(root, "etc", "hadoop"))
    return dirs


def read_hadoop_config(conf_dir=None):
    """``{property-name: value}`` merged from ``core-site.xml`` + ``hdfs-site.xml``
    (hdfs-site wins on conflicts, matching Hadoop's own load order)."""
    props = {}
    dirs = [conf_dir] if conf_dir else _hadoop_conf_dirs()
    for d in dirs:
        found_any = False
        for fname in ("core-site.xml", "hdfs-site.xml"):
            path = os.path.join(d, fname)
            if not os.path.isfile(path):
                continue
            found_any = True
            try:
                root = ET.parse(path).getroot()
            except ET.ParseError as e:
                logger.warning("Unparseable Hadoop config %s: %s", path, e)
                continue
            for prop in root.iter("property"):
                name = prop.findtext("name")
                value = prop.findtext("value")
                if name is not None and value is not None:
                    props[name.strip()] = value.strip()
        if found_any:
            break  # first directory with config wins (reference behavior)
    return props


class HdfsNamenodeResolver:
    """Resolve nameservice ids → namenode ``(host, port)`` lists from Hadoop config
    (reference petastorm/hdfs/namenode.py ~L40)."""

    DEFAULT_PORT = 8020

    def __init__(self, config=None, conf_dir=None):
        self._config = dict(config) if config is not None \
            else read_hadoop_config(conf_dir)

    @property
    def nameservices(self):
        raw = self._config.get("dfs.nameservices", "")
        return [s.strip() for s in raw.split(",") if s.strip()]

    def resolve_hdfs_name_service(self, namespace):
        """Namenode ``[(host, port), ...]`` for a nameservice id, or None when the
        config does not define it (a plain hostname, not an HA nameservice)."""
        if namespace not in self.nameservices:
            return None
        nns = self._config.get("dfs.ha.namenodes.%s" % namespace, "")
        out = []
        for nn in (s.strip() for s in nns.split(",") if s.strip()):
            addr = self._config.get(
                "dfs.namenode.rpc-address.%s.%s" % (namespace, nn))
            if not addr:
                continue
            host, _, port = addr.partition(":")
            out.append((host, int(port) if port else self.DEFAULT_PORT))
        if not out:
            raise ValueError(
                "Nameservice %r is declared in dfs.nameservices but has no resolvable "
                "dfs.ha.namenodes / dfs.namenode.rpc-address entries" % namespace)
        return out

    def resolve_default_hdfs_service(self):
        """(nameservice, namenodes) for ``fs.defaultFS`` (reference ~L120)."""
        default = self._config.get("fs.defaultFS", "")
        if not default.startswith("hdfs://"):
            raise ValueError("fs.defaultFS is not an hdfs:// URL: %r" % default)
        from urllib.parse import urlparse

        host = urlparse(default).hostname
        nns = self.resolve_hdfs_name_service(host)
        if nns is None:
            port = urlparse(default).port or self.DEFAULT_PORT
            nns = [(host, port)]
        return host, nns


def _default_connect(host, port, storage_options=None):
    import pyarrow.fs as pafs

    return pafs.HadoopFileSystem(host, int(port), **(storage_options or {}))


class HAHdfsClient:
    """Failover proxy around ``pyarrow.fs.HadoopFileSystem`` (reference ``HAHdfsClient``
    petastorm/hdfs/namenode.py ~L200): every method call retries across the namenode
    list, reconnecting on connection/standby errors, until
    ``MAX_FAILOVER_ATTEMPTS`` full passes fail — then :class:`MaxFailoversExceeded`.

    Real answers (``FileNotFoundError`` etc.) propagate immediately — only
    connection-shaped ``OSError``/``RuntimeError`` rotate the namenode.
    """

    #: full passes over the namenode list before giving up (reference default)
    MAX_FAILOVER_ATTEMPTS = 2

    def __init__(self, namenodes, connect=None, storage_options=None):
        if not namenodes:
            raise ValueError("HAHdfsClient needs at least one namenode")
        # NOTE: attribute writes must go through __dict__ because __getattr__ proxies
        self.__dict__["_namenodes"] = [(h, int(p)) for h, p in namenodes]
        self.__dict__["_connect"] = connect or _default_connect
        self.__dict__["_storage_options"] = storage_options or {}
        self.__dict__["_index"] = 0
        self.__dict__["_fs"] = None
        #: readers share one client across worker threads — failover state needs a
        #: lock, and rotation is guarded by the connection the caller saw fail so a
        #: burst of simultaneous errors rotates ONCE, not once per thread (which
        #: would land back on the dead namenode and clobber healthy reconnects)
        self.__dict__["_lock"] = threading.RLock()

    # -- connection management ----------------------------------------------------------

    def _ensure_fs(self):
        with self._lock:
            if self._fs is None:
                host, port = self._namenodes[self._index]
                self.__dict__["_fs"] = self._connect(
                    host, port, storage_options=self._storage_options)
            return self._fs

    def _failover_from(self, failed_fs, exc):
        """Rotate namenodes — but only if ``failed_fs`` is still the active
        connection (another thread may already have rotated past it)."""
        with self._lock:
            if self._fs is not failed_fs:
                return  # someone else already failed over; retry on their connection
            old = self._namenodes[self._index]
            self.__dict__["_index"] = (self._index + 1) % len(self._namenodes)
            self.__dict__["_fs"] = None
            logger.warning("HDFS failover: %s:%d -> %s:%d after %r",
                           old[0], old[1], *self._namenodes[self._index], exc)

    # -- proxy --------------------------------------------------------------------------

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        # even the connect + attribute probe can hit a dead/standby namenode
        probe_errors = []
        attempts = self.MAX_FAILOVER_ATTEMPTS * len(self._namenodes)
        for _ in range(attempts):
            fs = None
            try:
                fs = self._ensure_fs()
                probe = getattr(fs, name)
                break
            except _NON_RETRYABLE:
                raise
            except AttributeError:
                raise
            except (OSError, RuntimeError) as e:
                probe_errors.append(e)
                self._failover_from(fs, e)
        else:
            raise MaxFailoversExceeded(probe_errors, attempts, name)
        if not callable(probe):
            return probe

        def call(*args, **kwargs):
            errors = []
            attempts = self.MAX_FAILOVER_ATTEMPTS * len(self._namenodes)
            for _ in range(attempts):
                fs = None
                try:
                    fs = self._ensure_fs()
                    return getattr(fs, name)(*args, **kwargs)
                except _NON_RETRYABLE:
                    raise
                except (OSError, RuntimeError) as e:
                    errors.append(e)
                    self._failover_from(fs, e)
            raise MaxFailoversExceeded(errors, attempts, name)

        call.__name__ = name
        return call

    def __repr__(self):
        return "HAHdfsClient(namenodes=%r, active=%d)" % (self._namenodes, self._index)


def connect_hdfs(hostname, port, storage_options=None, resolver=None, connect=None):
    """hdfs:// authority → filesystem, with HA when the config knows the authority.

    - authority is a configured *nameservice id* (no port) → :class:`HAHdfsClient`
      over its namenode list;
    - no authority (``hdfs:///path``) → the default nameservice from ``fs.defaultFS``
      when config is readable (HA client for multi-NN services), else libhdfs's
      ``'default'``;
    - explicit ``host:port`` → plain ``HadoopFileSystem`` (a single concrete namenode
      was requested; nothing to fail over to).
    """
    connect = connect or _default_connect
    if hostname and port:
        return connect(hostname, int(port), storage_options=storage_options)
    try:
        resolver = resolver or HdfsNamenodeResolver()
    except Exception:  # noqa: BLE001 — unreadable config: fall through to libhdfs
        resolver = None
    if resolver is not None:
        try:
            if hostname:
                nns = resolver.resolve_hdfs_name_service(hostname)
            else:
                _, nns = resolver.resolve_default_hdfs_service()
        except ValueError:
            nns = None
        if nns and len(nns) > 1:
            return HAHdfsClient(nns, connect=connect,
                                storage_options=storage_options)
        if nns and len(nns) == 1:
            return connect(nns[0][0], nns[0][1], storage_options=storage_options)
    # libhdfs handles 'default' / nameservice authorities from its own config
    return connect(hostname or "default", 0, storage_options=storage_options)
