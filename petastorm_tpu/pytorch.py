"""Migration alias: the reference exposes its torch adapters as ``petastorm.pytorch``
(petastorm/pytorch.py); users switching frameworks keep their import path —
``from petastorm_tpu.pytorch import DataLoader, BatchedDataLoader``.

Canonical home: :mod:`petastorm_tpu.adapters.pytorch`.
"""
from petastorm_tpu.adapters.pytorch import (  # noqa: F401
    BatchedDataLoader,
    DataLoader,
    InMemBatchedDataLoader,
    decimal_friendly_collate,
)
