"""``petastorm-tpu-bench autotune``: does the closed loop actually converge?

**The acceptance harness for the ISSUE-13 controller.** Three arms, every
window driven deterministically (``registry.sample_timelines()`` per batch —
no timer-thread races on loaded CI hosts):

- ``converge``: the :class:`~petastorm_tpu.io.latencyfs.CloudLatencyFS`
  remote-latency injection behind DELIBERATELY WRONG initial knobs
  (``readahead_depth=1`` — every row-group read serializes behind its 20 ms
  simulated round trip). The controller must observe ``io.readahead_wait``
  owning the slow decile (provenance attribution), grow the readahead window
  live, and recover the measured epoch to **>= 80% of the hand-tuned
  arm's rows/s** within a bounded number of windows — each actuation logged
  with its triggering window and culprit signal.
- ``fleet``: a consumer-bound pipeline (slow consumer, short host queue) on
  thread AND process pools. The controller must shrink the worker fleet live
  (producer put-wait share fires ``shrink-workers``), and the chaos-style
  invariant must hold across the resize: delivered ∪ quarantined == plan,
  duplicate-free, ``ptpu_lease_leaked_total`` delta == 0.
- ``clean``: the same workload healthy, controller armed — ZERO actuations
  allowed, and the armed-vs-off throughput delta must stay under the CI
  ceiling (acceptance target <=1% on a quiet host; asserted at 20% because
  shared CI cores jitter far more than the instrument).

The last stdout line is a one-line JSON summary for BENCH artifacts.
"""
from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


def _make_store(root, files=4, row_groups=8, rows_per_group=32):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(13)
    rows_per_file = row_groups * rows_per_group
    for i in range(files):
        pq.write_table(
            pa.table({
                "id": np.arange(rows_per_file, dtype=np.int64)
                + i * rows_per_file,
                "x": rng.random(rows_per_file),
            }),
            os.path.join(root, "part-%02d.parquet" % i),
            row_group_size=rows_per_group)
    return files * rows_per_file


def _leaked_total():
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().counter("ptpu_lease_leaked_total").value


# --------------------------------------------------------------------------------------
# converge arm
# --------------------------------------------------------------------------------------


def _latency_fs(seed=11, base_latency_s=0.02):
    import pyarrow.fs as pafs

    from petastorm_tpu.io.latencyfs import CloudLatencyFS

    # no tail spikes: the bottleneck is the SERIAL latency the wrong
    # readahead depth exposes, and determinism beats drama in CI
    return CloudLatencyFS(pafs.LocalFileSystem(), seed=seed,
                          base_latency_s=base_latency_s, per_byte_s=0.0,
                          tail_fraction=0.0)


def _drain_timed(reader, registry, batch_size, **loader_kwargs):
    """Drain one run, sampling one window per batch; returns
    ``(loader, [batch wall-clock timestamps])``."""
    from petastorm_tpu.loader import DataLoader

    stamps = []
    loader_kwargs.setdefault("host_queue_size", 2)
    with DataLoader(reader, batch_size, to_device=False, metrics=registry,
                    **loader_kwargs) as loader:
        stamps.append(time.perf_counter())
        for batch in loader:
            registry.sample_timelines()
            stamps.append(time.perf_counter())
    return loader, stamps


def _tail_rows_s(stamps, batch_size, tail):
    """rows/s over the LAST ``tail`` batches — the steady-state window, past
    the controller's convergence (and past both arms' cold starts)."""
    tail = min(tail, len(stamps) - 1)
    return tail * batch_size / (stamps[-1] - stamps[-1 - tail])


def scenario_converge(workdir, smoke):
    """Wrong initial knobs + injected latency -> the controller must recover
    to >= 80% of the hand-tuned arm within a bounded number of windows."""
    from petastorm_tpu.control import ControlOptions
    from petastorm_tpu.obs.metrics import MetricsRegistry
    from petastorm_tpu.reader import make_batch_reader

    files = 6 if smoke else 10
    rows_per_group = 32
    root = os.path.join(workdir, "converge")
    os.makedirs(root)
    total = _make_store(root, files=files, rows_per_group=rows_per_group)
    batches = total // rows_per_group
    tail = batches // 2  # measure the second half: converged steady state
    # remote tier explicitly off: this arm isolates the READAHEAD loop (the
    # remote engine's own knobs are unit-tested; one bottleneck per arm)
    io_base = dict(coalesce=False, remote=dict(enabled=False))

    def make(depth, provenance=False):
        # results_queue_size=2: with the default 16 the reader BURSTS far
        # ahead of the consumer's sampling cadence and the exposed-latency
        # windows decouple from production; short queues keep each window
        # aligned with one production period (and match a paced trainer)
        return make_batch_reader(
            "file://" + root, filesystem=_latency_fs(), num_epochs=1,
            workers_count=1, results_queue_size=2, provenance=provenance,
            io_options=dict(io_base, readahead_depth=depth,
                            io_threads=min(depth, 16)))

    # hand-tuned arm: a depth that keeps the latency fully hidden
    registry = MetricsRegistry()
    _, tuned_stamps = _drain_timed(make(8), registry, rows_per_group)
    tuned_rows_s = _tail_rows_s(tuned_stamps, rows_per_group, tail)

    # wrong-knob arm under the controller: converge within the first half,
    # measured over the second
    registry = MetricsRegistry()
    opts = ControlOptions(warmup_windows=3, settle_windows=2)
    loader, ctl_stamps = _drain_timed(make(1, provenance=True), registry,
                                      rows_per_group, controller=opts)
    ctl = loader.controller
    decisions = ctl.decisions()
    actuations = [d for d in decisions if d.cause == "ctl_actuate"]
    depth_moves = [d for d in actuations if d.knob == "readahead_depth"]
    head = min(8, batches)  # the pre-convergence head, for the report
    first_rows_s = head * rows_per_group / (ctl_stamps[head] - ctl_stamps[0])
    final_rows_s = _tail_rows_s(ctl_stamps, rows_per_group, tail)
    recovered = final_rows_s >= 0.8 * tuned_rows_s
    failures = []
    if not depth_moves:
        failures.append("controller never actuated readahead_depth "
                        "(decisions: %r)" % [d.to_dict() for d in decisions])
    else:
        first = depth_moves[0]
        if "io.readahead_wait" not in first.trigger:
            failures.append("actuation trigger does not name the culprit "
                            "signal: %r" % first.trigger)
        if not first.window:
            failures.append("actuation carries no triggering window")
    if ctl.frozen:
        failures.append("controller froze on a recoverable bottleneck")
    if not recovered:
        failures.append(
            "controller-tuned epoch reached %.1f rows/s < 80%% of the "
            "hand-tuned %.1f rows/s" % (final_rows_s, tuned_rows_s))
    return {
        "hand_tuned_rows_s": round(tuned_rows_s, 1),
        "wrong_knob_head_rows_s": round(first_rows_s, 1),
        "converged_tail_rows_s": round(final_rows_s, 1),
        "recovery_fraction": round(final_rows_s / tuned_rows_s, 3),
        "actuations": [d.to_dict() for d in actuations],
        "knob_state": ctl.knobs.describe(),
        "ok": not failures,
    }, failures


# --------------------------------------------------------------------------------------
# fleet arm
# --------------------------------------------------------------------------------------


def scenario_fleet(workdir, smoke, pool):
    """Consumer-bound pipeline -> the controller shrinks the fleet live;
    the chaos-style invariant holds across the resize."""
    import numpy as np

    from petastorm_tpu.control import ControlOptions, Controller, default_rules
    from petastorm_tpu.control.knobs import build_knobset
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.obs.metrics import MetricsRegistry
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "fleet-%s" % pool)
    os.makedirs(root)
    total = _make_store(root, files=3 if smoke else 4, row_groups=8)
    leaked_before = _leaked_total()
    registry = MetricsRegistry()
    workers = 4
    reader = make_batch_reader(
        "file://" + root, num_epochs=2, workers_count=workers,
        reader_pool_type=pool,
        wire_serializer="shm-view" if pool == "process" else "pickle")
    ctl = Controller(build_knobset(reader), rules=default_rules(),
                     registry=registry,
                     options=ControlOptions(warmup_windows=2,
                                            cooldown_windows=1,
                                            settle_windows=1))
    delivered = []
    min_alive = workers
    ctl_ack = None  # process pool: a mid-stream child retune must land live
    respawns_at_retune = None
    with DataLoader(reader, 32, to_device=False, metrics=registry,
                    controller=ctl, host_queue_size=2) as loader:
        for i, batch in enumerate(loader):
            delivered.extend(int(v) for v in np.asarray(batch["id"]))
            time.sleep(0.02)  # the slow consumer: the pipeline IS the bill
            registry.sample_timelines()
            alive = reader.live_workers()
            if alive:  # 0 = stream already drained, not a shrink
                min_alive = min(min_alive, alive)
            if pool == "process":
                # ISSUE 14 satellite: a KnobSet retune of a child-side IO
                # knob reaches ALREADY-RUNNING children over the pool
                # control frame — assert it lands without a respawn
                executor = reader._executor
                if i == 2:
                    respawns_at_retune = executor._respawn_budget
                    reader.apply_readahead_depth(6)
                acks = executor.ctl_acks()
                if any(a.get("readahead_depth") == 6 for a in acks.values()):
                    ctl_ack = acks
        report = reader.quarantine_report
        if pool == "process" and respawns_at_retune is not None:
            if ctl_ack is None:
                # the stream may have drained before a dispatch flushed the
                # frame — check the ledger one last time
                ctl_ack = reader._executor.ctl_acks() or None
            respawn_delta = respawns_at_retune - reader._executor._respawn_budget
    import gc

    gc.collect()
    leak_delta = _leaked_total() - leaked_before
    shrinks = [d for d in ctl.decisions()
               if d.cause == "ctl_actuate" and d.knob == "workers"]
    failures = []
    if not shrinks:
        failures.append("%s pool: controller never shrank the fleet "
                        "(decisions: %r)"
                        % (pool, [d.to_dict() for d in ctl.decisions()]))
    if shrinks and min_alive >= workers:
        failures.append("%s pool: fleet never actually shrank live "
                        "(min alive %d of %d)" % (pool, min_alive, workers))
    # the chaos-style invariant across the live resize
    expected = sorted(list(range(total)) * 2)
    if report:
        failures.append("%s pool: healthy run quarantined %d item(s)"
                        % (pool, len(report)))
    if sorted(delivered) != expected:
        failures.append(
            "%s pool: delivered set != plan across the resize "
            "(%d rows vs %d expected, %d unique)"
            % (pool, len(delivered), len(expected), len(set(delivered))))
    if leak_delta:
        failures.append("%s pool: ptpu_lease_leaked_total moved by %d"
                        % (pool, leak_delta))
    child_retune_ok = None
    if pool == "process" and respawns_at_retune is not None:
        child_retune_ok = bool(ctl_ack) and not respawn_delta
        if not ctl_ack:
            failures.append("%s pool: no running child acked the live "
                            "readahead_depth retune (control frame never "
                            "landed)" % pool)
        elif respawn_delta:
            failures.append("%s pool: the child retune coincided with %d "
                            "respawn(s) — the frame must land on RUNNING "
                            "children" % (pool, respawn_delta))
    return {
        "pool": pool,
        "shrinks": [d.to_dict() for d in shrinks],
        "min_alive": min_alive,
        "delivered_rows": len(delivered),
        "lease_leak_delta": leak_delta,
        "child_retune_ok": child_retune_ok,
        "ok": not failures,
    }, failures


# --------------------------------------------------------------------------------------
# clean arm
# --------------------------------------------------------------------------------------


def scenario_clean(workdir, smoke):
    """Healthy steady state: zero actuations, and the armed plane's
    throughput cost stays under the ceiling. The armed arm runs the REAL
    deployment cadence — a live Reporter sampling timelines on its interval
    (the controller rides its windows), not per-batch sampling."""
    import random

    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.obs.export import Reporter
    from petastorm_tpu.obs.metrics import MetricsRegistry
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "clean")
    os.makedirs(root)
    _make_store(root, files=3, row_groups=8)
    epochs = 6 if smoke else 10
    jsonl = os.path.join(root, "stats.jsonl")

    last_ctl = [None]

    def one_epoch(armed):
        # provenance deliberately OFF in both arms: this arm isolates the
        # CONTROLLER plane's cost (metrics + Reporter cadence + rule
        # evaluation + ctl collector). The provenance plane has its own
        # measured <=1% bar in `petastorm-tpu-bench attribution` — paying
        # its 10Hz window re-fold here would charge attribution's bill to
        # the controller. Without it the controller runs its metric-driven
        # rules (the attribution-driven ones skip — exactly the
        # zero-actuation contract under test).
        reader = make_batch_reader("file://" + root, num_epochs=1,
                                   workers_count=2)
        rows = 0
        t0 = time.perf_counter()
        if armed:
            registry = MetricsRegistry()
            with Reporter(registry=registry, interval_s=0.1,
                          jsonl_path=jsonl):
                with DataLoader(reader, 32, to_device=False,
                                metrics=registry, controller=True) as loader:
                    for batch in loader:
                        rows += len(batch["id"])
                    last_ctl[0] = loader.controller
        else:
            with DataLoader(reader, 32, to_device=False) as loader:
                for batch in loader:
                    rows += len(batch["id"])
        return rows / (time.perf_counter() - t0)

    one_epoch(False)  # warmup
    one_epoch(True)   # armed warmup too: first-use imports (control/,
    #                   Reporter thread, provenance arm) must not eat one of
    #                   the armed arm's best-of slots
    arms = [False] * epochs + [True] * epochs
    random.Random(31).shuffle(arms)
    off, on = [], []
    actuation_total = 0
    for arm in arms:
        rate = one_epoch(arm)
        (on if arm else off).append(rate)
        if arm:
            actuation_total += len([d for d in last_ctl[0].decisions()
                                    if d.cause == "ctl_actuate"])
    off_best, on_best = max(off), max(on)
    overhead = max(0.0, 1.0 - on_best / off_best)
    failures = []
    if actuation_total:
        failures.append("clean arm: controller actuated %d time(s) on a "
                        "healthy pipeline" % actuation_total)
    if smoke and overhead > 0.20:
        failures.append("controller-plane overhead %.1f%% exceeds the 20%% "
                        "smoke ceiling (target <=1%% on a quiet host)"
                        % (100 * overhead))
    return {
        "off_best_rows_s": round(off_best, 1),
        "armed_best_rows_s": round(on_best, 1),
        "overhead_fraction": round(overhead, 4),
        "actuations": actuation_total,
        "ok": not failures,
    }, failures


# --------------------------------------------------------------------------------------


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench autotune", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny stores, hard assertions, 20%% "
                             "overhead ceiling")
    parser.add_argument("--skip-overhead", action="store_true",
                        help="skip the clean armed-vs-off arm")
    parser.add_argument("--pools", nargs="*", default=["thread", "process"],
                        choices=["thread", "process"],
                        help="pools for the fleet arm")
    args = parser.parse_args(argv)

    failures = []
    summary = {"bench": "autotune"}

    with tempfile.TemporaryDirectory(prefix="ptpu-autotune-") as workdir:
        converge, f = scenario_converge(workdir, smoke=args.smoke)
        failures.extend(f)
        summary["converge"] = converge
        print("converge: hand-tuned %.0f rows/s, wrong knobs %.0f -> %.0f "
              "after %d actuation(s) (%.0f%% of hand-tuned)%s"
              % (converge["hand_tuned_rows_s"],
                 converge["wrong_knob_head_rows_s"],
                 converge["converged_tail_rows_s"],
                 len(converge["actuations"]),
                 100 * converge["recovery_fraction"],
                 "" if converge["ok"] else "  [FAIL]"))
        for d in converge["actuations"]:
            print("  window %d: %s %s %r -> %r (%s)"
                  % (d["window"], d["rule"], d["knob"], d["before"],
                     d["after"], d["trigger"]))

    summary["fleet"] = []
    for pool in args.pools:
        with tempfile.TemporaryDirectory(prefix="ptpu-autotune-") as workdir:
            fleet, f = scenario_fleet(workdir, smoke=args.smoke, pool=pool)
        failures.extend(f)
        summary["fleet"].append(fleet)
        print("fleet[%s]: %d shrink decision(s), min alive %d, %d rows "
              "delivered, lease leak delta %d%s"
              % (pool, len(fleet["shrinks"]), fleet["min_alive"],
                 fleet["delivered_rows"], fleet["lease_leak_delta"],
                 "" if fleet["ok"] else "  [FAIL]"))

    if not args.skip_overhead:
        with tempfile.TemporaryDirectory(prefix="ptpu-autotune-") as workdir:
            clean, f = scenario_clean(workdir, smoke=args.smoke)
        failures.extend(f)
        summary["clean"] = clean
        print("clean: off %.0f vs armed %.0f rows/s best-of-epochs "
              "(overhead %.2f%%, target <=1%%), %d actuation(s)%s"
              % (clean["off_best_rows_s"], clean["armed_best_rows_s"],
                 100 * clean["overhead_fraction"], clean["actuations"],
                 "" if clean["ok"] else "  [FAIL]"))

    summary["failures"] = failures
    print(json.dumps(summary, ensure_ascii=False))
    if failures:
        for failure in failures:
            print("FAIL:", failure)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
