"""``petastorm-tpu-bench fleet``: one decode fleet feeding many trainers —
does disaggregation actually cut decode work, and does it stay exact?

**The acceptance harness for the ISSUE-19 disaggregated data service.**
Scenarios (``--scenarios`` selects a subset; default runs the first three):

- ``shared``: 3 trainers attached to ONE service/fleet vs 3 dedicated
  pipelines decoding the same plan independently. The decode cost is a
  calibrated synthetic sleep, so decode worker-seconds are deterministic;
  the harness asserts the shared fleet's decode worker-seconds **per
  delivered row** are cut >=2x (decode-once/serve-many), that every trainer
  received the full plan exactly once (delivered sets duplicate-free and
  identical), and that zero leases leaked in either arm.
- ``elasticity``: a trainer detaches mid-epoch (``state_dict()`` +
  ``stop()``); a replacement attaches with ``load_state_dict`` and must
  receive EXACTLY the remaining plan — no loss, no replay, the
  checkpoint-watermark contract over the wire.
- ``qos``: two tenants share the fleet; the noisy one runs a slow decode.
  The PR 18 accounting plane must name it: ``TenantUsageReport`` shows the
  noisy tenant as the top worker-seconds consumer, and a per-tenant burn
  SLO (``SloSpec(per_tenant=True)``) fires an alert naming the noisy tenant
  while the quiet tenant never alerts.
- ``linkdeath``: a seeded ``chaos`` ``net.reset`` is armed on
  ``transport.send`` during dispatch. Whichever link it kills (worker or
  trainer), the run must stay exact: delivered == plan, ZERO quarantined
  items (link faults re-dispatch, they do not poison), zero leaked leases,
  and at least one observed reconnect.
- ``attribution``: the ISSUE-20 fleet-observability acceptance arm. Latency
  is injected into ONE of two decode workers for a bounded window; every
  layer must name that worker: the trainer's cross-wire provenance fold
  (``attribution_report().slow_top`` == ``svc.decode@<worker>``), the
  ``/fleet`` straggler alert scraped live during the drain, and the
  FleetAdvisor (``ptpu_svc_advised_workers`` rises above the actual fleet
  size during the injection and returns once it clears). Exactness and
  zero leaked leases still hold with provenance riding every frame.
- ``provoverhead``: the same 2-worker drain with the cross-wire provenance
  plane fully off vs fully on; wall-clock overhead must stay under the CI
  ceiling (20% — the paper target is <=1%, but CI hosts are noisy).

The last stdout line is a one-line JSON summary for BENCH artifacts.
"""
from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np

from petastorm_tpu.recovery import RecoveryOptions
from petastorm_tpu.service import (
    DataService,
    DecodeWorker,
    JobSpec,
    ServiceOptions,
    ServiceReader,
)
from petastorm_tpu.service.protocol import svc_metrics
from petastorm_tpu.unischema import Unischema, UnischemaField

QUIET = "a-quiet"
NOISY = "b-noisy"

SCHEMA = Unischema("fleet", [UnischemaField("id", np.int64, (), None, False)])

#: rows each synthetic decode yields (the per-row denominator below)
ROWS_PER_ITEM = 8
#: synthetic decode cost — sleeps dominate, so worker-seconds are a property
#: of the PLAN (items x cost), not of host speed: the shared-vs-dedicated
#: ratio is deterministic
DECODE_COST_S = 0.004
NOISY_COST_S = 0.04
_BURN_BUDGET_S = 0.05
_SAMPLE_S = 0.25


def _rec():
    return RecoveryOptions(link_heartbeat_s=0.1, link_miss_threshold=3,
                           link_reconnect_s=8.0, link_connect_timeout_s=5.0,
                           io_retry_backoff_s=0.01)


def decode_shared(item):
    time.sleep(DECODE_COST_S)
    return {"id": np.arange(ROWS_PER_ITEM, dtype=np.int64)
            + item * ROWS_PER_ITEM}


def decode_quiet(item):
    time.sleep(0.001)
    return {"id": np.full(ROWS_PER_ITEM, item, dtype=np.int64)}


def decode_noisy(item):
    time.sleep(NOISY_COST_S)
    return {"id": np.full(ROWS_PER_ITEM, item, dtype=np.int64)}


SLOW_WORKER = "w-slow"
FAST_WORKER = "w-fast"
ATTR_COST_S = 0.01
ATTR_LAG_S = 0.05
#: gate for the injected straggler: ``decode_attr`` lags ONLY on the thread
#: named ``ptpu-w-slow`` (``DecodeWorker.start`` names its thread after the
#: worker) and only until this monotonic deadline — armed by the scenario
_ATTR_LAG_UNTIL = [0.0]


def decode_attr(item):
    time.sleep(ATTR_COST_S)
    if (threading.current_thread().name == "ptpu-%s" % SLOW_WORKER
            and time.monotonic() < _ATTR_LAG_UNTIL[0]):
        time.sleep(ATTR_LAG_S)
    return {"id": np.arange(ROWS_PER_ITEM, dtype=np.int64)
            + item * ROWS_PER_ITEM}


def _svc_snapshot():
    return {k: v.value for k, v in svc_metrics().items()}


def _svc_delta(before, key):
    return svc_metrics()[key].value - before[key]


def _drain(reader, out, key):
    """Thread target: drain one trainer, collecting delivered item ids."""
    items = []
    try:
        for batch in reader:
            items.append(int(batch.id[0]) // ROWS_PER_ITEM
                         if key == "tagged" else int(batch.id[0]))
    except Exception as e:  # noqa: BLE001 — surfaced as a bench failure
        out["error"] = repr(e)
    out["items"] = items


def _exactness(name, items, plan, failures):
    """delivered must be the plan exactly once: duplicate-free and total."""
    if len(items) != len(set(items)):
        failures.append("%s: %d duplicate deliveries"
                        % (name, len(items) - len(set(items))))
    if sorted(set(items)) != sorted(plan):
        missing = set(plan) - set(items)
        extra = set(items) - set(plan)
        failures.append("%s: delivered != plan (missing %s, extra %s)"
                        % (name, sorted(missing)[:8], sorted(extra)[:8]))


def _run_fleet(n_items, n_trainers, n_workers, decode, rec):
    """One service, ``n_trainers`` attached BEFORE the fleet starts (the
    steady-state shape: decode-once fans out to everybody). Returns
    ``(per-trainer item lists, decode worker-seconds, failures)``."""
    failures = []
    before = _svc_snapshot()
    svc = DataService(options=ServiceOptions(arena=False), recovery=rec)
    svc.add_job(JobSpec("fleet", list(range(n_items)), decode, SCHEMA))
    readers = [ServiceReader(svc.trainer_address(), svc.token, job="fleet",
                             trainer="t%d" % i, recovery=rec, arena=False)
               for i in range(n_trainers)]
    fleet = [DecodeWorker(svc.worker_address(), svc.token, recovery=rec)
             for _ in range(n_workers)]
    for w in fleet:
        w.start()
    outs = [{} for _ in readers]
    threads = [threading.Thread(target=_drain, args=(r, out, "tagged"))
               for r, out in zip(readers, outs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i, out in enumerate(outs):
        if "error" in out:
            failures.append("trainer %d drain died: %s" % (i, out["error"]))
        _exactness("trainer %d" % i, out.get("items", []),
                   range(n_items), failures)
    leases = svc.outstanding_leases()
    if leases:
        failures.append("%d leases outstanding after full drain" % leases)
    for r in readers:
        r.stop()
    svc.stop()
    if _svc_delta(before, "lease_leaked"):
        failures.append("%d leases leaked at service stop"
                        % _svc_delta(before, "lease_leaked"))
    return ([out.get("items", []) for out in outs],
            _svc_delta(before, "decode_seconds"), failures)


def scenario_shared(smoke):
    """3 trainers on one fleet vs 3 dedicated pipelines: decode
    worker-seconds per delivered row must drop >=2x."""
    failures = []
    n_items = 12 if smoke else 32
    rec = _rec()

    _sets, shared_ws, f = _run_fleet(n_items, 3, 2, decode_shared, rec)
    failures.extend("shared arm: %s" % x for x in f)
    shared_rows = 3 * n_items * ROWS_PER_ITEM

    dedicated_ws = 0.0
    dedicated_rows = 0
    for i in range(3):
        _s, ws, f = _run_fleet(n_items, 1, 2, decode_shared, rec)
        failures.extend("dedicated pipeline %d: %s" % (i, x) for x in f)
        dedicated_ws += ws
        dedicated_rows += n_items * ROWS_PER_ITEM

    shared_per_row = shared_ws / max(1, shared_rows)
    dedicated_per_row = dedicated_ws / max(1, dedicated_rows)
    cut = dedicated_per_row / max(shared_per_row, 1e-12)
    if cut < 2.0:
        failures.append(
            "decode worker-seconds per delivered row cut only %.2fx "
            "(shared %.6fs/row vs dedicated %.6fs/row) — acceptance "
            "needs >=2x" % (cut, shared_per_row, dedicated_per_row))
    return {
        "items": n_items,
        "shared_decode_s": round(shared_ws, 4),
        "dedicated_decode_s": round(dedicated_ws, 4),
        "worker_s_per_row_cut": round(cut, 2),
        "ok": not failures,
    }, failures


def scenario_elasticity(smoke):
    """Mid-epoch detach + reattach: the presented consumed-watermark is the
    ONLY resume authority, and it must be exact."""
    failures = []
    n_items = 12 if smoke else 24
    take = n_items // 3
    rec = _rec()
    svc = DataService(options=ServiceOptions(arena=False), recovery=rec)
    svc.add_job(JobSpec("fleet", list(range(n_items)), decode_shared, SCHEMA))
    worker = DecodeWorker(svc.worker_address(), svc.token, recovery=rec)
    worker.start()

    r1 = ServiceReader(svc.trainer_address(), svc.token, job="fleet",
                       trainer="elastic", recovery=rec, arena=False)
    first = [int(next(r1).id[0]) // ROWS_PER_ITEM for _ in range(take)]
    state = r1.state_dict()
    r1.stop()  # mid-epoch detach: unconsumed claims return, nothing lost

    r2 = ServiceReader(svc.trainer_address(), svc.token, job="fleet",
                       trainer="elastic", recovery=rec, arena=False)
    r2.load_state_dict(state)
    out = {}
    _drain(r2, out, "tagged")
    rest = out.get("items", [])
    r2.stop()
    leases = svc.outstanding_leases()
    svc.stop()

    if "error" in out:
        failures.append("reattached trainer died: %s" % out["error"])
    if set(first) & set(rest):
        failures.append("replayed after reattach: %s"
                        % sorted(set(first) & set(rest)))
    _exactness("detach+reattach union", first + rest, range(n_items),
               failures)
    if leases:
        failures.append("%d leases outstanding after reattach drain" % leases)
    return {"items": n_items, "before_detach": len(first),
            "after_reattach": len(rest), "ok": not failures}, failures


def scenario_qos(smoke):
    """Two tenants, one fleet: the accounting plane must name the noisy
    neighbor — usage report AND a per-tenant burn alert."""
    from petastorm_tpu.obs import tenant as tenant_mod
    from petastorm_tpu.obs.metrics import default_registry
    from petastorm_tpu.obs.slo import SloEngine, SloSpec

    failures = []
    registry = default_registry()
    snap0 = registry.snapshot()
    rec = _rec()

    svc = DataService(options=ServiceOptions(arena=False), recovery=rec)
    svc.add_job(JobSpec("quiet", list(range(6)), decode_quiet, SCHEMA,
                        tenant=QUIET))
    # the noisy plan is sized so its drain spans several sample windows
    # (~0.25s each at 2 workers x 40ms/item): the burn SLO's 2-window
    # debounce needs consecutive breaching windows, not one spike
    svc.add_job(JobSpec("noisy", list(range(30 if smoke else 60)),
                        decode_noisy, SCHEMA, tenant=NOISY))

    spec = SloSpec(name="fleet-tenant-burn",
                   metric=tenant_mod.RESOURCES["worker_s"][0],
                   stat="delta", op="<=", threshold=_BURN_BUDGET_S,
                   breach_windows=2, per_tenant=True,
                   description="per-window decode worker-seconds budget "
                               "per tenant on the shared fleet")
    engine = SloEngine(specs=[spec], registry=registry)
    engine.attach(registry.timeline_store())

    rq = ServiceReader(svc.trainer_address(), svc.token, job="quiet",
                       recovery=rec, arena=False)
    rn = ServiceReader(svc.trainer_address(), svc.token, job="noisy",
                       recovery=rec, arena=False)
    workers = [DecodeWorker(svc.worker_address(), svc.token, recovery=rec)
               for _ in range(2)]
    for w in workers:
        w.start()
    out_q, out_n = {}, {}
    threads = [threading.Thread(target=_drain, args=(rq, out_q, "raw")),
               threading.Thread(target=_drain, args=(rn, out_n, "raw"))]
    for t in threads:
        t.start()
    while any(t.is_alive() for t in threads):
        time.sleep(_SAMPLE_S)
        registry.sample_timelines()
    for t in threads:
        t.join()
    registry.sample_timelines()
    rq.stop()
    rn.stop()
    svc.stop()

    report = tenant_mod.TenantUsageReport.from_metrics(
        {name: value - snap0.get(name, 0)
         for name, value in registry.snapshot().items()
         if isinstance(value, (int, float))
         and isinstance(snap0.get(name, 0), (int, float))})
    top, top_v = report.top_consumer("worker_s")
    if top != NOISY:
        failures.append("top worker-seconds consumer is %r (%.3fs), "
                        "expected %r" % (top, top_v, NOISY))
    svc_items = report.get(NOISY, "svc_items")
    if svc_items <= 0:
        failures.append("no ptpu_tenant_svc_items_total charged to %r"
                        % NOISY)

    breaches = [a for a in engine.alerts() if a.cause == "slo_breach"]
    noisy_alerts = [a for a in breaches if a.tenant == NOISY]
    quiet_alerts = [a for a in breaches if a.tenant == QUIET]
    if not noisy_alerts:
        failures.append("no per-tenant burn alert named %r (windows "
                        "evaluated: %d)" % (NOISY, engine.windows_evaluated))
    if quiet_alerts:
        failures.append("the quiet tenant %r fired %d burn alerts"
                        % (QUIET, len(quiet_alerts)))
    return {
        "top_worker_s": top,
        "noisy_worker_s": round(report.get(NOISY, "worker_s"), 4),
        "quiet_worker_s": round(report.get(QUIET, "worker_s"), 4),
        "alerts": [{"tenant": a.tenant, "value": a.value} for a in breaches],
        "ok": not failures,
    }, failures


def scenario_linkdeath(smoke):
    """Seeded chaos net.reset during dispatch: exactness must survive
    whichever link it kills."""
    from petastorm_tpu import chaos
    from petastorm_tpu.chaos import FaultPlan, FaultRule
    from petastorm_tpu.obs.metrics import default_registry

    failures = []
    n_items = 12 if smoke else 24
    rec = _rec()
    before = _svc_snapshot()
    reconnects = default_registry().counter("ptpu_net_reconnects_total")
    reconnects0 = reconnects.value

    plan = FaultPlan([
        FaultRule("transport.send", "net.reset", nth=9, times=1),
    ], seed=7)
    svc = DataService(options=ServiceOptions(arena=False), recovery=rec)
    svc.add_job(JobSpec("fleet", list(range(n_items)), decode_shared, SCHEMA))
    reader = ServiceReader(svc.trainer_address(), svc.token, job="fleet",
                           recovery=rec, arena=False)
    worker = DecodeWorker(svc.worker_address(), svc.token, recovery=rec)
    out = {}
    chaos.arm(plan, propagate=False)
    try:
        worker.start()
        _drain(reader, out, "tagged")
    finally:
        chaos.disarm()
    leases = svc.outstanding_leases()
    reader.stop()
    svc.stop()

    if "error" in out:
        failures.append("trainer drain died under net.reset: %s"
                        % out["error"])
    _exactness("linkdeath trainer", out.get("items", []), range(n_items),
               failures)
    if _svc_delta(before, "quarantined"):
        failures.append("link faults must re-dispatch, not quarantine "
                        "(%d items)" % _svc_delta(before, "quarantined"))
    if leases or _svc_delta(before, "lease_leaked"):
        failures.append("leases outstanding/leaked after the link death "
                        "(%d/%d)" % (leases,
                                     _svc_delta(before, "lease_leaked")))
    recon = reconnects.value - reconnects0
    if plan.stats().get("injected_total", 0) and recon < 1:
        failures.append("net.reset fired but no transport reconnect "
                        "was observed")
    return {"items": n_items, "reconnects": recon,
            "redispatches": _svc_delta(before, "lease_redispatch"),
            "chaos": plan.stats(), "ok": not failures}, failures


def scenario_attribution(smoke):
    """Inject latency into ONE decode worker: the trainer's provenance fold,
    the live ``/fleet`` scrape, and the advisor must all name it."""
    import urllib.request

    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.obs.metrics import MetricsRegistry

    failures = []
    n_items = 160 if smoke else 320
    rec = _rec()
    before = _svc_snapshot()
    svc = DataService(options=ServiceOptions(
        arena=False, sample_s=_SAMPLE_S,
        straggler_decode_p99_s=ATTR_COST_S + ATTR_LAG_S / 2), recovery=rec)
    svc.add_job(JobSpec("fleet", list(range(n_items)), decode_attr, SCHEMA,
                        tenant="attr-tenant"))
    # each worker homes its counters on a PRIVATE registry (the worker-side
    # homing contract): /fleet must still merge both sources by name
    workers = [DecodeWorker(svc.worker_address(), svc.token, recovery=rec,
                            name=name, registry=MetricsRegistry(),
                            telemetry_s=0.5)
               for name in (SLOW_WORKER, FAST_WORKER)]
    _ATTR_LAG_UNTIL[0] = time.monotonic() + (2.0 if smoke else 2.5)
    for w in workers:
        w.start()
    # ordered delivery pins the lagged worker's latency to its own items
    # (head of line) so the step-gap decile can name it
    reader = ServiceReader(svc.trainer_address(), svc.token, job="fleet",
                           trainer="attr", recovery=rec, arena=False,
                           ordered=True)
    loader = DataLoader(reader, batch_size=ROWS_PER_ITEM, to_device=False,
                        provenance=True)
    ms = svc.metrics_server()

    samples = []      # (advised, actual) at ~10Hz while the watcher runs
    fleet_docs = []   # /fleet scrapes taken DURING the drain
    scrape_errors = []
    draining = threading.Event()
    draining.set()
    done = threading.Event()

    def _watch():
        m = svc_metrics()
        next_scrape = time.monotonic()
        while not done.wait(0.1):
            samples.append((m["advised_workers"].value, m["workers"].value))
            if draining.is_set() and time.monotonic() >= next_scrape:
                next_scrape = time.monotonic() + 0.5
                try:
                    with urllib.request.urlopen(ms.url + "/fleet",
                                                timeout=2) as resp:
                        fleet_docs.append(json.loads(resp.read()))
                except Exception as exc:  # noqa: BLE001 — reported once below
                    scrape_errors.append(repr(exc))

    watcher = threading.Thread(target=_watch, daemon=True)
    watcher.start()
    items = []
    with loader:
        for batch in loader:
            items.append(int(batch["id"][0]) // ROWS_PER_ITEM)
        report = loader.attribution_report()
    draining.clear()
    # let the injection window close and the advisor walk back down
    time.sleep(8 * _SAMPLE_S)
    done.set()
    watcher.join(timeout=5)
    advised_after = svc_metrics()["advised_workers"].value
    leases = svc.outstanding_leases()
    ms.stop()
    svc.stop()

    _exactness("attribution trainer", items, range(n_items), failures)
    if leases or _svc_delta(before, "lease_leaked"):
        failures.append("leases outstanding/leaked with provenance riding "
                        "every frame (%d/%d)"
                        % (leases, _svc_delta(before, "lease_leaked")))
    culprit_site = "svc.decode@%s" % SLOW_WORKER
    if report.slow_top != culprit_site:
        failures.append("slow_top is %r, expected %r — cross-wire "
                        "provenance did not name the lagged worker (slow "
                        "share: %s)" % (report.slow_top, culprit_site,
                                        report.slow_share))
    if scrape_errors:
        failures.append("/fleet scrape failed %d times (first: %s)"
                        % (len(scrape_errors), scrape_errors[0]))
    if not fleet_docs:
        failures.append("no /fleet document captured during the drain")
    alert_workers = {a.get("worker") for doc in fleet_docs
                     for a in doc.get("alerts", ())}
    if SLOW_WORKER not in alert_workers:
        failures.append("no /fleet straggler alert named %r (alerts over "
                        "%d scrapes: %s)"
                        % (SLOW_WORKER, len(fleet_docs),
                           sorted(a for a in alert_workers if a)))
    if FAST_WORKER in alert_workers:
        failures.append("the healthy worker %r fired a straggler alert"
                        % FAST_WORKER)
    healthy = [doc for doc in fleet_docs
               if {SLOW_WORKER, FAST_WORKER} <= set(doc.get("workers", {}))]
    if not healthy:
        failures.append("/fleet never showed health for both workers")
    merged_sources = {src for doc in fleet_docs
                      for src in doc.get("sources", ())}
    for want in ("worker:%s" % SLOW_WORKER, "worker:%s" % FAST_WORKER,
                 "trainer:attr"):
        if want not in merged_sources:
            failures.append("/fleet fleet merge never included source %r "
                            "(saw %s)" % (want, sorted(merged_sources)))
    actual = max((a for _adv, a in samples), default=0)
    advised_peak = max((adv for adv, _a in samples), default=0)
    if actual != 2:
        failures.append("expected 2 connected workers, gauge peaked at %s"
                        % actual)
    if advised_peak <= actual:
        failures.append("ptpu_svc_advised_workers never rose above the "
                        "actual fleet size during the injection (peak %s, "
                        "actual %s)" % (advised_peak, actual))
    if advised_after > actual:
        failures.append("advised workers stuck at %s after the injection "
                        "cleared (actual %s)" % (advised_after, actual))
    return {
        "items": n_items,
        "slow_top": report.slow_top,
        "alert_workers": sorted(a for a in alert_workers if a),
        "advised_peak": advised_peak,
        "advised_after": advised_after,
        "fleet_scrapes": len(fleet_docs),
        "ok": not failures,
    }, failures


def _prov_arm(n_items, on, rec, failures):
    """One 2-worker drain through the DataLoader with the cross-wire
    provenance plane fully off or fully on; returns wall seconds."""
    from petastorm_tpu.loader import DataLoader

    svc = DataService(options=ServiceOptions(arena=False), recovery=rec)
    svc.add_job(JobSpec("fleet", list(range(n_items)), decode_shared, SCHEMA))
    workers = [DecodeWorker(svc.worker_address(), svc.token, recovery=rec,
                            provenance=on, telemetry_s=2.0 if on else None)
               for _ in range(2)]
    for w in workers:
        w.start()
    reader = ServiceReader(svc.trainer_address(), svc.token, job="fleet",
                           recovery=rec, arena=False,
                           telemetry_s=2.0 if on else None)
    loader = DataLoader(reader, batch_size=ROWS_PER_ITEM, to_device=False,
                        provenance=True if on else None)
    items = []
    t0 = time.monotonic()
    with loader:
        for batch in loader:
            items.append(int(batch["id"][0]) // ROWS_PER_ITEM)
    wall = time.monotonic() - t0
    leases = svc.outstanding_leases()
    svc.stop()
    _exactness("provenance=%s arm" % on, items, range(n_items), failures)
    if leases:
        failures.append("provenance=%s arm: %d leases outstanding"
                        % (on, leases))
    return wall


def scenario_provoverhead(smoke):
    """Cross-wire provenance overhead: the same drain with the plane off vs
    on. Paper target <=1%; the CI assertion allows 20% (noisy hosts, tiny
    absolute walls)."""
    failures = []
    n_items = 96 if smoke else 192
    ceiling = 0.20
    rec = _rec()
    # min-of-2 per arm damps scheduler jitter on small absolute walls
    off = min(_prov_arm(n_items, False, rec, failures) for _ in range(2))
    on = min(_prov_arm(n_items, True, rec, failures) for _ in range(2))
    overhead = (on - off) / max(off, 1e-9)
    if overhead > ceiling:
        failures.append("cross-wire provenance overhead %.1f%% exceeds the "
                        "%.0f%% CI ceiling (off %.3fs, on %.3fs)"
                        % (100 * overhead, 100 * ceiling, off, on))
    return {
        "items": n_items,
        "wall_off_s": round(off, 4),
        "wall_on_s": round(on, 4),
        "overhead_pct": round(100 * overhead, 2),
        "ceiling_pct": round(100 * ceiling, 1),
        "ok": not failures,
    }, failures


SCENARIOS = {
    "shared": scenario_shared,
    "elasticity": scenario_elasticity,
    "qos": scenario_qos,
    "linkdeath": scenario_linkdeath,
    "attribution": scenario_attribution,
    "provoverhead": scenario_provoverhead,
}
DEFAULT_SCENARIOS = ("shared", "elasticity", "qos")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench fleet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: small plans, hard assertions")
    parser.add_argument("--scenarios", nargs="+", default=None,
                        choices=sorted(SCENARIOS),
                        metavar="{%s}" % ",".join(sorted(SCENARIOS)),
                        help="subset to run (default: %s)"
                        % " ".join(DEFAULT_SCENARIOS))
    args = parser.parse_args(argv)

    names = tuple(args.scenarios) if args.scenarios else DEFAULT_SCENARIOS
    failures = []
    results = {}
    for name in names:
        result, scenario_failures = SCENARIOS[name](smoke=args.smoke)
        results[name] = result
        failures.extend("%s: %s" % (name, f) for f in scenario_failures)
        print("%s: %s (%s)" % (name,
                               {k: v for k, v in result.items() if k != "ok"},
                               "OK" if result["ok"] else "FAILING"))

    summary = {"bench": "fleet", "scenarios": results, "failures": failures}
    print(json.dumps(summary, ensure_ascii=False))
    if failures:
        for failure in failures:
            print("FAIL:", failure)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
