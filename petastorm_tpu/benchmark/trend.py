"""``petastorm-tpu-bench trend``: the CI throughput-regression gate.

The BENCH artifacts record per-PR numbers, but nothing in CI ever COMPARED
them — a PR could halve rows/s and land green. This gate closes that hole:

1. run a small fixed synthetic workload through the real
   reader→DataLoader path (best of N post-warmup epochs — contention on
   shared CI cores can only LOWER an epoch, so the best one is the
   machine's throughput envelope; a real code regression lowers the
   envelope itself),
2. append the one-line JSON summary (schema ``ptpu-bench-trend-v1``) to the
   history file (``BENCH_HISTORY.jsonl`` at the repo root by default),
3. FAIL (exit 1) when the measured best rows/s regresses more than
   ``--threshold`` (default 30%) against the MEDIAN of the stored history
   for the SAME workload fingerprint — median baseline so one historically
   lucky run cannot ratchet the bar up, per-workload so a full run's
   numbers never gate a smoke run.

An empty (or missing) history is seeded with the current run and passes —
the gate arms itself on first use. The entry is appended BEFORE the verdict
so a failing run is still recorded (the regression is visible in the
history, not just the log).

Schema v2 (ISSUE 12): entries additionally carry the measured workload's
per-site critical-path self-times (``"sites"``, from the attribution plane
— the run executes with ``provenance=True``, ~1% instrument cost well
inside the 30% gate margin) and its step p99, so a failing gate can say
WHY: the failure message runs ``petastorm-tpu-bench diff``-style forensics
against the baseline entry and names the regressed site ("rows/s −28%:
io.remote self-time 2.3×") instead of just the number. v1 entries remain
loadable (they simply carry no sites), so existing histories keep gating.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import tempfile
import time

SCHEMA = "ptpu-bench-trend-v2"
#: older entries stay comparable — the gate metric (rows_per_s per workload
#: fingerprint) is identical across versions; only the forensic fields grew
ACCEPTED_SCHEMAS = ("ptpu-bench-trend-v1", "ptpu-bench-trend-v2")


def _make_store(root, files, rows_per_file):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(13)
    for i in range(files):
        pq.write_table(
            pa.table({
                "id": np.arange(rows_per_file, dtype=np.int64)
                + i * rows_per_file,
                "a": rng.random(rows_per_file),
                "b": rng.random(rows_per_file),
                "c": rng.integers(0, 1000, rows_per_file),
            }),
            os.path.join(root, "part-%02d.parquet" % i),
            row_group_size=max(64, rows_per_file // 4))
    return files * rows_per_file


def measure(files=4, rows_per_file=2048, batch_size=256, epochs=5):
    """Gate metric: BEST rows/s over ``epochs`` fresh single-epoch loader
    runs of the fixed synthetic workload (thread pool, readahead on — the
    default production read path), after one discarded warmup epoch (import
    + first-open costs).

    Best-of-N, not median-of-N: on shared CI cores a co-tenant can halve any
    individual epoch (observed 2-30x swings), but contention can only LOWER
    an epoch — it cannot inflate one. The best epoch is the machine's
    throughput envelope, and a real code regression lowers the envelope
    itself.

    Every epoch runs with the provenance plane on (ISSUE 12) so the BEST
    epoch's per-site critical-path self-times ride into the trend entry —
    the forensic baseline ``petastorm-tpu-bench diff`` compares against.
    The ~1% instrument cost applies equally to every entry (and to the
    stored baseline from the first v2 run on), so the gate comparison stays
    apples-to-apples. Returns ``(best, all_measured_rates, best_forensics)``
    where ``best_forensics`` is ``{"sites": {...}, "step_p99_s": ...}``."""
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    def one_epoch():
        reader = make_batch_reader("file://" + root, num_epochs=1,
                                   workers_count=2, provenance=True)
        rows = 0
        t0 = time.perf_counter()
        with DataLoader(reader, batch_size, to_device=False) as loader:
            for batch in loader:
                rows += len(batch["id"])
        rate = rows / (time.perf_counter() - t0)
        assert rows == total, (rows, total)
        report = loader.attribution_report()
        return rate, {"sites": {site: round(sec, 4) for site, sec
                                in report.stage_self_s.items()},
                      "step_p99_s": report.step_p99_s}

    rates = []
    forensics = []
    with tempfile.TemporaryDirectory(prefix="ptpu-trend-") as root:
        total = _make_store(root, files, rows_per_file)
        one_epoch()  # warmup: imports, first-open footers, allocator warm
        for _ in range(epochs):
            rate, f = one_epoch()
            rates.append(rate)
            forensics.append(f)
    best_idx = max(range(len(rates)), key=rates.__getitem__)
    return rates[best_idx], rates, forensics[best_idx]


def load_history(path, workload=None):
    """Prior trend entries (same schema, same WORKLOAD fingerprint) from the
    history JSONL, oldest first; malformed/foreign lines are skipped (the
    file is shared with other bench artifacts). The workload filter keeps
    the baseline comparable: a full run's median must never gate a smoke
    run (different store size/batch size = a different number)."""
    entries = []
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for line in f:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and obj.get("schema") in ACCEPTED_SCHEMAS \
                    and obj.get("rows_per_s") \
                    and (workload is None or obj.get("workload") == workload):
                entries.append(obj)
    return entries


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench trend", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--history", default="BENCH_HISTORY.jsonl",
                        help="history JSONL to append to / gate against")
    parser.add_argument("--threshold", type=float, default=0.30,
                        help="fail when best-of-N rows/s drops more than this "
                             "fraction below the history median (default "
                             "0.30)")
    parser.add_argument("--epochs", type=int, default=5,
                        help="post-warmup epochs to sample (default 5)")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: smaller store, 3 epochs")
    parser.add_argument("--dry-run", action="store_true",
                        help="measure + compare but do not append")
    args = parser.parse_args(argv)

    if args.smoke:
        shape = dict(files=3, rows_per_file=1024, batch_size=128)
        best, rates, forensics = measure(epochs=min(args.epochs, 3), **shape)
    else:
        shape = dict(files=4, rows_per_file=2048, batch_size=256)
        best, rates, forensics = measure(epochs=args.epochs)
    #: the comparability fingerprint: only same-shaped runs share a baseline
    workload = "f%d-r%d-b%d" % (shape["files"], shape["rows_per_file"],
                                shape["batch_size"])

    history = load_history(args.history, workload=workload)
    baseline = statistics.median(e["rows_per_s"] for e in history) \
        if history else None

    entry = {
        "schema": SCHEMA,
        "ts": time.time(),
        "workload": workload,
        "rows_per_s": round(best, 1),
        "epoch_rates": [round(r, 1) for r in rates],
        "smoke": bool(args.smoke),
        "baseline_rows_per_s": None if baseline is None
        else round(baseline, 1),
        "history_entries": len(history),
        #: forensic fields (schema v2): the best epoch's per-site
        #: critical-path self-times + step p99 — what `bench diff` compares
        "sites": forensics["sites"],
        "step_p99_s": forensics["step_p99_s"],
    }
    regressed = baseline is not None \
        and best < (1.0 - args.threshold) * baseline
    entry["regressed"] = regressed
    if not args.dry_run:
        # append before the verdict: a FAILING run must still be recorded
        with open(args.history, "a") as f:
            f.write(json.dumps(entry) + "\n")

    if baseline is None:
        print("trend: history empty for workload %s — seeded with %.0f "
              "rows/s (gate arms on the next run)" % (workload, best))
    else:
        delta = best / baseline - 1.0
        print("trend: %.0f rows/s vs history median %.0f (%+.1f%%; gate "
              "fails below %+.0f%%, %d prior %s entries)"
              % (best, baseline, 100 * delta, -100 * args.threshold,
                 len(history), workload))
    print(json.dumps(entry))
    if regressed:
        # forensics (ISSUE 12): diff the regressed run against the baseline
        # entry closest to the gating median, so the failure NAMES the site
        # that regressed instead of just the number
        baseline_entry = min(
            history, key=lambda e: abs(e["rows_per_s"] - baseline))
        if baseline_entry.get("sites"):
            from petastorm_tpu.obs.diff import diff_runs

            verdict = diff_runs(baseline_entry, entry)
            print("why: %s" % verdict["verdict"])
            if verdict["regressed_site"]:
                print("     rerun `petastorm-tpu-bench diff -2 -1 --history "
                      "%s` for the per-site table" % args.history)
        print("FAIL: throughput regressed more than %.0f%% vs the stored "
              "median — investigate before merging (history: %s)"
              % (100 * args.threshold, args.history))
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
