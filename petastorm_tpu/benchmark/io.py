"""Async-IO micro-benchmark: cold sequential vs readahead vs readahead+coalesce.

Measures exactly what ``petastorm_tpu/io/`` was built to hide (ISSUE 4): the
per-row-group read latency that BENCH_HISTORY showed dominating the overlap
scenarios (``read_s`` 3-6.6 s per window against 0.8-2.6 s of decode). A
synthetic parquet dataset is scanned sequentially through a latency-injecting
filesystem proxy — every ``read()`` call against the file pays a configurable
round-trip delay, emulating an object store from a local disk — and each
scenario toggles one feature:

==================  ==========================================================
scenario            io_options
==================  ==========================================================
sync                readahead off (the pre-ISSUE-4 blocking read path)
readahead           next-K prefetch on the IO thread pool, no coalescing
readahead+coalesce  prefetch + adjacent row groups merged into ranged reads
memcache-warm       readahead+coalesce + in-memory LRU, second epoch measured
==================  ==========================================================

The score is payload MB/s through the reader (single sequential consumer, dummy
pool: the overlap comes from the IO threads, not from more workers — the same
per-worker overlap the real pools get). ``--check`` asserts every scenario
delivers byte-identical tables to the synchronous path; ``--smoke`` is the CI
preset (tiny dataset, identity assertions, no throughput claims — shared CI
cores). A perf run wants real latency (``--latency-ms 5`` ≈ same-region object
store) — at 0 latency every scenario measures parse/decode and converges.

Run as ``petastorm-tpu-bench io`` (or ``python -m petastorm_tpu.benchmark.cli io``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

SCENARIOS = ("sync", "readahead", "readahead+coalesce", "memcache-warm")

#: io_options per scenario (memcache budget filled in at run time)
_SCENARIO_OPTS = {
    "sync": {"readahead": False, "work_stealing": False},
    "readahead": {"readahead": True, "coalesce": False},
    "readahead+coalesce": {"readahead": True, "coalesce": True},
    "memcache-warm": {"readahead": True, "coalesce": True},
}


# the latency-injection filesystem moved to a shared module (ISSUE 8
# satellite) so the remote bench's CloudLatencyFS extends one copy; the
# import keeps this module's historical `benchmark.io.LatencyFS` name alive
from petastorm_tpu.io.latencyfs import LatencyFS  # noqa: E402,F401


def make_dataset(root, rows, row_bytes, rows_per_group, files=2):
    """Synthetic parquet store: an int64 id plus a ``row_bytes`` binary payload
    per row (deterministic fill — identity checks compare exact bytes),
    ``rows_per_group`` rows per row group, split over ``files`` files."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    per_file = max(rows_per_group, rows // files)
    written = 0
    index = 0
    while written < rows:
        n = min(per_file, rows - written)
        ids = np.arange(written, written + n, dtype=np.int64)
        payload = [bytes([i % 251]) * row_bytes for i in ids]
        pq.write_table(
            pa.table({"id": ids, "payload": payload}),
            os.path.join(root, "part-%05d.parquet" % index),
            row_group_size=rows_per_group)
        written += n
        index += 1
    return root


def _drain(reader, collect):
    """Consume every batch; returns (rows, payload_bytes, [per-batch records])."""
    rows = 0
    payload_bytes = 0
    records = []
    for batch in reader:
        ids = np.asarray(batch.id)
        rows += len(ids)
        sizes = [len(p) for p in batch.payload]
        payload_bytes += sum(sizes)
        if collect:
            import zlib

            crc = 0
            for p in batch.payload:
                crc = zlib.crc32(p, crc)
            records.append((ids.tolist(), sizes, crc))
    return rows, payload_bytes, records


def _measure_one(scenario, root, latency_s, depth, io_threads, memcache_mb,
                 check):
    from petastorm_tpu.reader import make_batch_reader

    import pyarrow.fs as pafs

    opts = dict(_SCENARIO_OPTS[scenario])
    opts["readahead_depth"] = depth
    opts["io_threads"] = io_threads
    warm = scenario == "memcache-warm"
    if warm:
        opts["memcache_bytes"] = memcache_mb << 20
    fs = LatencyFS(pafs.LocalFileSystem(), latency_s)
    num_epochs = 2 if warm else 1
    with make_batch_reader("file://" + root, filesystem=fs,
                           reader_pool_type="dummy", shuffle_row_groups=False,
                           num_epochs=num_epochs, io_options=opts) as reader:
        if warm:
            # cold epoch fills the memcache; only the warm epoch is timed
            t0 = time.perf_counter()
            cold_rows, _, _ = _drain_epoch_rows(reader)
            t_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        rows, payload_bytes, records = _drain(reader, collect=check)
        elapsed = time.perf_counter() - t0
        io_stats = reader.io_stats()
    row = {
        "scenario": scenario,
        "rows": rows,
        "payload_mb": round(payload_bytes / 1e6, 3),
        "seconds": round(elapsed, 4),
        "mb_s": round(payload_bytes / 1e6 / elapsed, 1) if elapsed > 0 else None,
        "read_calls": fs.read_calls[0],
        "readahead_hits": io_stats.get("readahead_hits", 0),
        "coalesced_reads": io_stats.get("coalesced_reads", 0),
        "coalesced_items": io_stats.get("coalesced_items", 0),
        "memcache_hits": io_stats.get("memcache_hits", 0),
    }
    if warm:
        row["cold_epoch_seconds"] = round(t_cold, 4)
    return row, records


def _drain_epoch_rows(reader):
    """Consume exactly one epoch's worth of rows (the plan repeats the same item
    count per epoch, so counting rows is exact for an unfiltered scan)."""
    target = None
    rows = 0
    batches = 0
    for batch in reader:
        ids = np.asarray(batch.id)
        rows += len(ids)
        batches += 1
        if target is None:
            target = reader._num_items  # row groups per epoch
        if batches >= target:
            break
    return rows, batches, target


def run_io_bench(rows=2048, row_bytes=16384, rows_per_group=64, files=2,
                 latency_ms=5.0, depth=4, io_threads=2, memcache_mb=512,
                 scenarios=SCENARIOS, check=False, root=None):
    """One result row per scenario; with ``check`` every scenario's delivered
    batches (ids, payload sizes, payload CRC) must be byte-identical to the
    synchronous path's."""
    tmp = None
    if root is None:
        tmp = tempfile.TemporaryDirectory(prefix="ptpu-io-bench-")
        root = tmp.name
    try:
        make_dataset(root, rows, row_bytes, rows_per_group, files=files)
        results = []
        baseline_records = None
        for scenario in scenarios:
            row, records = _measure_one(scenario, root, latency_ms / 1e3, depth,
                                        io_threads, memcache_mb, check)
            if check:
                if baseline_records is None:
                    if scenario != "sync":
                        raise ValueError("--check needs the 'sync' scenario "
                                         "first as the identity baseline")
                    baseline_records = records
                elif scenario != "memcache-warm":
                    # warm scenario drains 2 epochs; identity is asserted on the
                    # single-epoch scenarios where batch order is deterministic
                    if records != baseline_records:
                        raise AssertionError(
                            "scenario %r delivered different tables than the "
                            "synchronous path" % scenario)
                    row["identical_to_sync"] = True
            results.append(row)
        return results
    finally:
        from petastorm_tpu.io.memcache import shared_store

        # the memcache-warm scenario fills the PROCESS-WIDE store; a
        # programmatic caller (tests, a long-lived process) must not keep
        # paying those bytes after the bench returns
        shared_store().clear()
        if tmp is not None:
            tmp.cleanup()


def _format_table(rows):
    cols = ("scenario", "rows", "payload_mb", "seconds", "mb_s", "read_calls",
            "readahead_hits", "coalesced_reads", "memcache_hits")
    present = [c for c in cols if any(c in r for r in rows)]
    widths = [max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in present]
    lines = ["  ".join(c.ljust(w) for c, w in zip(present, widths))]
    for r in rows:
        lines.append("  ".join(str(r.get(c, "")).ljust(w)
                               for c, w in zip(present, widths)))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench io", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--rows", type=int, default=2048)
    parser.add_argument("--row-bytes", type=int, default=16384,
                        help="binary payload bytes per row (default 16 KB)")
    parser.add_argument("--rows-per-group", type=int, default=64)
    parser.add_argument("--files", type=int, default=2)
    parser.add_argument("--latency-ms", type=float, default=5.0,
                        help="injected delay per file read call (object-store "
                             "round-trip emulation; 0 = bare local disk)")
    parser.add_argument("--depth", type=int, default=4,
                        help="readahead depth (row groups in flight)")
    parser.add_argument("--io-threads", type=int, default=2)
    parser.add_argument("--memcache-mb", type=int, default=512)
    parser.add_argument("--scenarios", nargs="*", default=list(SCENARIOS),
                        choices=SCENARIOS)
    parser.add_argument("--check", action="store_true",
                        help="assert readahead/coalesce deliver byte-identical "
                             "tables to the synchronous path")
    parser.add_argument("--json", action="store_true", help="JSON lines output")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, low latency, --check, "
                             "correctness-only (no throughput claims)")
    args = parser.parse_args(argv)

    if args.smoke:
        kwargs = dict(rows=256, row_bytes=2048, rows_per_group=16, files=2,
                      latency_ms=1.0, depth=4, io_threads=2, memcache_mb=64,
                      scenarios=SCENARIOS, check=True)
    else:
        kwargs = dict(rows=args.rows, row_bytes=args.row_bytes,
                      rows_per_group=args.rows_per_group, files=args.files,
                      latency_ms=args.latency_ms, depth=args.depth,
                      io_threads=args.io_threads, memcache_mb=args.memcache_mb,
                      scenarios=tuple(args.scenarios), check=args.check)

    results = run_io_bench(**kwargs)
    if args.json:
        for r in results:
            print(json.dumps(r))
    else:
        print(_format_table(results))
    by_name = {r["scenario"]: r for r in results}
    sync = by_name.get("sync")
    best = by_name.get("readahead+coalesce") or by_name.get("readahead")
    if sync and best and sync.get("mb_s") and best.get("mb_s"):
        print("readahead%s speedup over cold synchronous: %.2fx"
              % ("+coalesce" if "coalesce" in best["scenario"] else "",
                 best["mb_s"] / sync["mb_s"]))
    if kwargs["check"]:
        print("identity: all checked scenarios delivered byte-identical tables")
    return 0


if __name__ == "__main__":
    sys.exit(main())
