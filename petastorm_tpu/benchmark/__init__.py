"""benchmark subpackage."""
