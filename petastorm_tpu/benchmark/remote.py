"""Remote read-path benchmark: the object-store tier under a cloud simulator.

Measures what :mod:`petastorm_tpu.io.remote` was built for (ISSUE 8), with no
credentials and no network: every scenario scans a synthetic multi-file
parquet store through :class:`petastorm_tpu.io.latencyfs.CloudLatencyFS`
(same-region profile — ~5 ms request floor, ~1 s/GB streaming, seeded
lognormal jitter and tail spikes) and asserts on the simulator's per-request
ledger, so the claims are GET counts and wall latencies, not vibes:

==============  ==========================================================
scenario        configuration
==============  ==========================================================
cold            remote tier on, footer cache OFF — every row-group read
                re-fetches the file footer (the metadata-plane round trips
                the cache exists to collapse)
footer-cached   footer cache ON — footers are fetched once per file per
                process, row-group reads issue data GETs only
unhedged-tail   seeded tail spikes injected, hedging OFF — epoch-2 p99
                batch latency eats the spikes
hedged-tail     hedging ON — a GET pending past the learned latency
                quantile gets a duplicate; first responder wins, the p99
                collapses toward the deadline (``hedge_wins > 0``)
tiered          memcache + footer cache + hedging (the production combo):
                epoch 2 serves from the mem tier — the warm epoch must beat
                the cold one ≥2×
==============  ==========================================================

``--check`` asserts every scenario's delivered batches are byte-identical
(ids, payload sizes, payload CRCs) to a plain local read, and that the run
leaked zero leases (hedge losers drain clean). ``--smoke`` is the CI preset:
tiny dataset, every assertion on, no throughput claims.

Run as ``petastorm-tpu-bench remote``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import zlib

import numpy as np

from petastorm_tpu.benchmark.io import make_dataset

SCENARIOS = ("cold", "footer-cached", "unhedged-tail", "hedged-tail", "tiered")

#: same-region object-store profile (the BASELINE.json GCS shape)
PROFILE = dict(base_latency_s=0.005, per_byte_s=1.0 / (1 << 30),
               jitter_sigma=0.1)

_TAIL = dict(tail_fraction=0.06, tail_multiplier=10.0)
_NO_TAIL = dict(tail_fraction=0.0, tail_multiplier=1.0)


def _scenario_config(scenario, memcache_mb, hedge_min_samples):
    """(fs kwargs, io_options dict, num_epochs) per scenario."""
    remote = dict(enabled=True, hedge=False, footer_cache_bytes=0,
                  hedge_min_samples=hedge_min_samples, hedge_quantile=0.9,
                  hedge_min_s=0.001)
    io_opts = dict(readahead=False, work_stealing=False, remote=remote)
    fs_kwargs = dict(PROFILE, **_NO_TAIL)
    epochs = 1
    if scenario == "cold":
        pass
    elif scenario == "footer-cached":
        remote["footer_cache_bytes"] = 64 << 20
    elif scenario == "unhedged-tail":
        remote["footer_cache_bytes"] = 64 << 20
        fs_kwargs.update(_TAIL)
        epochs = 2
    elif scenario == "hedged-tail":
        remote["footer_cache_bytes"] = 64 << 20
        remote["hedge"] = True
        fs_kwargs.update(_TAIL)
        epochs = 2
    elif scenario == "tiered":
        remote["footer_cache_bytes"] = 64 << 20
        remote["hedge"] = True
        io_opts["readahead"] = True
        io_opts["memcache_bytes"] = memcache_mb << 20
        epochs = 2
    else:
        raise ValueError(scenario)
    return fs_kwargs, io_opts, epochs


def _reset_process_state():
    """Scenario isolation: the footer cache, memcache store and latency model
    are process-wide by design — a bench comparing with/without must clear
    them between scenarios."""
    from petastorm_tpu.io.footercache import shared_footer_cache
    from petastorm_tpu.io.memcache import shared_store
    from petastorm_tpu.io.remote import shared_latency_model

    shared_footer_cache().clear()
    shared_store().clear()
    shared_latency_model().reset()


def _drain_epochs(reader, num_epochs, collect):
    """Consume ``num_epochs`` epochs; per epoch returns (seconds, [per-batch
    wall latencies], [identity records])."""
    per_epoch = reader._num_items  # row groups per epoch (unfiltered scan)
    out = []
    t_epoch = t_prev = time.perf_counter()
    lat, records, batches = [], [], 0
    for batch in reader:
        now = time.perf_counter()
        lat.append(now - t_prev)
        t_prev = now
        if collect:
            ids = np.asarray(batch.id)
            sizes = [len(p) for p in batch.payload]
            crc = 0
            for p in batch.payload:
                crc = zlib.crc32(p, crc)
            records.append((ids.tolist(), sizes, crc))
        batches += 1
        if batches == per_epoch:
            out.append((time.perf_counter() - t_epoch, lat, records))
            t_epoch = t_prev = time.perf_counter()
            lat, records, batches = [], [], 0
    if batches:
        out.append((time.perf_counter() - t_epoch, lat, records))
    while len(out) < num_epochs:
        out.append((0.0, [], []))
    return out


def _footer_windows(file_sizes):
    """Each file's EXACT footer length (thrift + trailer) from its last 8
    bytes — so tail data GETs on small files never count as metadata GETs."""
    out = {}
    for path, size in file_sizes.items():
        with open(path, "rb") as f:
            f.seek(size - 8)
            out[path] = int.from_bytes(f.read(4), "little") + 8
    return out


def _p99(latencies):
    if not latencies:
        return 0.0
    ordered = sorted(latencies)
    return ordered[min(len(ordered) - 1, int(0.99 * (len(ordered) - 1)))]


def _leaked_leases():
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().snapshot().get("ptpu_lease_leaked_total", 0)


def _measure_one(scenario, root, file_sizes, footer_windows, seed, memcache_mb,
                 hedge_min_samples, check):
    import pyarrow.fs as pafs

    from petastorm_tpu.io.latencyfs import CloudLatencyFS
    from petastorm_tpu.reader import make_batch_reader

    _reset_process_state()
    fs_kwargs, io_opts, epochs = _scenario_config(scenario, memcache_mb,
                                                  hedge_min_samples)
    fs = CloudLatencyFS(pafs.LocalFileSystem(), seed=seed, **fs_kwargs)
    with make_batch_reader("file://" + root, filesystem=fs,
                           reader_pool_type="dummy", shuffle_row_groups=False,
                           num_epochs=epochs, io_options=io_opts) as reader:
        # measure the READ PATH: construction (file listing, schema inference,
        # the planner's one footer scan per file) is identical across
        # scenarios and is dropped from the ledger here — the footer-cache
        # claim is about the scan-time re-reads N workers issue, and the
        # dummy pool reads nothing until the drain below starts
        fs.reset_accounting()
        t0 = time.perf_counter()
        epoch_results = _drain_epochs(reader, epochs, collect=check)
        elapsed = time.perf_counter() - t0
        io_stats = reader.io_stats()
    footer_gets = len(fs.footer_requests(file_sizes, footer_windows))
    last_seconds, last_lat, _ = epoch_results[-1]
    row = {
        "scenario": scenario,
        "epochs": epochs,
        "seconds": round(elapsed, 4),
        "gets": fs.request_count(),
        "footer_gets": footer_gets,
        "epoch_seconds": [round(e[0], 4) for e in epoch_results],
        "last_epoch_p99_ms": round(_p99(last_lat) * 1e3, 2),
        "hedges": io_stats.get("remote_hedges", 0),
        "hedge_wins": io_stats.get("remote_hedge_wins", 0),
        "sparse_fallbacks": io_stats.get("remote_sparse_fallbacks", 0),
        "tier_mem_hits": io_stats.get("tier_mem_hits", 0),
        "footer_cache_misses": io_stats.get("footer_cache_misses", 0),
    }
    records = [e[2] for e in epoch_results]
    return row, records


def _local_baseline(root, check):
    """The identity baseline: a plain local read with the remote tier off."""
    from petastorm_tpu.reader import make_batch_reader

    with make_batch_reader("file://" + root, reader_pool_type="dummy",
                           shuffle_row_groups=False, num_epochs=1,
                           io_options=dict(readahead=False,
                                           remote=dict(enabled=False))) as reader:
        return _drain_epochs(reader, 1, collect=check)[0][2]


def run_remote_bench(files=4, rows_per_group=32, row_bytes=2048,
                     groups_per_file=8, seed=7, memcache_mb=256,
                     hedge_min_samples=8, scenarios=SCENARIOS, check=False,
                     smoke=False, workers_hint=4, root=None):
    """One result row per scenario, plus the cross-scenario assertions.

    ``workers_hint`` is the N in the footer-cache acceptance bar (metadata
    GETs cut ≥ N×): the per-thread ``ParquetFile`` footer re-reads this
    replaces scale with the worker count, so the cache must beat at least
    that."""
    tmp = None
    if root is None:
        tmp = tempfile.TemporaryDirectory(prefix="ptpu-remote-bench-")
        root = tmp.name
    try:
        rows = files * rows_per_group * groups_per_file
        make_dataset(root, rows, row_bytes, rows_per_group, files=files)
        file_sizes = {
            os.path.join(root, name): os.path.getsize(os.path.join(root, name))
            for name in os.listdir(root) if name.endswith(".parquet")}
        footer_windows = _footer_windows(file_sizes)
        leaked_before = _leaked_leases()
        baseline = _local_baseline(root, check) if check else None
        results = {}
        all_records = {}
        for scenario in scenarios:
            row, records = _measure_one(scenario, root, file_sizes,
                                        footer_windows, seed, memcache_mb,
                                        hedge_min_samples, check)
            results[scenario] = row
            all_records[scenario] = records
            if check:
                for i, epoch_records in enumerate(records):
                    if not epoch_records:
                        continue
                    if epoch_records != baseline:
                        raise AssertionError(
                            "scenario %r epoch %d delivered different batches "
                            "than the plain local read" % (scenario, i))
                row["identical_to_local"] = True
        checks = _assert_scenarios(results, scenarios, workers_hint,
                                   smoke=smoke)
        leaked = _leaked_leases() - leaked_before
        if check and leaked:
            raise AssertionError("%d lease(s) leaked during the bench (hedge "
                                 "losers must drain clean)" % leaked)
        checks["leaked_leases"] = leaked
        return list(results.values()), checks
    finally:
        _reset_process_state()
        if tmp is not None:
            tmp.cleanup()


def _assert_scenarios(results, scenarios, workers_hint, smoke):
    """The acceptance bars, computed (and, under --smoke, enforced)."""
    checks = {}
    cold = results.get("cold")
    cached = results.get("footer-cached")
    if cold and cached:
        ratio = cold["footer_gets"] / max(1, cached["footer_gets"])
        checks["footer_get_cut"] = round(ratio, 2)
        if smoke and ratio < workers_hint:
            raise AssertionError(
                "footer cache cut metadata GETs only %.1fx (%d -> %d); "
                "acceptance bar is >= %dx" % (ratio, cold["footer_gets"],
                                              cached["footer_gets"],
                                              workers_hint))
        if smoke and not cold["gets"] > cached["gets"]:
            raise AssertionError(
                "footer cache did not reduce total GET round trips "
                "(%d vs %d)" % (cold["gets"], cached["gets"]))
    unhedged = results.get("unhedged-tail")
    hedged = results.get("hedged-tail")
    if unhedged and hedged:
        checks["p99_unhedged_ms"] = unhedged["last_epoch_p99_ms"]
        checks["p99_hedged_ms"] = hedged["last_epoch_p99_ms"]
        checks["hedges"] = hedged["hedges"]
        checks["hedge_wins"] = hedged["hedge_wins"]
        if smoke:
            if hedged["hedges"] < 1 or hedged["hedge_wins"] < 1:
                raise AssertionError(
                    "hedging never fired/won under injected tail (hedges=%d, "
                    "wins=%d)" % (hedged["hedges"], hedged["hedge_wins"]))
            if not hedged["last_epoch_p99_ms"] < unhedged["last_epoch_p99_ms"]:
                raise AssertionError(
                    "hedged p99 batch latency (%.2f ms) did not beat unhedged "
                    "(%.2f ms) under injected tail"
                    % (hedged["last_epoch_p99_ms"],
                       unhedged["last_epoch_p99_ms"]))
    tiered = results.get("tiered")
    if tiered and len(tiered["epoch_seconds"]) >= 2:
        cold_s, warm_s = tiered["epoch_seconds"][0], tiered["epoch_seconds"][1]
        speedup = cold_s / warm_s if warm_s > 0 else float("inf")
        checks["tiered_warm_speedup"] = round(speedup, 2)
        if smoke:
            if tiered["tier_mem_hits"] < 1:
                raise AssertionError("tiered warm epoch never hit the mem tier")
            if speedup < 2.0:
                raise AssertionError(
                    "tiered warm epoch only %.2fx over cold (bar: >= 2x; "
                    "cold=%.3fs warm=%.3fs)" % (speedup, cold_s, warm_s))
    return checks


def _format_table(rows):
    cols = ("scenario", "epochs", "seconds", "gets", "footer_gets",
            "last_epoch_p99_ms", "hedges", "hedge_wins", "tier_mem_hits",
            "sparse_fallbacks")
    widths = [max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(str(r.get(c, "")).ljust(w)
                               for c, w in zip(cols, widths)))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench remote", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--files", type=int, default=4)
    parser.add_argument("--rows-per-group", type=int, default=32)
    parser.add_argument("--row-bytes", type=int, default=2048)
    parser.add_argument("--groups-per-file", type=int, default=8)
    parser.add_argument("--seed", type=int, default=7,
                        help="cloud simulator seed (jitter + tail spikes)")
    parser.add_argument("--memcache-mb", type=int, default=256)
    parser.add_argument("--workers-hint", type=int, default=4,
                        help="N in the footer-cache acceptance bar (metadata "
                             "GETs cut >= N x)")
    parser.add_argument("--scenarios", nargs="*", default=list(SCENARIOS),
                        choices=SCENARIOS)
    parser.add_argument("--check", action="store_true",
                        help="assert byte-identity vs a plain local read and "
                             "zero leaked leases")
    parser.add_argument("--json", action="store_true", help="JSON lines output")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, --check, and every "
                             "acceptance assertion enforced (footer-GET cut, "
                             "hedges fire and win, tiered warm >= 2x cold)")
    args = parser.parse_args(argv)

    if args.smoke:
        kwargs = dict(files=3, rows_per_group=16, row_bytes=1024,
                      groups_per_file=8, seed=args.seed, memcache_mb=64,
                      hedge_min_samples=8, scenarios=SCENARIOS, check=True,
                      smoke=True, workers_hint=args.workers_hint)
    else:
        kwargs = dict(files=args.files, rows_per_group=args.rows_per_group,
                      row_bytes=args.row_bytes,
                      groups_per_file=args.groups_per_file, seed=args.seed,
                      memcache_mb=args.memcache_mb, hedge_min_samples=8,
                      scenarios=tuple(args.scenarios), check=args.check,
                      smoke=False, workers_hint=args.workers_hint)

    results, checks = run_remote_bench(**kwargs)
    if args.json:
        for r in results:
            print(json.dumps(r))
    else:
        print(_format_table(results))
    if "footer_get_cut" in checks:
        print("footer cache metadata-GET cut: %.1fx" % checks["footer_get_cut"])
    if "p99_hedged_ms" in checks:
        print("tail p99 batch latency: unhedged %.2f ms -> hedged %.2f ms "
              "(%d hedges, %d wins)"
              % (checks["p99_unhedged_ms"], checks["p99_hedged_ms"],
                 checks["hedges"], checks["hedge_wins"]))
    if "tiered_warm_speedup" in checks:
        print("tiered warm epoch speedup over cold: %.2fx"
              % checks["tiered_warm_speedup"])
    if kwargs["check"]:
        print("identity: all scenarios byte-identical to the local read; "
              "leaked leases: %d" % checks.get("leaked_leases", 0))
    print(json.dumps({"remote_summary": checks}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
