"""Shared-memory cache arena acceptance bench: one mapped warm set per host.

Measures exactly what ``petastorm_tpu/io/arena.py`` (ISSUE 17) exists to
deliver: a SECOND process on the same host serving its warm reads out of the
first process's mapped cache arena instead of refilling a private copy. Three
legs over one synthetic parquet store behind the :class:`LatencyFS` read
counter:

==================  ==========================================================
leg                 what runs
==================  ==========================================================
per-process         subprocess with ``PTPU_ARENA=off`` — today's private
                    caches; its per-batch (ids, sizes, crc) records are the
                    byte-identity baseline
arena-warm          this process reads with ``io_options.arena_bytes`` set:
                    creates the host arena and admits every decoded row
                    group + footer blob (the single-process warm set)
arena-attach        a fresh subprocess attaches via ``PTPU_ARENA_ATTACH``
                    (the exact env handoff pool children get) and reads the
                    same store — its DRAIN must be served from the arena
==================  ==========================================================

Asserted invariants (``--smoke`` is the CI preset — tiny store, correctness
only, shared CI cores):

- **byte identity**: both arena legs deliver per-batch records identical to
  the ``PTPU_ARENA=off`` baseline;
- **warm attach, zero store IO**: the attacher's drain issues ZERO
  ``LatencyFS`` read calls and its arena hit ratio is >= 0.9;
- **zero-copy serves**: the attacher's ``arena_admit`` copy-census delta is
  0 — mapping an admitted entry charges nothing, only the original admit
  copied;
- **one warm set**: host-wide arena resident bytes after the attacher leg are
  <= 1.2x the single-process warm set (the attacher added ~nothing);
- **no leftovers**: after ``close()`` nothing named ``ptpu_arena_*`` survives
  in ``/dev/shm``.

Run as ``petastorm-tpu-bench shmcache`` (or
``python -m petastorm_tpu.benchmark.shmcache``).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

from petastorm_tpu.benchmark.io import LatencyFS, _drain, make_dataset


def _reader_opts(arena_mb):
    """One io_options dict for every leg: deterministic sequential scan, the
    arena budget the only variable (the PTPU_ARENA env decides per-process
    vs shared for the subprocess legs)."""
    return {"readahead": False, "work_stealing": False,
            "arena_bytes": arena_mb << 20}


def _run_leg(root, latency_s, arena_mb):
    """Scan the store once through a LatencyFS counter; returns the leg's
    report row (records, drain-phase read calls, arena funnel stats)."""
    import pyarrow.fs as pafs

    from petastorm_tpu.io.lease import copy_census
    from petastorm_tpu.reader import make_batch_reader

    fs = LatencyFS(pafs.LocalFileSystem(), latency_s)
    census_before = copy_census()
    with make_batch_reader("file://" + root, filesystem=fs,
                           reader_pool_type="dummy",
                           shuffle_row_groups=False, num_epochs=1,
                           io_options=_reader_opts(arena_mb)) as reader:
        construct_reads = fs.read_calls[0]
        t0 = time.perf_counter()
        rows, payload_bytes, records = _drain(reader, collect=True)
        elapsed = time.perf_counter() - t0
        io_stats = reader.io_stats()
    census_after = copy_census()
    hits = io_stats.get("arena_hits", 0)
    misses = io_stats.get("arena_misses", 0)
    looked = hits + misses
    return {
        "rows": rows,
        "payload_mb": round(payload_bytes / 1e6, 3),
        "seconds": round(elapsed, 4),
        "construct_read_calls": construct_reads,
        "drain_read_calls": fs.read_calls[0] - construct_reads,
        "arena_hits": hits,
        "arena_misses": misses,
        "arena_hit_ratio": round(hits / looked, 3) if looked else None,
        "arena_payload_bytes": io_stats.get("arena_payload_bytes", 0),
        "arena_admit_census_delta": (census_after.get("arena_admit", 0)
                                     - census_before.get("arena_admit", 0)),
        "records": records,
    }


def _child_main(args):
    """Internal subprocess entry (``--child``): attach the arena named by
    PTPU_ARENA_ATTACH when present (exactly what a pool child's bootstrap
    does), run one leg, print the JSON report on the LAST stdout line."""
    from petastorm_tpu.io import arena as arena_mod

    arena_mod.attach_from_env()
    report = _run_leg(args.root, args.latency_ms / 1e3, args.arena_mb)
    report["attached"] = arena_mod.process_arena() is not None
    arena_mod.close_process_arena()
    print(json.dumps(report))
    return 0


def _spawn_leg(root, latency_ms, arena_mb, env_overrides):
    """Run one leg in a fresh interpreter; returns its parsed JSON report."""
    env = dict(os.environ)
    env.update(env_overrides)
    cmd = [sys.executable, "-m", "petastorm_tpu.benchmark.shmcache",
           "--child", "--root", root, "--latency-ms", str(latency_ms),
           "--arena-mb", str(arena_mb)]
    proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE, check=False)
    out = proc.stdout.decode("utf-8", "replace").strip().splitlines()
    if proc.returncode != 0 or not out:
        raise RuntimeError("shmcache child leg failed (rc=%d)"
                           % proc.returncode)
    return json.loads(out[-1])


def _records_key(records):
    """Normalize per-batch records through a JSON round trip so the in-process
    leg's tuples compare equal to the subprocess legs' parsed lists."""
    return json.loads(json.dumps(records))


def _shm_leftovers():
    try:
        return sorted(n for n in os.listdir("/dev/shm")
                      if n.startswith("ptpu_arena_"))
    except OSError:
        return []  # no /dev/shm on this platform: nothing to leak


def run_shmcache_bench(rows=256, row_bytes=2048, rows_per_group=16, files=2,
                       latency_ms=1.0, arena_mb=64, root=None):
    """The three-leg harness; returns ``(results, failures)`` where every
    acceptance invariant that did not hold appends one message."""
    from petastorm_tpu.io import arena as arena_mod
    from petastorm_tpu.io.memcache import shared_store

    tmp = None
    if root is None:
        tmp = tempfile.TemporaryDirectory(prefix="ptpu-shmcache-bench-")
        root = tmp.name
    results = []
    failures = []
    try:
        make_dataset(root, rows, row_bytes, rows_per_group, files=files)

        # leg 1 — per-process baseline: a fresh interpreter with the arena
        # kill switch set; its records are the byte-identity reference
        base = _spawn_leg(root, latency_ms, arena_mb, {"PTPU_ARENA": "off"})
        base["leg"] = "per-process"
        baseline_records = _records_key(base.pop("records"))
        results.append(base)

        # leg 2 — arena warm: THIS process creates the host arena and fills
        # the one warm set (decoded row groups + footer blobs)
        warm = _run_leg(root, latency_ms / 1e3, arena_mb)
        warm["leg"] = "arena-warm"
        warm_records = _records_key(warm.pop("records"))
        results.append(warm)
        warm_set_bytes = warm["arena_payload_bytes"]
        token = arena_mod.current_token()
        if token is None:
            failures.append("arena-warm leg did not create a host arena "
                            "(shm unavailable?)")
            return results, failures
        if warm_records != baseline_records:
            failures.append("arena-warm leg delivered different batches than "
                            "the PTPU_ARENA=off baseline")

        # leg 3 — second attacher: a fresh interpreter joins via the same
        # PTPU_ARENA_ATTACH handoff pool children get and drains warm
        attach = _spawn_leg(root, latency_ms, arena_mb,
                            {arena_mod.ENV_ATTACH: token})
        attach["leg"] = "arena-attach"
        attach_records = _records_key(attach.pop("records"))
        results.append(attach)

        if not attach.get("attached"):
            failures.append("attacher leg failed to attach the arena")
        if attach_records != baseline_records:
            failures.append("attacher leg delivered different batches than "
                            "the PTPU_ARENA=off baseline")
        if attach["drain_read_calls"] != 0:
            failures.append(
                "attacher drain issued %d store read calls (want 0: every "
                "row group served from the arena)"
                % attach["drain_read_calls"])
        ratio = attach.get("arena_hit_ratio")
        if ratio is None or ratio < 0.9:
            failures.append("attacher arena hit ratio %r < 0.9" % (ratio,))
        if attach["arena_admit_census_delta"] != 0:
            failures.append(
                "attacher charged %d arena_admit copy-census bytes (want 0: "
                "serves map, only the original admit copies)"
                % attach["arena_admit_census_delta"])
        if warm_set_bytes and \
                attach["arena_payload_bytes"] > 1.2 * warm_set_bytes:
            failures.append(
                "host-wide arena resident bytes %d > 1.2x the "
                "single-process warm set %d"
                % (attach["arena_payload_bytes"], warm_set_bytes))
        return results, failures
    finally:
        arena_mod.close_process_arena()
        shared_store().clear()
        leftovers = _shm_leftovers()
        if leftovers:
            failures.append("orphaned shm segments after close(): %s"
                            % ", ".join(leftovers))
        if tmp is not None:
            tmp.cleanup()


def _format_table(rows):
    cols = ("leg", "rows", "payload_mb", "seconds", "construct_read_calls",
            "drain_read_calls", "arena_hits", "arena_hit_ratio",
            "arena_payload_bytes")
    present = [c for c in cols if any(c in r for r in rows)]
    widths = [max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in present]
    lines = ["  ".join(c.ljust(w) for c, w in zip(present, widths))]
    for r in rows:
        lines.append("  ".join(str(r.get(c, "")).ljust(w)
                               for c, w in zip(present, widths)))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench shmcache", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--rows", type=int, default=2048)
    parser.add_argument("--row-bytes", type=int, default=16384,
                        help="binary payload bytes per row (default 16 KB)")
    parser.add_argument("--rows-per-group", type=int, default=64)
    parser.add_argument("--files", type=int, default=2)
    parser.add_argument("--latency-ms", type=float, default=5.0,
                        help="injected delay per file read call (object-store "
                             "round-trip emulation; 0 = bare local disk)")
    parser.add_argument("--arena-mb", type=int, default=256)
    parser.add_argument("--json", action="store_true", help="JSON lines output")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, correctness-only "
                             "(no throughput claims)")
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--root", default=None, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.child:
        return _child_main(args)

    if args.smoke:
        kwargs = dict(rows=256, row_bytes=2048, rows_per_group=16, files=2,
                      latency_ms=1.0, arena_mb=64)
    else:
        kwargs = dict(rows=args.rows, row_bytes=args.row_bytes,
                      rows_per_group=args.rows_per_group, files=args.files,
                      latency_ms=args.latency_ms, arena_mb=args.arena_mb)

    results, failures = run_shmcache_bench(**kwargs, root=args.root)
    if args.json:
        for r in results:
            print(json.dumps(r))
    else:
        print(_format_table(results))
    if failures:
        for msg in failures:
            print("FAIL: %s" % msg)
        return 1
    print("shmcache: byte identity held; attacher drained warm from the "
          "arena with zero store reads and zero copy-census bytes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
