"""Chaos acceptance harness (ISSUE 7): scripted fault scenarios against the
real pipeline, asserting the invariant that makes the recovery machinery
production-grade:

    **every planned row is either delivered exactly once or listed in the
    quarantine report — no hangs, no duplicates, no leaked leases or slabs.**

Each scenario arms a deterministic :class:`petastorm_tpu.chaos.FaultPlan`,
runs a full epoch through ``make_batch_reader`` (readahead on; the process
pool runs the shm **view** wire, so slab leases are live under fault), and
checks:

- ``delivered ∪ quarantined == plan`` with the two sets disjoint and the
  delivered ids duplicate-free (quarantined ids are recovered by reading the
  quarantined row groups straight from parquet);
- ``ptpu_lease_leaked_total`` moved by exactly 0 during the scenario;
- for the shm wire, the pool's slab ring reports no in-flight slabs after the
  epoch (nothing wedged);
- the ``stall-heal`` scenario additionally requires the watchdog's ``heal``
  escalation to recover a LIVE injected hang without the consumer ever seeing
  :class:`~petastorm_tpu.errors.StallError`, while the respawn budget lasts.

Scenarios: ``transient-io`` (seeded transient read errors on the sync AND
readahead paths, absorbed by the shared retry budget), ``kills`` (children
SIGKILL-equivalent mid-item — re-dispatch on respawn — plus one poison item
that kills every child it meets and must be quarantined), ``poison`` (an item
that deterministically raises in the worker), ``corrupt`` (a flipped byte in
a wire payload — absorbed by re-dispatch, never delivered corrupt),
``stall-heal`` (an injected in-child hang healed in place), and
``mutating-dataset`` (ISSUE 11: seeded ``append_piece``/``remove_file``/
``rewrite_file`` actions fired at the watcher's ``dataset.mutate`` hook while
an epoch runs on dummy, thread AND process pools — asserting delivered ∪
quarantined == final plan, disjoint and duplicate-free, no batch mixing two
generations of one file, zero leaked leases; plus a ``num_epochs=None`` run
that must observe an appended piece through the live watch thread).

``--smoke`` is the CI preset: tiny dataset, every scenario on BOTH the thread
and process pools (where the fault applies to that pool), hard asserts on the
invariant. The full mode grows the dataset and prints per-scenario timings.

Run as ``petastorm-tpu-bench chaos`` (or ``python -m
petastorm_tpu.benchmark.cli chaos``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def _write_dataset(root, files, rows_per_file):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    for i in range(files):
        base = i * rows_per_file
        table = pa.table({
            "id": np.arange(base, base + rows_per_file, dtype=np.int64),
            "x": rng.random(rows_per_file),
        })
        # one row group per file: plan ordinals map 1:1 to files, so scenario
        # item_keys ("ordinal=3") pin faults to a known set of ids
        pq.write_table(table, os.path.join(root, "part_%03d.parquet" % i),
                       row_group_size=rows_per_file)


def _quarantined_ids(report):
    """Recover the rows the quarantine skipped by reading the quarantined row
    groups straight from parquet — the ground truth the invariant diffs."""
    import pyarrow.parquet as pq

    ids = []
    for entry in report:
        pf = pq.ParquetFile(entry.path)
        ids.extend(pf.read_row_group(entry.row_group, columns=["id"])
                   .column("id").to_pylist())
    return ids


def _leaked_total():
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().counter("ptpu_lease_leaked_total").value


def _run_scenario(name, root, expected_ids, pool, plan, recovery=None,
                  wire=None, health=None, workers=2, timeout_s=180.0,
                  transport=None):
    """One epoch under an armed plan; returns the scenario result dict and
    raises AssertionError the moment the invariant breaks."""
    import gc

    from petastorm_tpu import chaos
    from petastorm_tpu.reader import make_batch_reader

    gc.collect()  # settle any straggler leases from a previous scenario
    leaked_before = _leaked_total()
    t0 = time.perf_counter()
    stall_error = None
    monitor = None
    with chaos.armed(plan):
        reader = make_batch_reader(
            "file://" + root, num_epochs=1, shuffle_row_groups=False,
            reader_pool_type=pool, workers_count=workers,
            results_timeout_s=timeout_s, wire_serializer=wire,
            recovery=recovery, transport=transport)
        delivered = []
        wire_stats = {}
        try:
            if health is not None:
                from petastorm_tpu.obs.health import HealthMonitor

                monitor = HealthMonitor(health)
                reader.set_health(monitor)
                monitor.start()
            try:
                for batch in reader:
                    delivered.extend(int(v) for v in np.asarray(batch.id))
            except Exception as e:  # noqa: BLE001 — classified below
                from petastorm_tpu.errors import StallError

                if isinstance(e, StallError):
                    stall_error = e
                else:
                    raise
            report = reader.quarantine_report
            wire_stats = reader.wire_stats()
        finally:
            reader.stop()
            reader.join()
            if monitor is not None:
                monitor.stop()
    duration = time.perf_counter() - t0
    gc.collect()  # any lease dropped without release would count as a leak now
    leak_delta = _leaked_total() - leaked_before

    quarantined = _quarantined_ids(report)
    result = {
        "scenario": name, "pool": pool, "wire": wire or "default",
        "transport": transport or "pipe",
        "delivered": len(delivered), "quarantined_items": len(report),
        "quarantined_rows": len(quarantined),
        "injected": plan.stats()["injected_total"],
        "lease_leak_delta": leak_delta, "seconds": round(duration, 3),
        "heals": monitor.heal_count if monitor is not None else 0,
    }
    # -- the invariant ------------------------------------------------------------------
    assert stall_error is None, \
        "%s: consumer saw %r despite the heal tier" % (name, stall_error)
    assert len(delivered) == len(set(delivered)), \
        "%s: duplicate rows delivered" % name
    assert not (set(delivered) & set(quarantined)), \
        "%s: rows both delivered AND quarantined" % name
    assert sorted(delivered + quarantined) == expected_ids, \
        "%s: delivered ∪ quarantined != plan (%d + %d vs %d)" \
        % (name, len(delivered), len(quarantined), len(expected_ids))
    assert leak_delta == 0, \
        "%s: ptpu_lease_leaked_total moved by %d" % (name, leak_delta)
    in_flight = wire_stats.get("shm_slabs_in_flight")
    assert not in_flight, \
        "%s: %s slabs still in flight after the epoch" % (name, in_flight)
    return result


def _scenarios(files, smoke):
    """(name, pools, plan factory, recovery, needs_health) — plan factories
    build a FRESH plan per run (hit ledgers are stateful)."""
    from petastorm_tpu.chaos import FaultPlan, FaultRule
    from petastorm_tpu.recovery import RecoveryOptions

    mid = "ordinal=%d" % (files // 2)
    quarantine = RecoveryOptions(on_poison="quarantine", poison_attempts=2,
                                 worker_respawns=4 * files,
                                 io_retry_backoff_s=0.01)
    return [
        ("transient-io", ("thread", "process"), lambda: FaultPlan([
            # every 3rd read attempt resets: absorbed by the shared retry
            # budget (a retry attempt hits the site again, so the budget is
            # genuinely spent). The readahead site gets LATENCY, not a raise:
            # a background-read failure re-raises at get() with no extra
            # retries by contract (PR 4; pinned in test_io_retry), so a raise
            # there is a poison-policy scenario, not a transient one.
            FaultRule("reader.read", "raise_transient", every=3),
            FaultRule("io.readahead", "latency", every=2, latency_s=0.02),
        ], seed=7), RecoveryOptions(io_retries=3, io_retry_backoff_s=0.01),
            None),
        ("poison", ("thread", "process"), lambda: FaultPlan([
            FaultRule("worker.item", "raise_permanent", item_key=mid),
            FaultRule("child.item", "raise_permanent", item_key=mid),
        ], seed=7), quarantine, None),
        ("kills", ("process",), lambda: FaultPlan([
            # every child (original or respawned) dies at its 2nd item: pure
            # respawn-and-re-dispatch traffic ...
            FaultRule("child.item", "kill", nth=2, times=1),
            # ... plus one poison item that kills EVERY child it meets and
            # must end up quarantined (uncharged respawns)
            FaultRule("child.item", "kill", item_key=mid),
        ], seed=7), quarantine, None),
        ("corrupt", ("process",), lambda: FaultPlan([
            FaultRule("wire.decode", "corrupt", nth=2, times=1),
        ], seed=7), quarantine, None),
        ("stall-heal", ("process",), lambda: FaultPlan([
            # every child (original AND respawned) hangs once, at its 2nd
            # item: the heal tier must keep killing/respawning until the plan
            # drains — budget scaled to the plan so heal, not StallError, is
            # what carries the epoch
            FaultRule("child.item", "hang", nth=2, times=1,
                      hang_s=60.0),
        ], seed=7), RecoveryOptions(worker_respawns=4 * files), "heal"),
    ]


# -- network scenario (ISSUE 15) ---------------------------------------------------------


def _run_transport_identity(root, expected_count):
    """Clean-run twin check: the default pipe pool and the tcp pool must
    deliver BYTE-IDENTICAL payloads (per-id crc over the float column) — the
    framed transport is a wire, not a transform."""
    import zlib

    from petastorm_tpu.reader import make_batch_reader

    def run(transport):
        reader = make_batch_reader(
            "file://" + root, num_epochs=1, shuffle_row_groups=False,
            reader_pool_type="process", workers_count=2, transport=transport)
        out = {}
        try:
            for batch in reader:
                for i, x in zip(np.asarray(batch.id), np.asarray(batch.x)):
                    out[int(i)] = zlib.crc32(np.float64(x).tobytes())
        finally:
            reader.stop()
            reader.join()
        return out

    pipe, tcp = run(None), run("tcp")
    assert len(pipe) == expected_count, len(pipe)
    assert pipe == tcp, \
        "transport identity: pipe vs tcp delivered payloads differ"
    return len(pipe)


def _run_network(root, expected_ids, timeout_s=180.0):
    """Seeded partition/reset/slow/corrupt-frame injection on a loopback
    ``TcpTransport`` pool. ``worker_respawns=0`` makes the assertion sharp:
    every injected link fault must be absorbed by RECONNECT + ledgered
    re-dispatch alone (a reconnect slower than the configured ceiling would
    surface as ``WorkerDiedError`` and fail the scenario), and no plan item
    may quarantine — link faults re-dispatch, they do not poison."""
    from petastorm_tpu.chaos import FaultPlan, FaultRule
    from petastorm_tpu.obs.metrics import default_registry
    from petastorm_tpu.recovery import RecoveryOptions

    recovery = RecoveryOptions(
        on_poison="quarantine", poison_attempts=10, worker_respawns=0,
        io_retry_backoff_s=0.01, link_heartbeat_s=0.2, link_miss_threshold=3,
        link_reconnect_s=8.0, link_connect_timeout_s=5.0)
    plan = FaultPlan([
        FaultRule("transport.send", "net.slow", every=7, latency_s=0.005),
        FaultRule("transport.send", "net.reset", nth=5, times=1),
        FaultRule("transport.send", "net.corrupt_frame", nth=11, times=1),
        # the drop window (latency_s) sits ABOVE the half-open threshold
        # (miss_threshold x heartbeat = 0.6s): detection — and therefore
        # teardown + re-dispatch — is guaranteed, not probabilistic
        FaultRule("transport.send", "net.partition", nth=17, times=1,
                  latency_s=1.0),
    ], seed=7)
    reconnects = default_registry().counter("ptpu_net_reconnects_total")
    before = reconnects.value
    result = _run_scenario("network", root, expected_ids, "process", plan,
                           recovery=recovery, transport="tcp",
                           timeout_s=timeout_s)
    delta = reconnects.value - before
    assert delta >= 1, \
        "network: no transport reconnect observed (delta=%d)" % delta
    assert result["quarantined_items"] == 0, \
        "network: link faults must re-dispatch, not quarantine (%d items)" \
        % result["quarantined_items"]
    result["reconnects"] = delta
    return result


# -- mutating-dataset scenario (ISSUE 11) ------------------------------------------------

#: the id range rewritten generations start at — far above any planned id, so
#: "a new-generation row leaked into the epoch" is one integer comparison
_REWRITE_BASE = 10_000_000


def _expected_ids_for_entry(entry, rows_per_file, files):
    """A quarantined entry's planned ids by NAME CONVENTION (the file may be
    removed or rewritten — reading it back is impossible or wrong)."""
    name = os.path.basename(entry.path)
    if name.startswith("part_zz"):
        return list(range(files * rows_per_file,
                          (files + 1) * rows_per_file))
    index = int(name.split("_")[1].split(".")[0])
    return list(range(index * rows_per_file, (index + 1) * rows_per_file))


def _run_mutating_dataset(pool, files, rows, timeout_s=180.0):
    """One epoch under seeded dataset mutations driven through the
    ``dataset.mutate`` chaos hook: append at tick 1, remove + rewrite (of the
    two LAST files, still pending behind the throttled consumer) at tick 2.
    Asserts the exactly-once-or-quarantined invariant over the FINAL plan
    (initial ∪ appended ids), no mixed generations, zero leaked leases."""
    import time as _time

    from petastorm_tpu import chaos
    from petastorm_tpu.chaos import FaultPlan, FaultRule
    from petastorm_tpu.dataset.mutate import LocalDatasetMutator
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.recovery import RecoveryOptions

    root = tempfile.mkdtemp(prefix="ptpu-chaos-mut-")
    try:
        _write_dataset(root, files, rows)
        remove_name = "part_%03d.parquet" % (files - 1)
        rewrite_name = "part_%03d.parquet" % (files - 2)
        plan = FaultPlan([
            FaultRule("dataset.mutate", "append_piece", nth=1, times=1,
                      target={"name": "part_zz0.parquet",
                              "start": files * rows, "rows": rows}),
            FaultRule("dataset.mutate", "remove_file", nth=2, times=1,
                      target={"name": remove_name}),
            FaultRule("dataset.mutate", "rewrite_file", nth=2, times=1,
                      target={"name": rewrite_name, "start": _REWRITE_BASE,
                              "rows": rows}),
        ], seed=11)
        recovery = RecoveryOptions(on_poison="quarantine", poison_attempts=2,
                                   io_retries=1, io_retry_backoff_s=0.01,
                                   worker_respawns=4 * files)
        leaked_before = _leaked_total()
        t0 = time.perf_counter()
        with chaos.armed(plan):
            reader = make_batch_reader(
                "file://" + root, num_epochs=1, shuffle_row_groups=False,
                reader_pool_type=pool, workers_count=2, results_queue_size=2,
                results_timeout_s=timeout_s,
                wire_serializer="shm-view" if pool == "process" else None,
                recovery=recovery, watch={"interval_s": 0.1})
            mutator = LocalDatasetMutator(root)
            reader.dataset_watcher.set_mutator(mutator)
            delivered = []
            wire_stats = {}
            try:
                for batch in reader:
                    delivered.extend(int(v) for v in np.asarray(batch.id))
                    # throttle until the seeded mutations have all fired AND
                    # the watcher has applied the resulting deltas — the
                    # bounded results queue holds the plan open meanwhile, so
                    # the appended piece joins THIS epoch deterministically
                    deadline = _time.monotonic() + 60.0
                    while (plan.stats()["injected_total"] < 3
                           or reader.io_stats().get("watch_deltas", 0) < 1) \
                            and _time.monotonic() < deadline:
                        _time.sleep(0.02)
                report = reader.quarantine_report
                wire_stats = reader.wire_stats()
            finally:
                reader.stop()
                reader.join()
        duration = time.perf_counter() - t0
        import gc

        gc.collect()
        leak_delta = _leaked_total() - leaked_before

        assert plan.stats()["injected_total"] == 3, plan.stats()
        assert len(mutator.applied) == 3, mutator.applied
        quarantined = []
        for entry in report:
            quarantined.extend(_expected_ids_for_entry(entry, rows, files))
        expected = list(range((files + 1) * rows))  # initial ∪ appended
        # -- the invariant (ISSUE 11 flavor) --------------------------------------------
        new_gen = [i for i in delivered if i >= _REWRITE_BASE]
        assert not new_gen, \
            "mutating-dataset(%s): rewritten generation leaked into the " \
            "epoch (%d rows)" % (pool, len(new_gen))
        assert len(delivered) == len(set(delivered)), \
            "mutating-dataset(%s): duplicate rows delivered" % pool
        assert not (set(delivered) & set(quarantined)), \
            "mutating-dataset(%s): rows both delivered AND quarantined" % pool
        assert sorted(delivered + quarantined) == expected, \
            "mutating-dataset(%s): delivered ∪ quarantined != final plan " \
            "(%d + %d vs %d)" % (pool, len(delivered), len(quarantined),
                                 len(expected))
        assert leak_delta == 0, \
            "mutating-dataset(%s): ptpu_lease_leaked_total moved by %d" \
            % (pool, leak_delta)
        in_flight = wire_stats.get("shm_slabs_in_flight")
        assert not in_flight, \
            "mutating-dataset(%s): %s slabs still in flight" % (pool, in_flight)
        return {
            "scenario": "mutating-dataset", "pool": pool,
            "wire": "shm-view" if pool == "process" else "default",
            "delivered": len(delivered), "quarantined_items": len(report),
            "quarantined_rows": len(quarantined), "injected": 3,
            "lease_leak_delta": leak_delta, "seconds": round(duration, 3),
            "heals": 0,
        }
    finally:
        import shutil

        shutil.rmtree(root, ignore_errors=True)


def _run_infinite_watch(files, rows, timeout_s=60.0):
    """num_epochs=None acceptance: the LIVE watch thread (no manual polling)
    must observe a chaos-appended piece and feed it to the consumer within
    the run's deadline (~a handful of 0.1s watch intervals)."""
    import time as _time

    from petastorm_tpu import chaos
    from petastorm_tpu.chaos import FaultPlan, FaultRule
    from petastorm_tpu.dataset.mutate import LocalDatasetMutator
    from petastorm_tpu.reader import make_batch_reader

    root = tempfile.mkdtemp(prefix="ptpu-chaos-watch-")
    try:
        _write_dataset(root, files, rows)
        appended = set(range(files * rows, (files + 1) * rows))
        plan = FaultPlan([
            FaultRule("dataset.mutate", "append_piece", nth=2, times=1,
                      target={"name": "part_zz0.parquet",
                              "start": files * rows, "rows": rows}),
        ], seed=13)
        t0 = time.perf_counter()
        seen = False
        with chaos.armed(plan):
            reader = make_batch_reader(
                "file://" + root, num_epochs=None, shuffle_row_groups=False,
                reader_pool_type="thread", workers_count=2,
                results_queue_size=2, results_timeout_s=timeout_s,
                watch={"interval_s": 0.1})
            reader.dataset_watcher.set_mutator(LocalDatasetMutator(root))
            deadline = _time.monotonic() + timeout_s
            try:
                for batch in reader:
                    if {int(v) for v in np.asarray(batch.id)} & appended:
                        seen = True
                        break
                    if _time.monotonic() > deadline:
                        break
            finally:
                reader.stop()
                reader.join()
        assert seen, \
            "infinite-watch: the appended piece never reached the consumer " \
            "within %.0fs" % timeout_s
        return {"scenario": "infinite-watch", "pool": "thread",
                "wire": "default", "delivered": len(appended),
                "quarantined_items": 0, "quarantined_rows": 0, "injected": 1,
                "lease_leak_delta": 0,
                "seconds": round(time.perf_counter() - t0, 3), "heals": 0}
    finally:
        import shutil

        shutil.rmtree(root, ignore_errors=True)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, all scenarios, hard "
                             "asserts on the exactly-once-or-quarantined "
                             "invariant and zero leaked leases")
    parser.add_argument("--files", type=int, default=None,
                        help="parquet files (= plan items); default 8 "
                             "(smoke) / 16")
    parser.add_argument("--rows-per-file", type=int, default=None,
                        help="rows per file; default 64 (smoke) / 512")
    parser.add_argument("--scenario", default=None,
                        help="run only this scenario (by name)")
    parser.add_argument("scenario_pos", nargs="?", default=None,
                        metavar="SCENARIO",
                        help="positional form of --scenario "
                             "(petastorm-tpu-bench chaos network --smoke)")
    args = parser.parse_args(argv)
    args.scenario = args.scenario or args.scenario_pos

    files = args.files or (8 if args.smoke else 16)
    rows = args.rows_per_file or (64 if args.smoke else 512)
    results = []
    with tempfile.TemporaryDirectory(prefix="ptpu-chaos-") as root:
        _write_dataset(root, files, rows)
        expected = list(range(files * rows))
        for name, pools, plan_fn, recovery, heal in _scenarios(files,
                                                               args.smoke):
            if args.scenario and name != args.scenario:
                continue
            for pool in pools:
                health = None
                if heal == "heal":
                    from petastorm_tpu.obs.health import HealthOptions

                    health = HealthOptions(
                        stall_threshold_s=1.5, poll_interval_s=0.3,
                        escalation="heal", thresholds={"child": 1.5},
                        flight_path=os.path.join(root, "chaos_flight.json"))
                wire = "shm-view" if pool == "process" else None
                result = _run_scenario(
                    name, root, expected, pool, plan_fn(), recovery=recovery,
                    wire=wire, health=health)
                if heal == "heal":
                    assert result["heals"] >= 1, \
                        "stall-heal: watchdog never healed (heals=0)"
                print("chaos %-13s %-8s delivered=%-6d quarantined=%-3d "
                      "injected=%-3d heals=%d leak_delta=%d %.2fs"
                      % (name, pool, result["delivered"],
                         result["quarantined_rows"], result["injected"],
                         result["heals"], result["lease_leak_delta"],
                         result["seconds"]))
                results.append(result)

        # network scenario (ISSUE 15): seeded link faults on the loopback
        # tcp transport — reconnect + ledgered re-dispatch must carry the
        # epoch with a ZERO respawn budget; plus the clean-run pipe-vs-tcp
        # byte-identity twin
        if not args.scenario or args.scenario == "network":
            count = _run_transport_identity(root, len(expected))
            print("chaos %-13s %-8s pipe vs tcp byte-identical over %d rows"
                  % ("transport-id", "process", count))
            result = _run_network(root, expected)
            print("chaos %-13s %-8s delivered=%-6d quarantined=%-3d "
                  "injected=%-3d reconnects=%d leak_delta=%d %.2fs"
                  % ("network", "process", result["delivered"],
                     result["quarantined_rows"], result["injected"],
                     result["reconnects"], result["lease_leak_delta"],
                     result["seconds"]))
            results.append(result)

    # mutating-dataset (ISSUE 11) runs against its own per-run dataset dirs
    # (the mutations destroy them); at least 16 files so the pools' claimed/
    # prefetched window never covers the remove/rewrite targets
    if not args.scenario or args.scenario == "mutating-dataset":
        mut_files = max(files, 16)
        for pool in ("dummy", "thread", "process"):
            result = _run_mutating_dataset(pool, mut_files, rows)
            print("chaos %-13s %-8s delivered=%-6d quarantined=%-3d "
                  "injected=%-3d heals=%d leak_delta=%d %.2fs"
                  % (result["scenario"], pool, result["delivered"],
                     result["quarantined_rows"], result["injected"],
                     result["heals"], result["lease_leak_delta"],
                     result["seconds"]))
            results.append(result)
        result = _run_infinite_watch(4, rows)
        print("chaos %-13s %-8s appended piece observed live in %.2fs"
              % (result["scenario"], result["pool"], result["seconds"]))
        results.append(result)

    summary = {
        "chaos_summary": {
            "scenarios": results,
            "invariant": "delivered ∪ quarantined == plan; no duplicates; "
                         "zero leaked leases/slabs; no hangs; no batch mixes "
                         "two generations of one file",
            "ok": True,
        }
    }
    print(json.dumps(summary, ensure_ascii=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
