"""Tabular-preprocessing micro-benchmark: declarative pipeline vs pandas callable.

Measures exactly what ISSUE 9 replaced: the opaque per-batch pandas
``TransformSpec`` forces an Arrow→pandas→Arrow round trip, a writable payload
copy, and per-element python work for ops pandas has no vectorized primitive
for (hashing, crossing). The declarative
:class:`~petastorm_tpu.ops.tabular.FeaturePipeline` compiles the SAME feature
math to fused vectorized numpy kernels that run columnar in the workers.

Each scenario drives the REAL pipeline (``make_batch_reader`` +
``DataLoader``, host delivery) over a synthetic multi-column feature workload
(8 float features standardized/normalized/clipped/cast, a hashed id, a
quantile bucketize, a vocabulary lookup, and a 2-column feature cross):

====================  ====================================================
scenario              configuration
====================  ====================================================
pandas-dummy          ``TransformSpec(pandas_twin)`` — the equivalent
                      per-batch pandas callable (vectorized Series ops
                      where pandas has them, per-element ``apply``-style
                      work for hash/cross), dummy pool — the timing twin
declarative-dummy     the ``FeaturePipeline``, dummy pool — timing +
                      value-identity vs the pandas twin
declarative-thread    the same pipeline on a thread pool (identity)
declarative-process   the same pipeline on a process pool with the
                      ``shm-view`` lease wire (identity + census)
====================  ====================================================

``--check`` asserts every declarative scenario delivers **value-identical**
batches to the pandas twin (elementwise, compared as sorted-by-id per-column
CRCs — pool arrival order is not deterministic), that
``ptpu_lease_leaked_total`` moved by 0, and that the declarative scenarios
charged ZERO bytes to the ``loader_detach`` and ``wire_writable`` census
sites (the whole-batch writable copy the opaque callable forces is gone).
``--smoke`` is the CI preset: tiny dataset, all checks, plus the hard
assertion that the fused-vectorized path delivers **≥ 2× rows/s** over the
pandas twin (the per-batch pandas overhead is deterministic work, so the
ratio is stable even on shared CI cores).

The last line of output is a one-line JSON summary (``tabular_summary``).
Run as ``petastorm-tpu-bench tabular`` (or
``python -m petastorm_tpu.benchmark.tabular``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import zlib

import numpy as np

SCENARIOS = ("pandas-dummy", "declarative-dummy", "declarative-thread",
             "declarative-process")

_FLOAT_COLS = 8
_VOCAB = list(range(50))

#: fixed feature-statistics constants shared by both paths (explicit
#: parameters: the statistics tiers are exercised by tests, not timed here)
_MEANS = [0.5 * (k + 1) for k in range(4)]
_STDS = [1.0 + 0.25 * k for k in range(4)]
_MIN, _MAX = 0.0, 64.0
_BOUNDS = np.linspace(-2.0, 2.0, 15)


def make_dataset(root, rows, rows_per_group, files=2):
    """Synthetic recommender-ish feature store: 8 float features, a wide id to
    hash, a small-cardinality category, and a second id to cross — all
    deterministic per row id so identity checks compare exact values."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    per_file = max(rows_per_group, rows // files)
    written = 0
    index = 0
    while written < rows:
        n = min(per_file, rows - written)
        ids = np.arange(written, written + n, dtype=np.int64)
        cols = {"id": ids}
        for k in range(_FLOAT_COLS):
            cols["f%d" % k] = np.sin(ids.astype(np.float64) * (k + 1) * 0.1) \
                * 2.0 + k * 0.5
        cols["u0"] = (ids * 2654435761) % 1000003  # wide id to hash
        cols["c0"] = ids % len(_VOCAB)             # vocab category
        cols["u1"] = ids % 97                      # cross partner
        pq.write_table(pa.table(cols),
                       os.path.join(root, "part-%05d.parquet" % index),
                       row_group_size=rows_per_group)
        written += n
        index += 1
    return root


def build_pipeline():
    """The declarative side of the workload."""
    from petastorm_tpu.ops.tabular import (
        Bucketize,
        Cast,
        Clip,
        FeatureCross,
        HashField,
        Normalize,
        Standardize,
        VocabLookup,
    )

    ops = []
    for k in range(4):
        ops.append(Standardize("f%d" % k, mean=_MEANS[k], std=_STDS[k]))
    for k in (4, 5):
        ops.append(Normalize("f%d" % k, min=_MIN, max=_MAX))
        ops.append(Clip("f%d" % k, 0.0, 1.0))
    for k in (6, 7):
        ops.append(Cast("f%d" % k, np.float32))
    ops.append(Bucketize("f0", boundaries=_BOUNDS, out="f0b"))
    ops.append(HashField("u0", 1000, out="u0h"))
    ops.append(VocabLookup("c0", vocab=_VOCAB, out="c0v"))
    ops.append(FeatureCross(("u0", "u1"), 4096, out="x01"))
    from petastorm_tpu.ops.tabular import FeaturePipeline

    return FeaturePipeline(ops)


def _fnv32_scalar(value, seed=0):
    """Pure-python twin of the vectorized 32-bit hash (what a pandas user
    writes per element — pandas has no wrapping-uint32 hash primitive)."""
    h = (2166136261 ^ seed) & 0xFFFFFFFF
    v = int(value) & 0xFFFFFFFFFFFFFFFF
    for shift in (0, 8, 16, 24):
        h = ((h ^ ((v >> shift) & 0xFF)) * 16777619) & 0xFFFFFFFF
    return h


def pandas_twin(df):
    """The equivalent per-batch pandas callable: identical values, idiomatic
    pandas — vectorized Series arithmetic where pandas has it, per-element
    python for the hash/cross ops it does not."""
    for k in range(4):
        df["f%d" % k] = (df["f%d" % k].astype(np.float32)
                         - np.float32(_MEANS[k])) * np.float32(1.0 / _STDS[k])
    scale = np.float32(1.0 / (_MAX - _MIN))
    for k in (4, 5):
        df["f%d" % k] = ((df["f%d" % k].astype(np.float32) - np.float32(_MIN))
                         * scale).clip(0.0, 1.0)
    for k in (6, 7):
        df["f%d" % k] = df["f%d" % k].astype(np.float32)
    df["f0b"] = np.searchsorted(
        _BOUNDS, df["f0"].to_numpy().astype(np.float64),
        side="right").astype(np.int32)
    df["u0h"] = df["u0"].map(
        lambda v: _fnv32_scalar(v) % 1000).astype(np.int64)
    df["c0v"] = df["c0"].map({v: i for i, v in enumerate(_VOCAB)}) \
        .fillna(-1).astype(np.int64)
    df["x01"] = [((_fnv32_scalar(a) * 16777619) & 0xFFFFFFFF
                  ^ _fnv32_scalar(b)) % 4096
                 for a, b in zip(df["u0"], df["u1"])]
    df["x01"] = df["x01"].astype(np.int64)
    return df


def build_pandas_spec():
    from petastorm_tpu.transform import TransformSpec

    edits = [("f%d" % k, np.float32, (), False) for k in range(_FLOAT_COLS)]
    edits += [("f0b", np.int32, (), False), ("u0h", np.int64, (), False),
              ("c0v", np.int64, (), False), ("x01", np.int64, (), False)]
    return TransformSpec(pandas_twin, edit_fields=edits)


def _batch_record(batch):
    """Sorted-by-id per-column CRCs — the identity unit (pool arrival order
    and in-batch row order both vary across pool types)."""
    ids = np.asarray(batch["id"])
    order = np.argsort(ids, kind="stable")
    crcs = [("id", zlib.crc32(np.ascontiguousarray(ids[order]).tobytes()))]
    for name in sorted(batch):
        v = batch[name]
        if name != "id" and isinstance(v, np.ndarray) and v.dtype != object:
            crcs.append(
                (name, str(v.dtype),
                 zlib.crc32(np.ascontiguousarray(np.asarray(v)[order])
                            .tobytes())))
    return int(ids.min()), crcs


def _census_delta(before):
    from petastorm_tpu.io.lease import copy_census

    after = copy_census()
    return {site: after.get(site, 0) - before.get(site, 0)
            for site in set(after) | set(before)
            if after.get(site, 0) != before.get(site, 0)}


def _measure(scenario, root, batch_size, workers, check):
    from petastorm_tpu.io.lease import copy_census, lease_stats
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    kind, _, pool = scenario.partition("-")
    spec = build_pandas_spec() if kind == "pandas" else build_pipeline()
    kwargs = {"reader_pool_type": pool, "shuffle_row_groups": False,
              "num_epochs": 1, "transform_spec": spec}
    if pool == "process":
        kwargs.update(workers_count=workers, wire_serializer="shm-view")
    elif pool == "thread":
        kwargs.update(workers_count=workers)
    before = copy_census()
    leases_before = lease_stats()
    t0 = time.perf_counter()
    with make_batch_reader("file://" + root, **kwargs) as reader:
        with DataLoader(reader, batch_size=batch_size, to_device=False,
                        last_batch="drop") as loader:
            batches = 0
            rows = 0
            records = []
            for batch in loader:
                batches += 1
                rows += len(batch["id"])
                if check:
                    records.append(_batch_record(batch))
    elapsed = time.perf_counter() - t0
    census = _census_delta(before)
    leases = lease_stats()
    row = {
        "scenario": scenario,
        "batches": batches,
        "rows": rows,
        "seconds": round(elapsed, 4),
        "rows_s": round(rows / elapsed, 1) if elapsed > 0 else None,
        "census": {k: census[k] for k in sorted(census)},
        "leases_leaked": leases["leaked"] - leases_before["leaked"],
    }
    return row, records


def run_tabular_bench(rows=16384, rows_per_group=256, batch_size=256, files=2,
                      workers=2, scenarios=SCENARIOS, check=False, root=None):
    """One result row per scenario. With ``check``, every declarative scenario
    must deliver value-identical batches to the pandas twin, leak no leases,
    and charge zero ``loader_detach``/``wire_writable`` census bytes."""
    if rows_per_group % batch_size:
        raise ValueError("rows_per_group must be a multiple of batch_size so "
                         "all paths cut identical batch boundaries")
    tmp = None
    if root is None:
        tmp = tempfile.TemporaryDirectory(prefix="ptpu-tabular-bench-")
        root = tmp.name
    try:
        make_dataset(root, rows, rows_per_group, files=files)
        results = []
        baseline = None
        for scenario in scenarios:
            row, records = _measure(scenario, root, batch_size, workers, check)
            if check:
                if row["leases_leaked"]:
                    raise AssertionError(
                        "scenario %r leaked %d lease(s)"
                        % (scenario, row["leases_leaked"]))
                if scenario.startswith("declarative"):
                    for site in ("loader_detach", "wire_writable"):
                        if row["census"].get(site):
                            raise AssertionError(
                                "declarative scenario %r charged %d bytes to "
                                "census site %r — the writable-batch copy is "
                                "supposed to be gone"
                                % (scenario, row["census"][site], site))
                    if baseline is None:
                        raise ValueError(
                            "--check needs pandas-dummy before declarative "
                            "scenarios as the identity baseline")
                    if sorted(records) != sorted(baseline):
                        raise AssertionError(
                            "scenario %r delivered different values than the "
                            "pandas twin" % scenario)
                    row["identical_to_pandas"] = True
                else:
                    baseline = records
            results.append(row)
        return results
    finally:
        if tmp is not None:
            tmp.cleanup()


def string_hash_bench(rows=200000, reps=3, check=True):
    """The ISSUE-13 satellite micro-bench: the vectorized byte-matrix crc32
    (:func:`petastorm_tpu.ops.tabular._hash_strings_matrix` behind
    ``_hash_strings_host``) vs the per-element ``zlib.crc32`` loop it
    replaced as the default lane, on the hot tabular string shapes
    (short-uniform ids, categorical codes, emails). With ``check`` the two
    lanes must be bit-identical on every shape — the dispatch is invisible
    to pipelines."""
    from petastorm_tpu.ops.tabular import (_hash_strings_host,
                                           _hash_strings_scalar)

    shapes = {
        "ids": ["u%08d" % i for i in range(rows)],
        "categories": ["cat-%03d" % (i % 512) for i in range(rows)],
        "emails": ["user-%d@example.com" % i for i in range(rows)],
    }
    out = []
    for name, data in shapes.items():
        if check:
            a = _hash_strings_host(data)
            b = _hash_strings_scalar(data)
            if a.dtype != np.uint32 or not (a == b).all():
                raise AssertionError(
                    "vectorized string hash diverged from zlib.crc32 on %r"
                    % name)
        t0 = time.perf_counter()
        for _ in range(reps):
            _hash_strings_host(data)
        t1 = time.perf_counter()
        for _ in range(reps):
            _hash_strings_scalar(data)
        t2 = time.perf_counter()
        vec_s, loop_s = (t1 - t0) / reps, (t2 - t1) / reps
        out.append({"shape": name, "rows": len(data),
                    "vectorized_s": round(vec_s, 4),
                    "scalar_loop_s": round(loop_s, 4),
                    "speedup": round(loop_s / vec_s, 2) if vec_s else None,
                    "identical": bool(check)})
    return out


def summarize(results):
    by_name = {r["scenario"]: r for r in results}
    summary = {"tabular_summary": True}
    pandas_row = by_name.get("pandas-dummy")
    decl = by_name.get("declarative-dummy")
    if pandas_row and decl and pandas_row.get("rows_s") and decl.get("rows_s"):
        summary["pandas_rows_s"] = pandas_row["rows_s"]
        summary["declarative_rows_s"] = decl["rows_s"]
        summary["speedup"] = round(decl["rows_s"] / pandas_row["rows_s"], 2)
    for name, row in by_name.items():
        if name.startswith("declarative"):
            summary.setdefault("census", {})[name] = row["census"]
    summary["leases_leaked"] = sum(r["leases_leaked"] for r in results)
    return summary


def _format_table(rows):
    cols = ("scenario", "batches", "rows", "seconds", "rows_s",
            "leases_leaked")
    widths = [max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(str(r.get(c, "")).ljust(w)
                               for c, w in zip(cols, widths)))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench tabular", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--rows", type=int, default=16384)
    parser.add_argument("--rows-per-group", type=int, default=256)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--files", type=int, default=2)
    parser.add_argument("--workers", type=int, default=2,
                        help="thread/process-pool workers")
    parser.add_argument("--scenarios", nargs="*", default=list(SCENARIOS),
                        choices=SCENARIOS)
    parser.add_argument("--check", action="store_true",
                        help="assert declarative scenarios deliver "
                             "value-identical batches to the pandas twin, "
                             "leak nothing, and copy nothing on the "
                             "loader_detach/wire_writable census sites")
    parser.add_argument("--json", action="store_true", help="JSON lines output")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, --check, plus the hard "
                             "assertion that the declarative path is >= 2x "
                             "the pandas twin's rows/s")
    args = parser.parse_args(argv)

    if args.smoke:
        kwargs = dict(rows=4096, rows_per_group=128, batch_size=128, files=2,
                      workers=2, scenarios=SCENARIOS, check=True)
    else:
        kwargs = dict(rows=args.rows, rows_per_group=args.rows_per_group,
                      batch_size=args.batch_size, files=args.files,
                      workers=args.workers, scenarios=tuple(args.scenarios),
                      check=args.check)

    results = run_tabular_bench(**kwargs)
    if args.json:
        for r in results:
            print(json.dumps(r))
    else:
        print(_format_table(results))
    summary = summarize(results)
    # string-hash satellite (ISSUE 13): identity always asserted; the timing
    # is informational off-smoke and a soft floor on smoke (the vectorized
    # lane must not LOSE to the loop it replaced on its target shapes)
    hash_rows = string_hash_bench(rows=20000 if args.smoke else 200000,
                                  reps=2 if args.smoke else 3, check=True)
    summary["string_hash"] = hash_rows
    for r in hash_rows:
        print("string-hash %-10s %d rows: vectorized %.4fs vs loop %.4fs "
              "(%.2fx, bit-identical)" % (r["shape"], r["rows"],
                                          r["vectorized_s"],
                                          r["scalar_loop_s"], r["speedup"]))
    if args.smoke:
        assert summary.get("speedup") and summary["speedup"] >= 2.0, \
            "declarative path is not >= 2x the pandas twin: %r" % summary
        assert summary["leases_leaked"] == 0, summary
        slow = [r for r in hash_rows if r["speedup"] is not None
                and r["speedup"] < 0.8]
        assert not slow, ("vectorized string hash regressed below the scalar "
                          "loop on: %r" % slow)
    if kwargs["check"]:
        print("identity: declarative scenarios delivered value-identical "
              "batches to the pandas twin")
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
