"""``petastorm-tpu-bench tenants``: does the accounting plane name the noisy
neighbor — and what does it cost when nobody asks?

**The acceptance harness for the ISSUE-18 per-tenant accounting plane.**
Four parts:

- ``contention`` scenario: two concurrent loaders share one host and one
  cache arena. Tenant ``a-quiet`` drains a small local store; tenant
  ``b-noisy`` drains an oversized store through a
  :class:`~petastorm_tpu.io.latencyfs.CloudLatencyFS` remote tail (the same
  injected bottleneck the slo/attribution benches use). The harness asserts
  the plane answers "who ate it?": the :class:`TenantUsageReport` names the
  noisy tenant as the top worker-seconds consumer, and a per-tenant burn
  SLO (``SloSpec(per_tenant=True)``) fires an alert that names BOTH the
  culprit tenant and (through the attached attribution snapshot) the culprit
  site — while the quiet tenant never alerts. Zero leaked arena leases after
  both drains.
- ``reconcile``: the tenant twins are charged ALONGSIDE the untagged totals,
  never instead — so cross-tenant sums must equal the untagged totals
  exactly: Σ ``ptpu_tenant_rows_total`` == delivered rows, and
  Σ ``ptpu_tenant_decode_seconds_total`` == the loaders' own decode stats.
- ``frames``: wire-compat of the version-negotiated tenant frame header —
  tagged and untagged frames round-trip byte-identically through
  ``pack_frame``/``take_frame``/``split_tenant`` (an old peer's unflagged
  frame passes through untouched; a truncated tenant header is a corrupt
  frame, not garbage), plus an end-to-end process-pool drain over the tcp
  transport asserting negotiated tagged frames bill ``wire_bytes`` to the
  owning tenant.
- ``overhead`` arm: the plane must be free when nobody tenants — a tagged vs
  untagged thread-pool workload over a randomized epoch schedule, comparing
  best-of-epoch envelopes. Measured ≤1% on a quiet host (the acceptance
  target), asserted at a 20% ceiling because shared CI cores jitter far more
  than the instrument. Identical delivered row sets in both arms.

The last stdout line is a one-line JSON summary for BENCH artifacts.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import tempfile
import threading
import time

QUIET = "a-quiet"
NOISY = "b-noisy"

#: per-window worker-seconds burn budget for the per-tenant SLO. The noisy
#: tenant is latency-bound (its single worker spends nearly the whole window
#: inside injected remote reads), so its per-window delta tracks the sampling
#: cadence (~_SAMPLE_S); the quiet tenant's TOTAL worker time for its tiny
#: local store sits well under one budget, so it cannot breach even once.
_BURN_BUDGET_S = 0.2
_SAMPLE_S = 0.5


def _make_store(root, files=2, rows_per_file=256):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(18)
    for i in range(files):
        pq.write_table(
            pa.table({
                "id": np.arange(rows_per_file, dtype=np.int64)
                + i * rows_per_file,
                "x": rng.random(rows_per_file),
                "y": rng.random(rows_per_file),
            }),
            os.path.join(root, "part-%02d.parquet" % i),
            row_group_size=max(32, rows_per_file // 4))
    return files * rows_per_file


def _snapshot_delta(registry, snap0):
    """Numeric counter movement since ``snap0`` (scenario-scoped metrics on
    the process-wide default registry)."""
    out = {}
    for name, value in registry.snapshot().items():
        if not isinstance(value, (int, float)):
            continue
        before = snap0.get(name)
        out[name] = value - before if isinstance(before, (int, float)) \
            else value
    return out


def _drain(loader, reader, out):
    """Drain one loader to exhaustion (thread target); arena stats are read
    INSIDE the with-block — after teardown the funnel is gone."""
    rows = 0
    try:
        with loader:
            for batch in loader:
                rows += len(batch["id"])
            out["io"] = reader.io_stats()
    except Exception as e:  # noqa: BLE001 — surfaced as a bench failure
        out["error"] = repr(e)
    out["rows"] = rows


def scenario_contention(workdir, smoke):
    """Two tenants, one host, one arena: the noisy one must be named."""
    import pyarrow.fs as pafs

    from petastorm_tpu.io import arena as arena_mod
    from petastorm_tpu.io.latencyfs import CloudLatencyFS
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.obs import tenant as tenant_mod
    from petastorm_tpu.obs.metrics import default_registry
    from petastorm_tpu.obs.slo import SloEngine, SloSpec
    from petastorm_tpu.reader import make_batch_reader

    registry = default_registry()
    snap0 = registry.snapshot()
    failures = []

    root_a = os.path.join(workdir, "quiet")
    root_b = os.path.join(workdir, "noisy")
    os.makedirs(root_a)
    os.makedirs(root_b)
    total_a = _make_store(root_a, files=2, rows_per_file=256)
    # the noisy tenant reads OVERSIZED: more files, more rows per file, and
    # every byte through an injected remote tail
    total_b = _make_store(root_b, files=4 if smoke else 6, rows_per_file=512)
    fs_b = CloudLatencyFS(pafs.LocalFileSystem(), seed=7,
                          base_latency_s=0.06, tail_fraction=0.25,
                          tail_multiplier=4.0)

    arena_opts = {"readahead": False, "work_stealing": False,
                  "arena_bytes": 32 << 20}
    # workers_count=1 on the noisy side: serialized reads keep every window's
    # worker delta carrying the injected latency (same reasoning as the slo
    # bench's breach scenario)
    reader_a = make_batch_reader(
        "file://" + root_a, num_epochs=1, workers_count=1, tenant=QUIET,
        io_options=dict(arena_opts))
    reader_b = make_batch_reader(
        "file://" + root_b, filesystem=fs_b, num_epochs=1, workers_count=1,
        provenance=True, tenant=NOISY,
        io_options=dict(arena_opts,
                        remote=dict(enabled=True, hedge=False)))

    spec = SloSpec(name="tenant-worker-burn",
                   metric=tenant_mod.RESOURCES["worker_s"][0],
                   stat="delta", op="<=", threshold=_BURN_BUDGET_S,
                   breach_windows=2, per_tenant=True,
                   description="per-window worker-seconds budget per tenant")
    engine = SloEngine(specs=[spec], registry=registry)
    engine.attach(registry.timeline_store())

    loader_a = DataLoader(reader_a, 64, to_device=False, host_queue_size=2)
    # slos= on the noisy loader wires its attribution_report (provenance is
    # on) so the burn alert names the culprit SITE beside the tenant
    loader_b = DataLoader(reader_b, 64, to_device=False, host_queue_size=2,
                          metrics=registry, slos=engine)

    out_a, out_b = {}, {}
    threads = [threading.Thread(target=_drain, args=(loader_a, reader_a,
                                                     out_a)),
               threading.Thread(target=_drain, args=(loader_b, reader_b,
                                                     out_b))]
    for t in threads:
        t.start()
    while any(t.is_alive() for t in threads):
        time.sleep(_SAMPLE_S)
        registry.sample_timelines()
    for t in threads:
        t.join()
    registry.sample_timelines()

    for label, out in ((QUIET, out_a), (NOISY, out_b)):
        if "error" in out:
            failures.append("tenant %s drain died: %s" % (label,
                                                          out["error"]))
    assert out_a.get("rows") == total_a, (out_a, total_a)
    assert out_b.get("rows") == total_b, (out_b, total_b)

    # zero leaked leases on the SHARED arena after both drains
    arena = arena_mod.process_arena()
    held = arena.stats().get("arena_held_leases", 0) \
        if arena is not None else 0
    if held:
        failures.append("%d arena leases leaked after both drains" % held)
    arena_mod.close_process_arena()

    delta = _snapshot_delta(registry, snap0)
    report = tenant_mod.TenantUsageReport.from_metrics(delta)
    tenant_mod.meter(registry).arena_settle()

    top_worker, top_worker_v = report.top_consumer("worker_s")
    if top_worker != NOISY:
        failures.append("top worker-seconds consumer is %r (%.3fs), "
                        "expected %r" % (top_worker, top_worker_v, NOISY))
    top_bytes, _v = report.top_consumer("read_bytes")
    if top_bytes not in (NOISY, None):
        # None = this config's read path didn't route through a counting
        # tier; wrong-tenant is a real failure
        failures.append("top read-bytes consumer is %r, expected %r"
                        % (top_bytes, NOISY))

    breaches = [a for a in engine.alerts() if a.cause == "slo_breach"]
    noisy_alerts = [a for a in breaches if a.tenant == NOISY]
    quiet_alerts = [a for a in breaches if a.tenant == QUIET]
    if not noisy_alerts:
        failures.append(
            "no per-tenant burn alert named %r (windows evaluated: %d, "
            "breaching: %s)" % (NOISY, engine.windows_evaluated,
                                engine.breaching()))
    if quiet_alerts:
        failures.append("the quiet tenant %r fired %d burn alerts"
                        % (QUIET, len(quiet_alerts)))
    culprit = noisy_alerts[0].culprit if noisy_alerts else None
    if noisy_alerts and culprit != "io.remote":
        failures.append("burn alert for %r blamed site %r, expected "
                        "io.remote" % (NOISY, culprit))

    # -- reconcile: cross-tenant sums == untagged totals --------------------
    rows_sum = sum(report.get(t, "rows") for t in report.tenants())
    if int(rows_sum) != total_a + total_b:
        failures.append(
            "tenant rows do not reconcile: sum(ptpu_tenant_rows_total) = %d "
            "!= %d delivered" % (int(rows_sum), total_a + total_b))
    decode_sum = sum(report.get(t, "decode_s") for t in report.tenants())
    decode_total = loader_a.stats.decode_s + loader_b.stats.decode_s
    if abs(decode_sum - decode_total) > 1e-6 + 1e-3 * decode_total:
        failures.append(
            "tenant decode seconds do not reconcile: %.6fs tagged vs %.6fs "
            "untagged" % (decode_sum, decode_total))

    return {
        "rows": {QUIET: out_a["rows"], NOISY: out_b["rows"]},
        "report": report.to_dict(),
        "top_worker_s": top_worker,
        "alerts": [{"tenant": a.tenant, "culprit": a.culprit,
                    "value": a.value} for a in breaches],
        "held_leases": held,
        "decode_s_tagged": round(decode_sum, 6),
        "decode_s_untagged": round(decode_total, 6),
        "ok": not failures,
    }, failures


def check_frames():
    """Tenant frame-header compat: tagged <-> untagged peers, both ways."""
    from petastorm_tpu.errors import TransportFrameCorrupt
    from petastorm_tpu.transport.framing import (
        K_OBJ,
        K_TENANT_FLAG,
        pack_frame,
        split_tenant,
        take_frame,
    )

    payload = b"row-group-result-bytes"
    # new sender -> new receiver: tagged round-trip, byte-identical payload
    buf = bytearray(pack_frame(K_OBJ, payload, tenant=NOISY))
    kind, body = take_frame(buf)
    assert kind == K_OBJ | K_TENANT_FLAG, kind
    assert split_tenant(kind, body) == (K_OBJ, payload, NOISY)
    # old sender -> new receiver: unflagged frame passes through untagged
    buf = bytearray(pack_frame(K_OBJ, payload))
    kind, body = take_frame(buf)
    assert split_tenant(kind, body) == (K_OBJ, payload, None)
    # new sender -> old peer: pack_frame without tenant= (what an
    # un-negotiated link sends after the downgrade) is byte-identical to the
    # old wire format
    assert pack_frame(K_OBJ, payload) == pack_frame(K_OBJ, payload,
                                                    tenant=None)
    # a truncated tenant header is a corrupt frame, never garbage delivery
    try:
        split_tenant(K_OBJ | K_TENANT_FLAG, b"\xff" + b"ab")
    except TransportFrameCorrupt:
        pass
    else:
        raise AssertionError("truncated tenant header parsed as a frame")


def scenario_wire(workdir):
    """End-to-end tcp pool drain with a tenant: negotiated tagged frames must
    deliver every row and bill wire bytes to the owning tenant."""
    from petastorm_tpu.obs import tenant as tenant_mod
    from petastorm_tpu.obs.metrics import default_registry
    from petastorm_tpu.reader import make_batch_reader

    registry = default_registry()
    snap0 = registry.snapshot()
    failures = []

    root = os.path.join(workdir, "wire")
    os.makedirs(root)
    total = _make_store(root, files=1, rows_per_file=128)
    rows = 0
    with make_batch_reader("file://" + root, num_epochs=1,
                           reader_pool_type="process", workers_count=1,
                           transport="tcp", tenant="c-wire") as reader:
        for batch in reader:
            rows += len(batch.id)
    assert rows == total, (rows, total)

    delta = _snapshot_delta(registry, snap0)
    report = tenant_mod.TenantUsageReport.from_metrics(delta)
    wire_bytes = report.get("c-wire", "wire_bytes")
    if wire_bytes <= 0:
        failures.append("tcp pool drain with tenant= charged no "
                        "ptpu_tenant_wire_bytes_total (negotiation or "
                        "rx accounting broken)")
    return {"rows": rows, "wire_bytes": int(wire_bytes),
            "ok": not failures}, failures


def measure_overhead(workdir, epochs=5):
    """BEST rows/s with a tenant tagged on every charge site vs fully
    untagged (the disabled plane pays only ``is None`` checks — tagged is a
    strict superset of that cost, so bounding tagged bounds disabled too).
    Randomized epoch order; identical delivered row sets asserted. Returns
    ``(off_best, on_best, overhead_fraction)``."""
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "overhead")
    os.makedirs(root)
    _make_store(root, files=3)

    def one_epoch(tagged):
        reader = make_batch_reader(
            "file://" + root, num_epochs=1, workers_count=2,
            tenant="ovh" if tagged else None)
        ids = []
        t0 = time.perf_counter()
        with DataLoader(reader, 64, to_device=False,
                        tenant="ovh" if tagged else None) as loader:
            for batch in loader:
                ids.extend(int(v) for v in batch["id"])
        dt = time.perf_counter() - t0
        return len(ids) / dt, sorted(ids)

    one_epoch(False)  # warmup
    arms = [False] * epochs + [True] * epochs
    random.Random(18).shuffle(arms)
    off, on = [], []
    ids_off = ids_on = None
    for arm in arms:
        rate, ids = one_epoch(arm)
        (on if arm else off).append(rate)
        if arm:
            ids_on = ids
        else:
            ids_off = ids
    assert ids_off == ids_on, "the tenant plane changed the delivered rows"
    print("overhead medians: untagged %.0f vs tagged %.0f rows/s"
          % (statistics.median(off), statistics.median(on)))
    off_best, on_best = max(off), max(on)
    return off_best, on_best, max(0.0, 1.0 - on_best / off_best)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench tenants", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny stores, hard assertions, 20%% "
                             "overhead ceiling")
    parser.add_argument("--skip-overhead", action="store_true",
                        help="skip the tagged/untagged throughput arms")
    parser.add_argument("--skip-wire", action="store_true",
                        help="skip the process-pool tcp wire leg (frame "
                             "round-trips still run)")
    args = parser.parse_args(argv)

    failures = []
    with tempfile.TemporaryDirectory(prefix="ptpu-tenants-") as workdir:
        contention, contention_failures = scenario_contention(
            workdir, smoke=args.smoke)
    failures.extend(contention_failures)
    print("contention: top worker-seconds consumer %s, %d burn alert(s) %s, "
          "%d leaked leases (%s)"
          % (contention["top_worker_s"], len(contention["alerts"]),
             [(a["tenant"], a["culprit"]) for a in contention["alerts"]],
             contention["held_leases"],
             "OK" if contention["ok"] else "FAILING"))
    print("reconcile: rows %s; decode %.4fs tagged vs %.4fs untagged"
          % (contention["rows"], contention["decode_s_tagged"],
             contention["decode_s_untagged"]))

    check_frames()
    print("frames: tagged/untagged round-trips byte-identical, truncated "
          "header rejected")

    wire = None
    if not args.skip_wire:
        with tempfile.TemporaryDirectory(prefix="ptpu-tenants-") as workdir:
            wire, wire_failures = scenario_wire(workdir)
        failures.extend(wire_failures)
        print("wire: %d rows over the tagged tcp pool, %d tenant wire bytes "
              "(%s)" % (wire["rows"], wire["wire_bytes"],
                        "OK" if wire["ok"] else "FAILING"))

    overhead = None
    if not args.skip_overhead:
        with tempfile.TemporaryDirectory(prefix="ptpu-tenants-") as workdir:
            off_best, on_best, overhead = measure_overhead(
                workdir, epochs=5 if args.smoke else 9)
        print("overhead: untagged %.0f rows/s vs tagged %.0f rows/s "
              "best-of-epochs (delta %.2f%%; acceptance target <=1%% on a "
              "quiet host)" % (off_best, on_best, 100 * overhead))
        if args.smoke and overhead > 0.20:
            failures.append("tenant-plane overhead %.1f%% exceeds the 20%% "
                            "smoke ceiling" % (100 * overhead))

    summary = {"bench": "tenants", "contention": contention, "wire": wire,
               "overhead_fraction": None if overhead is None
               else round(overhead, 4),
               "failures": failures}
    print(json.dumps(summary, ensure_ascii=False))
    if failures:
        for failure in failures:
            print("FAIL:", failure)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
