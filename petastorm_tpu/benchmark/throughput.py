"""Reader throughput harness (reference petastorm/benchmark/throughput.py
``reader_throughput`` ~L60: warmup + timed loop, per pool type / workers / fields), extended
with per-stage counters the reference lacks (SURVEY.md §6): read/decode vs device-feed split
and device-idle estimation when a loader is measured.
"""
from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class BenchmarkResult:
    rows_per_second: float
    rows: int
    seconds: float
    batches: int = 0
    device_idle_fraction: float | None = None
    stages: dict | None = None  # loader PipelineStats snapshot, when measured

    def __str__(self):
        s = "%.1f rows/s (%d rows in %.2fs)" % (self.rows_per_second, self.rows, self.seconds)
        if self.device_idle_fraction is not None:
            s += ", device idle %.1f%%" % (100 * self.device_idle_fraction)
        if self.stages:
            s += ", stages=%r" % (self.stages,)
        return s


def _count_rows(item):
    d = item._asdict() if hasattr(item, "_asdict") else item
    if isinstance(d, dict):
        first = next(iter(d.values()), None)
        if hasattr(first, "__len__") and getattr(first, "ndim", 1) >= 1:
            return len(first)
    return 1


def reader_throughput(reader, warmup_rows=1000, measure_rows=10000):
    """rows/sec of ``next(reader)`` after warmup (reference contract)."""
    warmed = 0
    it = iter(reader)
    for item in it:
        warmed += _count_rows(item)
        if warmed >= warmup_rows:
            break
    n = 0
    batches = 0
    t0 = time.perf_counter()
    for item in it:
        n += _count_rows(item)
        batches += 1
        if n >= measure_rows:
            break
    dt = time.perf_counter() - t0
    return BenchmarkResult(rows_per_second=n / dt if dt else float("inf"), rows=n,
                           seconds=dt, batches=batches)


def loader_throughput(loader, consume_fn=None, warmup_batches=4, measure_batches=50):
    """End-to-end loader rows/sec including device feed; estimates device idle as the
    fraction of wall time NOT spent inside ``consume_fn`` (the device work)."""
    it = iter(loader)
    for _ in range(warmup_batches):
        batch = next(it, None)
        if batch is None:
            break
        if consume_fn is not None:
            consume_fn(batch)
    stats = getattr(loader, "stats", None)
    if stats is not None:
        stats.reset()  # the stage split must cover only the measured window below
    n = 0
    batches = 0
    busy = 0.0
    t0 = time.perf_counter()
    for batch in it:
        n += _count_rows(batch)
        batches += 1
        if consume_fn is not None:
            c0 = time.perf_counter()
            consume_fn(batch)
            busy += time.perf_counter() - c0
        if batches >= measure_batches:
            break
    dt = time.perf_counter() - t0
    idle = None
    if consume_fn is not None and dt > 0:
        idle = max(0.0, 1.0 - busy / dt)
    stats = getattr(loader, "stats", None)
    return BenchmarkResult(rows_per_second=n / dt if dt else float("inf"), rows=n,
                           seconds=dt, batches=batches, device_idle_fraction=idle,
                           stages=stats.snapshot() if stats is not None else None)
