"""Reader throughput harness (reference petastorm/benchmark/throughput.py
``reader_throughput`` ~L60: warmup + timed loop, per pool type / workers / fields), extended
with per-stage counters the reference lacks (SURVEY.md §6): read/decode vs device-feed split
and device-idle estimation when a loader is measured.
"""
from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class BenchmarkResult:
    rows_per_second: float
    rows: int
    seconds: float
    batches: int = 0
    device_idle_fraction: float | None = None
    stages: dict | None = None  # loader PipelineStats snapshot, when measured
    step_seconds: float | None = None  # overlap mode: standalone per-step device cost
    step_repeats: int | None = None  # overlap mode: calibrated steps per batch

    def __str__(self):
        s = "%.1f rows/s (%d rows in %.2fs)" % (self.rows_per_second, self.rows, self.seconds)
        if self.device_idle_fraction is not None:
            s += ", device idle %.1f%%" % (100 * self.device_idle_fraction)
        if self.stages:
            s += ", stages=%r" % (self.stages,)
        return s


def _reset_stage_histograms(loader):
    """Re-anchor a metrics-enabled loader's stage percentiles alongside
    ``PipelineStats.reset()``: a bottleneck report read after a benchmark must
    describe the measured window, not the warmup/compile batches."""
    obs = getattr(loader, "_obs", None)
    if obs is not None:
        obs.reset_stage_histograms()


def _count_rows(item):
    d = item._asdict() if hasattr(item, "_asdict") else item
    if isinstance(d, dict):
        first = next(iter(d.values()), None)
        if hasattr(first, "__len__") and getattr(first, "ndim", 1) >= 1:
            return len(first)
    return 1


def reader_throughput(reader, warmup_rows=1000, measure_rows=10000):
    """rows/sec of ``next(reader)`` after warmup (reference contract)."""
    warmed = 0
    it = iter(reader)
    for item in it:
        warmed += _count_rows(item)
        if warmed >= warmup_rows:
            break
    n = 0
    batches = 0
    t0 = time.perf_counter()
    for item in it:
        n += _count_rows(item)
        batches += 1
        if n >= measure_rows:
            break
    dt = time.perf_counter() - t0
    return BenchmarkResult(rows_per_second=n / dt if dt else float("inf"), rows=n,
                           seconds=dt, batches=batches)


def loader_throughput(loader, consume_fn=None, warmup_batches=4, measure_batches=50):
    """End-to-end loader rows/sec including device feed; estimates device idle as the
    fraction of wall time NOT spent inside ``consume_fn`` (the device work)."""
    it = iter(loader)
    for _ in range(warmup_batches):
        batch = next(it, None)
        if batch is None:
            break
        if consume_fn is not None:
            consume_fn(batch)
    stats = getattr(loader, "stats", None)
    if stats is not None:
        stats.reset()  # the stage split must cover only the measured window below
    _reset_stage_histograms(loader)  # percentiles re-anchor with the totals
    n = 0
    batches = 0
    busy = 0.0
    t0 = time.perf_counter()
    for batch in it:
        n += _count_rows(batch)
        batches += 1
        if consume_fn is not None:
            c0 = time.perf_counter()
            consume_fn(batch)
            busy += time.perf_counter() - c0
        if batches >= measure_batches:
            break
    dt = time.perf_counter() - t0
    idle = None
    if consume_fn is not None and dt > 0:
        idle = max(0.0, 1.0 - busy / dt)
    stats = getattr(loader, "stats", None)
    return BenchmarkResult(rows_per_second=n / dt if dt else float("inf"), rows=n,
                           seconds=dt, batches=batches, device_idle_fraction=idle,
                           stages=stats.snapshot() if stats is not None else None)


def overlap_throughput(loader, step_fn, warmup_batches=3, measure_batches=30,
                       headroom=1.3, step_repeats=None, deadline=None):
    """The north-star measurement (BASELINE.md: device idle ≤ 2%): overlap the pipeline
    with device work sized ≥ the pipeline's per-batch cost and report the consumer's
    starvation — ``device_queue_wait_s / wall`` — as the device-idle fraction.

    ``loader_throughput`` measures the pipeline against a FREE device, so whenever the
    consume step is cheaper than the pipeline the reported "idle" is definitionally
    large — it conflates pipeline capability with step cost. This mode asks the
    question the north star actually asks: **with a device kept busy at least one
    pipeline interval per batch, does the pipeline ever make it wait?** It is
    weather-independent: a slow device service stretches both the step and the
    pipeline's dispatch equally, and starvation is measured on the consumer thread.

    ``step_fn(batch) -> device value`` must be an async-dispatching jitted function.
    The step runs ``step_repeats`` times per batch; when None it is auto-calibrated so
    ``step_repeats × step_time ≥ headroom × pipeline-interval``.

    ``deadline`` (optional ``time.perf_counter()`` value): adaptive re-measures are
    skipped once past it, so a caller budgeting a whole bench run can bound this
    call's worst case under degraded service weather.
    """
    import jax

    fixed_repeats = step_repeats is not None
    it = iter(loader)
    last = None
    for _ in range(warmup_batches):  # compiles the step, warms pipeline + page cache
        b = next(it, None)
        if b is None:
            break
        jax.block_until_ready(step_fn(b))
        last = b
    if last is None:
        raise ValueError("loader exhausted during warmup")

    # standalone device step cost (async ×10, block once)
    t0 = time.perf_counter()
    r = None
    for _ in range(10):
        r = step_fn(last)
    jax.block_until_ready(r)
    step_s = (time.perf_counter() - t0) / 10

    if step_repeats is None:
        # Pipeline-only interval. Buffered batches arrive at queue-pop speed and
        # would understate it badly, so first FLUSH until a get actually waits on
        # the queue (the pipeline, not the buffer, is pacing deliveries), then time
        # a paced window.
        stats_obj = getattr(loader, "stats", None)
        flush_cap = 3 * (getattr(loader, "prefetch", 2)
                         + getattr(loader, "_host_queue_size", 8) + 2)
        for _ in range(flush_cap):
            before = stats_obj.device_queue_wait_s if stats_obj is not None else 0.0
            if next(it, None) is None:
                raise ValueError("loader exhausted during calibration")
            if stats_obj is None \
                    or stats_obj.device_queue_wait_s - before > 1e-4:
                break
        probe = 6
        if stats_obj is not None:
            stats_obj.reset()
        t0 = time.perf_counter()
        for _ in range(probe):
            if next(it, None) is None:
                raise ValueError("loader exhausted during calibration")
        pipeline_interval = (time.perf_counter() - t0) / probe
        if stats_obj is not None:
            # second estimate: the pipeline's own per-batch stage cost — robust when
            # the probe window still rode buffered batches
            snap = stats_obj.snapshot()
            if snap["batches"]:
                stage_cost = (snap["read_s"] + snap["batch_s"] + snap["decode_s"]
                              + snap["h2d_s"]) / snap["batches"]
                pipeline_interval = max(pipeline_interval, stage_cost)
        step_repeats = max(1, int(headroom * pipeline_interval / max(step_s, 1e-9) + 1))

    stats = getattr(loader, "stats", None)

    def window(repeats):
        if stats is not None:
            stats.reset()  # idle split covers exactly the measured window
        _reset_stage_histograms(loader)
        n = 0
        batches = 0
        r = None
        t0 = time.perf_counter()
        for b in it:
            for _ in range(repeats):
                r = step_fn(b)
            n += _count_rows(b)
            batches += 1
            if batches >= measure_batches:
                break
        jax.block_until_ready(r)
        dt = time.perf_counter() - t0
        snapshot = stats.snapshot() if stats is not None else None
        idle = None
        if snapshot is not None and dt > 0:
            idle = min(1.0, snapshot["device_queue_wait_s"] / dt)
        return BenchmarkResult(
            rows_per_second=n / dt if dt else float("inf"), rows=n, seconds=dt,
            batches=batches, device_idle_fraction=idle, stages=snapshot,
            step_seconds=step_s, step_repeats=repeats,
        )

    # Adaptive re-measure: if the window shows starvation, the calibration
    # underestimated the pipeline interval (bursty deliveries, service weather) —
    # scale the device work to the OBSERVED per-batch wall and measure again. The
    # question is binary ("can the pipeline keep a sufficiently-busy device fed?"),
    # so sizing the step from observation is the measurement, not cheating: a
    # pipeline that serializes against the step would stay starved at any repeats.
    res = window(step_repeats)
    # An EXPLICIT step_repeats pins the question ("can the pipeline feed THIS much
    # device work per batch?") — escalating would silently answer a different one;
    # the observed idle IS the answer then, however large.
    for _ in range(2 if not fixed_repeats else 0):
        if res.device_idle_fraction is None or res.device_idle_fraction <= 0.1:
            break
        if deadline is not None and time.perf_counter() > deadline:
            break
        per_batch_wall = res.seconds / max(1, res.batches)
        step_repeats = max(step_repeats + 1,
                           int(headroom * per_batch_wall / max(step_s, 1e-9) + 1))
        res = window(step_repeats)
    return res
