"""Copy-census micro-benchmark: the copying default path vs the leased path.

Measures exactly what the ISSUE-6 buffer-lease contract removed: the memcpy
every hop of the read path used to pay defensively. Each scenario drives the
REAL pipeline (``make_batch_reader`` + ``DataLoader``, host delivery) over a
synthetic numeric parquet dataset and diffs the process-wide **copy census**
(``ptpu_copy_bytes_total{site=}``, see :mod:`petastorm_tpu.io.lease`) around
the drain, reporting **bytes copied per delivered batch** per path:

====================  ====================================================
scenario              configuration
====================  ====================================================
wire-default          process pool, ``wire_serializer='shm'`` — the
                      writable-batch contract deep-copies every read-only
                      reconstruction out of the slab (``wire_writable``)
wire-leased           process pool, ``wire_serializer='shm-view'`` — the
                      loader RETAINS the delivery's lease through batching
                      (no writable copy, no copy-out before buffering)
memcache-default      dummy pool, in-memory cache with the legacy
                      ``writable_hits`` contract — a deep copy per admit
                      AND per warm hit; the warm epoch is timed
memcache-leased       dummy pool, lease-contract cache — zero-copy
                      read-only views both ways; the warm epoch is timed
====================  ====================================================

``--check`` asserts each leased scenario delivers **byte-identical** batches
to its copying twin (ids + per-column CRC per batch, order included), and that
no lease leaked (``ptpu_lease_leaked_total`` delta must be 0). ``--smoke`` is
the CI preset: tiny dataset, identity checks, and a hard assertion that the
leased paths copy strictly fewer bytes per delivered batch than the default
paths (copied bytes are deterministic, so this is safe on shared CI cores —
unlike the wall-clock warm-hit throughput, which is reported but only asserted
in full runs).

The last line of output is a one-line JSON summary (``copies_summary``) with
the copied-bytes-per-batch of both paths and the reduction factors, so
BENCH_*.json artifacts record the census trajectory alongside throughput.

Run as ``petastorm-tpu-bench copies`` (or
``python -m petastorm_tpu.benchmark.copies``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import zlib

import numpy as np

SCENARIOS = ("wire-default", "wire-leased", "memcache-default", "memcache-leased")

#: numeric feature columns per row (float64): the payload the census counts
_FEATURE_COLS = 8


def make_dataset(root, rows, rows_per_group, files=2):
    """Synthetic numeric parquet store: an int64 ``id`` plus ``_FEATURE_COLS``
    float64 features, deterministic per id so identity checks compare exact
    bytes. All-numeric on purpose — every copy site the census tracks charges
    ndarray buffer bytes, so the per-batch numbers line up across paths."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    per_file = max(rows_per_group, rows // files)
    written = 0
    index = 0
    while written < rows:
        n = min(per_file, rows - written)
        ids = np.arange(written, written + n, dtype=np.int64)
        cols = {"id": ids}
        for k in range(_FEATURE_COLS):
            cols["f%d" % k] = (ids * (k + 1)).astype(np.float64) * 0.5
        pq.write_table(pa.table(cols),
                       os.path.join(root, "part-%05d.parquet" % index),
                       row_group_size=rows_per_group)
        written += n
        index += 1
    return root


def _batch_record(batch):
    """(ids, [(name, crc)]) for one delivered batch — the identity unit. Sorted
    column order so dict ordering differences can't fail the comparison."""
    ids = np.asarray(batch["id"]).tolist()
    crcs = []
    for name in sorted(batch):
        v = batch[name]
        if isinstance(v, np.ndarray) and v.dtype != object:
            crcs.append((name, zlib.crc32(np.ascontiguousarray(v).tobytes())))
    return ids, crcs


def _drain_loader(loader, collect):
    """Consume every host batch; returns (batches, rows, [records])."""
    batches = 0
    rows = 0
    records = []
    for batch in loader:
        batches += 1
        rows += len(batch["id"])
        if collect:
            records.append(_batch_record(batch))
    return batches, rows, records


def _census_delta(before):
    from petastorm_tpu.io.lease import copy_census

    after = copy_census()
    return {site: after.get(site, 0) - before.get(site, 0)
            for site in set(after) | set(before)
            if after.get(site, 0) != before.get(site, 0)}


def _measure_wire(scenario, root, batch_size, workers, check):
    """Process-pool scenario: shm (writable copies) vs shm-view (leases)."""
    from petastorm_tpu.io.lease import copy_census, lease_stats
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    wire = "shm" if scenario == "wire-default" else "shm-view"
    before = copy_census()
    leases_before = lease_stats()
    t0 = time.perf_counter()
    with make_batch_reader("file://" + root, reader_pool_type="process",
                           workers_count=workers, wire_serializer=wire,
                           shuffle_row_groups=False, num_epochs=1) as reader:
        with DataLoader(reader, batch_size=batch_size, to_device=False,
                        last_batch="drop") as loader:
            batches, rows, records = _drain_loader(loader, check)
    elapsed = time.perf_counter() - t0
    return _result_row(scenario, batches, rows, elapsed, _census_delta(before),
                       lease_stats(), leases_before), records


def _measure_memcache(scenario, root, batch_size, memcache_mb, check):
    """Dummy-pool scenario (cache runs in-process, so its census is visible):
    legacy writable_hits deep copies vs lease-contract read-only views. Two
    epochs — the cold one fills the cache, only the WARM epoch is measured."""
    from petastorm_tpu.io.lease import copy_census, lease_stats
    from petastorm_tpu.io.memcache import shared_store
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    writable = scenario == "memcache-default"
    io_opts = {"memcache_bytes": memcache_mb << 20,
               "memcache_writable_hits": writable}
    shared_store().clear()  # cold start regardless of scenario order
    try:
        # cold epoch: fill the cache through the same pipeline shape
        with make_batch_reader("file://" + root, reader_pool_type="dummy",
                               shuffle_row_groups=False, num_epochs=1,
                               io_options=io_opts) as reader:
            with DataLoader(reader, batch_size=batch_size, to_device=False,
                            last_batch="drop") as loader:
                _drain_loader(loader, collect=False)
        # warm epoch: every read is a cache hit — the memcpy-per-hit (or its
        # absence) is the whole difference between the two scenarios
        before = copy_census()
        leases_before = lease_stats()
        t0 = time.perf_counter()
        with make_batch_reader("file://" + root, reader_pool_type="dummy",
                               shuffle_row_groups=False, num_epochs=1,
                               io_options=io_opts) as reader:
            with DataLoader(reader, batch_size=batch_size, to_device=False,
                            last_batch="drop") as loader:
                batches, rows, records = _drain_loader(loader, check)
        elapsed = time.perf_counter() - t0
        return _result_row(scenario, batches, rows, elapsed,
                           _census_delta(before), lease_stats(),
                           leases_before), records
    finally:
        shared_store().clear()


def _result_row(scenario, batches, rows, elapsed, census, leases, leases_before):
    copied = sum(census.values())
    return {
        "scenario": scenario,
        "batches": batches,
        "rows": rows,
        "seconds": round(elapsed, 4),
        "rows_s": round(rows / elapsed, 1) if elapsed > 0 else None,
        "copied_bytes": copied,
        "copied_bytes_per_batch": round(copied / batches, 1) if batches else 0.0,
        "census": {k: census[k] for k in sorted(census)},
        "leases_leaked": leases["leaked"] - leases_before["leaked"],
    }


def run_copies_bench(rows=4096, rows_per_group=64, batch_size=32, files=2,
                     workers=2, memcache_mb=256, scenarios=SCENARIOS,
                     check=False, root=None):
    """One result row per scenario. With ``check``, each ``*-leased`` scenario
    must deliver byte-identical batches to its ``*-default`` twin and leak no
    leases; identity failures raise."""
    if rows_per_group % batch_size:
        raise ValueError("rows_per_group must be a multiple of batch_size so "
                         "both paths cut identical batch boundaries")
    tmp = None
    if root is None:
        tmp = tempfile.TemporaryDirectory(prefix="ptpu-copies-bench-")
        root = tmp.name
    try:
        make_dataset(root, rows, rows_per_group, files=files)
        results = []
        baselines = {}  # group -> records of the *-default twin
        for scenario in scenarios:
            group, _, variant = scenario.partition("-")
            if group == "wire":
                row, records = _measure_wire(scenario, root, batch_size,
                                             workers, check)
            else:
                row, records = _measure_memcache(scenario, root, batch_size,
                                                 memcache_mb, check)
            if check:
                if row["leases_leaked"]:
                    raise AssertionError(
                        "scenario %r leaked %d lease(s) (GC reclaimed a hold "
                        "no one released)" % (scenario, row["leases_leaked"]))
                if variant == "default":
                    baselines[group] = records
                else:
                    base = baselines.get(group)
                    if base is None:
                        raise ValueError(
                            "--check needs %s-default before %s as the "
                            "identity baseline" % (group, scenario))
                    # multi-worker pools deliver in ARRIVAL order, which varies
                    # run to run; batch boundaries are deterministic (the
                    # rows_per_group % batch_size == 0 guard above), so the
                    # identity claim is over the SET of delivered batches
                    if sorted(records) != sorted(base):
                        raise AssertionError(
                            "scenario %r delivered different batches than the "
                            "copying %s-default path" % (scenario, group))
                    row["identical_to_default"] = True
            results.append(row)
        return results
    finally:
        if tmp is not None:
            tmp.cleanup()


def summarize(results):
    """The last-line summary: copied-bytes-per-batch per path + reduction
    factors (None when a side is missing or the leased side copied nothing —
    reported as ``inf``-like ``None`` rather than a fake huge number)."""
    by_name = {r["scenario"]: r for r in results}
    summary = {"copies_summary": True}
    for group in ("wire", "memcache"):
        default = by_name.get(group + "-default")
        leased = by_name.get(group + "-leased")
        if not default or not leased:
            continue
        d, l = default["copied_bytes_per_batch"], leased["copied_bytes_per_batch"]
        summary[group] = {
            "default_copied_bytes_per_batch": d,
            "leased_copied_bytes_per_batch": l,
            "reduction_factor": round(d / l, 2) if l else None,
            "leased_strictly_below_default": l < d,
        }
        if default.get("rows_s") and leased.get("rows_s"):
            summary[group]["warm_rows_s_default"] = default["rows_s"]
            summary[group]["warm_rows_s_leased"] = leased["rows_s"]
    return summary


def _format_table(rows):
    cols = ("scenario", "batches", "rows", "seconds", "rows_s", "copied_bytes",
            "copied_bytes_per_batch", "leases_leaked")
    widths = [max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(str(r.get(c, "")).ljust(w)
                               for c, w in zip(cols, widths)))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench copies", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--rows", type=int, default=4096)
    parser.add_argument("--rows-per-group", type=int, default=64)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--files", type=int, default=2)
    parser.add_argument("--workers", type=int, default=2,
                        help="process-pool workers for the wire scenarios")
    parser.add_argument("--memcache-mb", type=int, default=256)
    parser.add_argument("--scenarios", nargs="*", default=list(SCENARIOS),
                        choices=SCENARIOS)
    parser.add_argument("--check", action="store_true",
                        help="assert leased scenarios deliver byte-identical "
                             "batches to their copying twins and leak nothing")
    parser.add_argument("--json", action="store_true", help="JSON lines output")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, --check, and a hard "
                             "assert that the leased paths copy strictly fewer "
                             "bytes per batch (correctness-only: wall-clock "
                             "numbers carry no claims on shared CI cores)")
    args = parser.parse_args(argv)

    if args.smoke:
        kwargs = dict(rows=512, rows_per_group=32, batch_size=16, files=2,
                      workers=2, memcache_mb=64, scenarios=SCENARIOS,
                      check=True)
    else:
        kwargs = dict(rows=args.rows, rows_per_group=args.rows_per_group,
                      batch_size=args.batch_size, files=args.files,
                      workers=args.workers, memcache_mb=args.memcache_mb,
                      scenarios=tuple(args.scenarios), check=args.check)

    results = run_copies_bench(**kwargs)
    if args.json:
        for r in results:
            print(json.dumps(r))
    else:
        print(_format_table(results))
    summary = summarize(results)
    if args.smoke:
        for group in ("wire", "memcache"):
            s = summary.get(group)
            assert s and s["leased_strictly_below_default"], \
                "leased %s path did not copy strictly fewer bytes per batch " \
                "than the default path: %r" % (group, s)
    if kwargs["check"]:
        print("identity: leased scenarios delivered byte-identical batches")
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
