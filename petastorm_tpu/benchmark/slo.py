"""``petastorm-tpu-bench slo``: does the temporal plane catch a burn and name
the culprit — and what does arming it cost?

**The acceptance harness for the ISSUE-12 SLO/anomaly engine.** Two parts:

- ``breach`` scenario: the :class:`~petastorm_tpu.io.latencyfs.CloudLatencyFS`
  remote-tail injection (the same bottleneck the attribution bench uses)
  behind a loader whose step-p99 SLO was calibrated against a CLEAN run of
  the identical workload (threshold = 3× the clean windowed p99 — the bench
  carries no magic milliseconds). The injected run must trip **exactly one**
  debounced ``slo_breach`` alert, and the alert's attached attribution
  snapshot must name ``io.remote`` as the critical-path culprit — the alert
  names the site, not just the symptom.
- ``overhead`` arm: the same thread-pool workload with the WHOLE plane armed
  (metrics registry + a live Reporter sampling timelines on its cadence + the
  SLO engine evaluating every window) vs fully disarmed, over a randomized
  epoch schedule (strict alternation couples an arm to host load drift),
  comparing best-of-epoch envelopes. Measured ≤1% on a quiet host — the
  acceptance target — and asserted at a 20% ceiling because shared CI cores
  jitter far more than the instrument. Identical delivered row sets are
  asserted in both arms.

The last stdout line is a one-line JSON summary for BENCH artifacts.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import tempfile
import time


def _make_store(root, files=3, rows_per_file=256):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(23)
    for i in range(files):
        pq.write_table(
            pa.table({
                "id": np.arange(rows_per_file, dtype=np.int64)
                + i * rows_per_file,
                "x": rng.random(rows_per_file),
                "y": rng.random(rows_per_file),
            }),
            os.path.join(root, "part-%02d.parquet" % i),
            # 4 row groups per file: enough distinct reads that the injected
            # tail spans several consumer windows
            row_group_size=max(32, rows_per_file // 4))
    return files * rows_per_file


def _drain_with_windows(reader, registry, batch_size=64, sample_every=1,
                        **loader_kwargs):
    """Drain one epoch, sampling the registry's timelines every
    ``sample_every`` delivered batches (a deterministic cadence — the bench
    must not depend on a timer thread winning races on loaded CI hosts). The
    host queue is kept SHORT so the producer's reads spread across consumer
    windows instead of all landing in the first one.
    Returns ``(loader, delivered_rows)``."""
    from petastorm_tpu.loader import DataLoader

    rows = 0
    loader_kwargs.setdefault("host_queue_size", 2)
    with DataLoader(reader, batch_size, to_device=False,
                    metrics=registry, **loader_kwargs) as loader:
        for i, batch in enumerate(loader):
            rows += len(batch["id"])
            if (i + 1) % sample_every == 0:
                registry.sample_timelines()
        registry.sample_timelines()
    return loader, rows


_STEP_METRIC = 'ptpu_pipeline_stage_seconds{stage="read"}'


def _clean_p99(workdir, files):
    """Windowed step p99 of the CLEAN (no injection) workload — the SLO
    calibration baseline."""
    from petastorm_tpu.obs.metrics import MetricsRegistry
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "clean")
    os.makedirs(root)
    total = _make_store(root, files=files)
    registry = MetricsRegistry()
    # workers_count=1 (here AND in the breach run): with 2 workers, reads
    # overlap and some reader.next calls return instantly from the ready
    # queue — legitimate recovery windows that re-arm the debounce and turn
    # "exactly one alert" into a race. Serialized reads make every window's
    # read observation carry the (injected) latency.
    reader = make_batch_reader(
        "file://" + root, num_epochs=1, workers_count=1,
        io_options=dict(readahead=False))
    _loader, rows = _drain_with_windows(reader, registry)
    assert rows == total, (rows, total)
    p99s = [p["p99"] for p in registry.timeline(_STEP_METRIC)
            if p.get("count")]
    assert p99s, "clean run produced no step windows"
    return max(p99s)


def scenario_breach(workdir, smoke):
    """Injected remote tail → exactly ONE debounced slo_breach naming
    io.remote."""
    import pyarrow.fs as pafs

    from petastorm_tpu.io.latencyfs import CloudLatencyFS
    from petastorm_tpu.obs.metrics import MetricsRegistry
    from petastorm_tpu.obs.slo import SloEngine, SloSpec
    from petastorm_tpu.reader import make_batch_reader

    files = 2 if smoke else 4
    threshold = 3.0 * _clean_p99(workdir, files)

    root = os.path.join(workdir, "breach")
    os.makedirs(root)
    total = _make_store(root, files=files)
    fs = CloudLatencyFS(pafs.LocalFileSystem(), seed=11,
                        base_latency_s=0.02, tail_fraction=0.3,
                        tail_multiplier=6.0)
    registry = MetricsRegistry()
    spec = SloSpec(name="loader-step-p99", metric=_STEP_METRIC, stat="p99",
                   op="<=", threshold=threshold, breach_windows=2,
                   min_count=1)
    engine = SloEngine(specs=[spec], registry=registry)
    engine.attach(registry.timeline_store())
    reader = make_batch_reader(
        "file://" + root, filesystem=fs, num_epochs=1, workers_count=1,
        provenance=True,
        io_options=dict(readahead=False,
                        remote=dict(enabled=True, hedge=False)))
    # the engine needs the loader's attribution; wire it through slos= so the
    # loader binds attribution_report for us
    from petastorm_tpu.loader import DataLoader

    rows = 0
    with DataLoader(reader, 64, to_device=False, metrics=registry,
                    slos=engine, host_queue_size=2) as loader:
        for i, batch in enumerate(loader):
            rows += len(batch["id"])
            registry.sample_timelines()
        registry.sample_timelines()
    assert rows == total, (rows, total)
    alerts = engine.alerts()
    assert len(alerts) == 1, (
        "expected exactly one debounced breach, got %d: %s"
        % (len(alerts), [a.name for a in alerts]))
    alert = alerts[0]
    assert alert.cause == "slo_breach", alert.cause
    assert alert.windows >= spec.breach_windows, alert.windows
    assert alert.attribution is not None, "alert carries no attribution"
    ok_culprit = alert.culprit == "io.remote"
    return {
        "delivered_rows": rows,
        "threshold_s": round(threshold, 6),
        "alert_value_s": alert.value,
        "alert_windows": alert.windows,
        "culprit": alert.culprit,
        "ok": ok_culprit,
    }, ([] if ok_culprit else
        ["breach alert blamed %r, expected io.remote (slow shares: %s)"
         % (alert.culprit, (alert.attribution or {}).get("slow_share"))])


def measure_overhead(workdir, epochs=5):
    """BEST rows/s with the temporal plane fully ARMED (metrics + Reporter
    sampling timelines on its cadence + SLO engine per window) vs fully OFF,
    randomized epoch order, plus row-set identity. Returns
    ``(off_best, on_best, overhead_fraction)``."""
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.obs.export import Reporter
    from petastorm_tpu.obs.metrics import MetricsRegistry
    from petastorm_tpu.obs.slo import SloEngine, SloSpec
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "overhead")
    os.makedirs(root)
    _make_store(root, files=3)
    jsonl = os.path.join(root, "stats.jsonl")

    def one_epoch(armed):
        reader = make_batch_reader("file://" + root, num_epochs=1,
                                   workers_count=2)
        ids = []
        if armed:
            registry = MetricsRegistry()
            engine = SloEngine(
                specs=[SloSpec(name="step-p99", metric=_STEP_METRIC,
                               stat="p99", op="<=", threshold=60.0)],
                registry=registry)
            engine.attach(registry.timeline_store())
            t0 = time.perf_counter()
            with Reporter(registry=registry, interval_s=0.05,
                          jsonl_path=jsonl):
                with DataLoader(reader, 64, to_device=False,
                                metrics=registry, slos=engine) as loader:
                    for batch in loader:
                        ids.extend(int(v) for v in batch["id"])
            dt = time.perf_counter() - t0
        else:
            t0 = time.perf_counter()
            with DataLoader(reader, 64, to_device=False) as loader:
                for batch in loader:
                    ids.extend(int(v) for v in batch["id"])
            dt = time.perf_counter() - t0
        return len(ids) / dt, sorted(ids)

    one_epoch(False)  # warmup
    arms = [False] * epochs + [True] * epochs
    random.Random(43).shuffle(arms)
    off, on = [], []
    ids_off = ids_on = None
    for arm in arms:
        rate, ids = one_epoch(arm)
        (on if arm else off).append(rate)
        if arm:
            ids_on = ids
        else:
            ids_off = ids
    assert ids_off == ids_on, "the armed plane changed the delivered row set"
    print("overhead medians: off %.0f vs armed %.0f rows/s"
          % (statistics.median(off), statistics.median(on)))
    off_best, on_best = max(off), max(on)
    return off_best, on_best, max(0.0, 1.0 - on_best / off_best)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench slo", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny store, hard assertions, 20%% "
                             "overhead ceiling")
    parser.add_argument("--skip-overhead", action="store_true",
                        help="skip the armed/disarmed throughput arms")
    args = parser.parse_args(argv)

    failures = []
    with tempfile.TemporaryDirectory(prefix="ptpu-slo-") as workdir:
        breach, breach_failures = scenario_breach(workdir, smoke=args.smoke)
    failures.extend(breach_failures)
    print("breach scenario: one %s alert after %d windows, value %.1fms vs "
          "threshold %.1fms, culprit %s (%s)"
          % ("slo_breach", breach["alert_windows"],
             breach["alert_value_s"] * 1e3, breach["threshold_s"] * 1e3,
             breach["culprit"], "OK" if breach["ok"] else "WRONG"))

    overhead = None
    if not args.skip_overhead:
        with tempfile.TemporaryDirectory(prefix="ptpu-slo-") as workdir:
            off_best, on_best, overhead = measure_overhead(
                workdir, epochs=5 if args.smoke else 9)
        print("overhead: plane off %.0f rows/s vs armed %.0f rows/s "
              "best-of-epochs (delta %.2f%%; acceptance target <=1%% on a "
              "quiet host)" % (off_best, on_best, 100 * overhead))
        if args.smoke and overhead > 0.20:
            failures.append("temporal-plane overhead %.1f%% exceeds the 20%% "
                            "smoke ceiling" % (100 * overhead))

    summary = {"bench": "slo", "breach": breach,
               "overhead_fraction": None if overhead is None
               else round(overhead, 4),
               "failures": failures}
    print(json.dumps(summary, ensure_ascii=False))
    if failures:
        for failure in failures:
            print("FAIL:", failure)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
