"""Process-pool WIRE micro-benchmark: socket-pickle vs socket-arrow vs shm slabs.

Measures exactly the transport hop the shared-memory ring was built to remove
(docs/performance.md): a pool child produces a tagged columnar payload of a given
size, the parent consumes it through the configured wire, and the score is
consumer-side payload MB/s. The payload copy counts per wire are structural, not
measured:

====================  =======================================================
wire                  full-payload copies (child serialize → usable batch)
====================  =======================================================
pickle / arrow        3 — socket send (kernel), ``recv_bytes`` allocation,
                      writable-contract copy of the read-only reconstruction
shm / shm-arrow       2 — child's write into the slab, writable-contract copy
shm-view variants     1 — child's write into the slab (batches are delivered
                      as read-only zero-copy slab views)
====================  =======================================================

Run it as ``petastorm-tpu-bench wire`` (or ``python -m petastorm_tpu.benchmark.cli
wire``); ``--check`` adds correctness assertions on every received payload, and
``--smoke`` is the CI preset — tiny payloads, every wire, correctness only, no
throughput claims (CI machines share cores; the MB/s column is still printed for
the curious). A perf run wants ≥1 MB payloads: below that the per-item socket
round-trip dominates and every wire measures the same dispatch overhead.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from petastorm_tpu.serializers import SHM_LEASE_KEY

#: structural full-payload copy counts per wire (see module docstring)
WIRE_COPIES = {
    "pickle": 3,
    "arrow": 3,
    "shm": 2,
    "shm-pickle": 2,
    "shm-arrow": 2,
    "shm-view": 1,
    "shm-pickle-view": 1,
    "shm-arrow-view": 1,
}

DEFAULT_WIRES = ("pickle", "arrow", "shm", "shm-arrow")


class _PayloadWorker:
    """Pool worker producing one tagged columnar payload of ``nbytes`` (picklable;
    runs in the clean child interpreters). The fill is a cheap deterministic
    function of the item index so ``--check`` can verify every byte arrived."""

    def __call__(self, item):
        nbytes, idx = item
        return (0, idx, {"x": np.full((nbytes,), idx % 251, np.uint8)})


def expected_payload(nbytes, idx):
    return np.full((nbytes,), idx % 251, np.uint8)


def _measure_one(wire, nbytes, items, workers, warmup, check, timeout_s,
                 slab_bytes=None):
    from petastorm_tpu.plan import EpochPlan
    from petastorm_tpu.workers import ProcessExecutor

    plan = EpochPlan([(nbytes, i) for i in range(warmup + items)], num_epochs=1)
    seen = 0
    with ProcessExecutor(workers_count=workers, results_queue_size=4,
                         results_timeout_s=timeout_s, serializer=wire,
                         shm_slab_bytes=slab_bytes) as ex:
        ex.start(_PayloadWorker(), plan)
        t0 = time.perf_counter() if warmup == 0 else None
        for _epoch, idx, columns in ex.results():
            lease = columns.pop(SHM_LEASE_KEY, None)
            if check:
                np.testing.assert_array_equal(columns["x"],
                                              expected_payload(nbytes, idx))
            elif columns["x"].nbytes != nbytes:
                raise AssertionError("payload size mismatch on wire %r" % wire)
            if lease is not None:
                lease.release()  # view wire: hand the slab back promptly
            seen += 1
            if seen == warmup:
                t0 = time.perf_counter()
        elapsed = time.perf_counter() - (t0 if t0 is not None else time.perf_counter())
        wire_stats = ex.wire_stats()
    if seen != warmup + items:
        raise AssertionError("wire %r delivered %d of %d items"
                             % (wire, seen, warmup + items))
    measured = seen - warmup
    return {
        "wire": wire,
        "payload_mb": round(nbytes / 1e6, 3),
        "items": measured,
        "seconds": round(elapsed, 4),
        "mb_s": round(measured * nbytes / 1e6 / elapsed, 1) if elapsed > 0 else None,
        "items_s": round(measured / elapsed, 1) if elapsed > 0 else None,
        "payload_copies": WIRE_COPIES[wire],
        "shm_fallbacks": wire_stats.get("shm_fallbacks", 0),
        "shm_unavailable": bool(wire_stats.get("shm_unavailable", 0)),
        "checked": bool(check),
    }


def run_wire_bench(sizes, items=32, wires=DEFAULT_WIRES, workers=2, warmup=4,
                   check=False, timeout_s=120.0, slab_bytes=None):
    """One row dict per (wire, size): MB/s, items/s, structural copy count, and
    the shm fallback/degradation gauges. Sizes are payload bytes."""
    rows = []
    for nbytes in sizes:
        for wire in wires:
            rows.append(_measure_one(wire, int(nbytes), items, workers, warmup,
                                     check, timeout_s, slab_bytes=slab_bytes))
    return rows


def _format_table(rows):
    header = ("wire", "payload_mb", "mb_s", "items_s", "payload_copies",
              "shm_fallbacks")
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in header]
    lines = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append("  ".join(str(r[h]).ljust(w) for h, w in zip(header, widths)))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench wire", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--sizes-mb", type=float, nargs="*", default=[0.25, 1.0, 8.0],
                        help="payload sizes in MB (default: 0.25 1 8)")
    parser.add_argument("--items", type=int, default=32,
                        help="measured items per (wire, size)")
    parser.add_argument("--warmup", type=int, default=4,
                        help="untimed leading items (pool spawn, first-touch)")
    parser.add_argument("--wires", nargs="*", default=list(DEFAULT_WIRES),
                        choices=sorted(WIRE_COPIES),
                        help="wire formats to measure")
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--slab-mb", type=float, default=None,
                        help="override slab size (MB) for the shm wires")
    parser.add_argument("--check", action="store_true",
                        help="assert every received payload byte-exact")
    parser.add_argument("--json", action="store_true", help="JSON lines output")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny payloads, all wires incl. view "
                             "variants, --check, correctness-only")
    args = parser.parse_args(argv)

    if args.smoke:
        sizes = [64 << 10]
        wires = ["pickle", "arrow", "shm", "shm-arrow", "shm-view",
                 "shm-arrow-view"]
        items, warmup, check = 6, 2, True
    else:
        sizes = [int(mb * 1e6) for mb in args.sizes_mb]
        wires = args.wires
        items, warmup, check = args.items, args.warmup, args.check

    rows = run_wire_bench(sizes, items=items, wires=wires, workers=args.workers,
                          warmup=warmup, check=check,
                          slab_bytes=int(args.slab_mb * 1e6) if args.slab_mb else None)
    if args.json:
        for r in rows:
            print(json.dumps(r))
    else:
        print(_format_table(rows))
    degraded = [r for r in rows if r["shm_unavailable"]]
    if degraded:
        print("note: shared memory unavailable on this platform — shm rows "
              "measured the socket fallback", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
