"""``petastorm-tpu-bench attribution``: does the provenance plane name the
right culprit?

**The acceptance harness for the ISSUE-10 attribution report: inject a known
bottleneck, then assert the critical-path analyzer blames exactly that site.**

Scenarios (each a fresh tiny parquet store + loader run with
``provenance=True``):

- ``remote-tail`` — reads go through the seeded :class:`~petastorm_tpu.io
  .latencyfs.CloudLatencyFS` simulator with a fat injected base + tail
  latency (remote ranged-GET engine active, hedging off so the tail LANDS).
  The report's top critical-path stage must be ``io.remote``.
- ``slow-transform`` — a host ``TransformSpec`` sleeping per row group on a
  thread pool. Top stage must be ``transform``.
- ``wire-stall`` — a PROCESS pool (shm-view wire) with a chaos-plane latency
  fault at the ``wire.decode`` hook site. Top stage must be ``wire.decode``,
  and the contributing items' spans must carry ≥2 distinct pids — the proof
  that provenance merges across the process-pool boundary.

Every scenario additionally asserts the bookkeeping invariants: provenance
ids are exactly-once (each delivered row attributed to exactly one item, the
per-item attributed rows summing to the delivered total) and
``ptpu_lease_leaked_total`` moved by 0.

``--smoke`` (the CI preset) runs all three scenarios plus the OVERHEAD
measurement the acceptance bar requires: the same thread-pool workload with
provenance disabled vs enabled over a RANDOMIZED epoch schedule (strict
alternation couples an arm to the host's load drift), asserting identical
delivered row sets and comparing best-of-epoch envelopes (contention can
only lower an epoch). Measured ≤1% on a quiet host — the acceptance target
— and asserted at a ≤20% ceiling because shared CI cores jitter far more
than the instrument itself. The last stdout line is a one-line JSON summary
for BENCH artifacts.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import tempfile
import time


def _make_store(root, files=3, rows_per_file=256):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    for i in range(files):
        pq.write_table(
            pa.table({
                "id": np.arange(rows_per_file, dtype=np.int64)
                + i * rows_per_file,
                "x": rng.random(rows_per_file),
                "y": rng.random(rows_per_file),
            }),
            os.path.join(root, "part-%02d.parquet" % i),
            row_group_size=rows_per_file // 2)
    return files * rows_per_file


def _leaked_total():
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().counter("ptpu_lease_leaked_total").value


def _run_loader(reader, batch_size=64):
    """Drain one epoch through a host DataLoader with provenance on; returns
    ``(loader, delivered_rows, ids)``."""
    from petastorm_tpu.loader import DataLoader

    ids = []
    with DataLoader(reader, batch_size, to_device=False) as loader:
        for batch in loader:
            ids.extend(int(v) for v in batch["id"])
    return loader, len(ids), ids


def _assert_exactly_once(loader, delivered_rows, scenario):
    """Provenance bookkeeping invariants: attributed rows == delivered rows,
    each item charged once, quarantine ledger disjoint from delivery."""
    rec = loader.provenance
    per_item = {}
    for b in rec.batches():
        for epoch, ordinal, rows in (b["items"] or ()):
            per_item[(epoch, ordinal)] = per_item.get((epoch, ordinal), 0) + rows
    attributed = sum(per_item.values())
    assert attributed == delivered_rows, (
        "[%s] provenance attributed %d rows, delivered %d"
        % (scenario, attributed, delivered_rows))
    quarantined = {(e, o) for e, o, _a, _k in rec.quarantined()}
    assert not (quarantined & set(per_item)), (
        "[%s] items both delivered and quarantined: %s"
        % (scenario, quarantined & set(per_item)))
    assert rec.duplicate_absorbs == 0, (
        "[%s] duplicate child-record absorbs: %d"
        % (scenario, rec.duplicate_absorbs))


def scenario_remote_tail(workdir, smoke):
    """Injected remote GET tail → the report must blame ``io.remote``."""
    import pyarrow.fs as pafs

    from petastorm_tpu.io.latencyfs import CloudLatencyFS
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "remote")
    os.makedirs(root)
    total = _make_store(root, files=2 if smoke else 4)
    fs = CloudLatencyFS(pafs.LocalFileSystem(), seed=11,
                        base_latency_s=0.02, tail_fraction=0.3,
                        tail_multiplier=6.0)
    leaked0 = _leaked_total()
    reader = make_batch_reader(
        "file://" + root, filesystem=fs, num_epochs=1, workers_count=2,
        provenance=True,
        io_options=dict(readahead=False,
                        remote=dict(enabled=True, hedge=False)))
    loader, rows, _ids = _run_loader(reader)
    assert rows == total, (rows, total)
    report = loader.attribution_report()
    _assert_exactly_once(loader, rows, "remote-tail")
    assert _leaked_total() - leaked0 == 0, "leaked leases under remote-tail"
    return report, {"delivered_rows": rows}


def scenario_slow_transform(workdir, smoke):
    """A slow host transform → the report must blame ``transform``."""
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.transform import TransformSpec

    root = os.path.join(workdir, "transform")
    os.makedirs(root)
    total = _make_store(root, files=2 if smoke else 4)
    leaked0 = _leaked_total()
    reader = make_batch_reader(
        "file://" + root, num_epochs=1, workers_count=2,
        reader_pool_type="thread", provenance=True,
        transform_spec=TransformSpec(_sleepy_transform))
    loader, rows, _ids = _run_loader(reader)
    assert rows == total, (rows, total)
    report = loader.attribution_report()
    _assert_exactly_once(loader, rows, "slow-transform")
    assert _leaked_total() - leaked0 == 0, "leaked leases under slow-transform"
    return report, {"delivered_rows": rows}


def _sleepy_transform(df):
    time.sleep(0.04)  # the injected bottleneck: ~40ms of host transform per group
    return df


def scenario_wire_stall(workdir, smoke):
    """Chaos latency at the wire.decode hook on a process pool → the report
    must blame ``wire.decode`` AND the item spans must span ≥2 pids."""
    from petastorm_tpu import chaos
    from petastorm_tpu.chaos.plan import FaultPlan, FaultRule
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "wire")
    os.makedirs(root)
    total = _make_store(root, files=2 if smoke else 4)
    leaked0 = _leaked_total()
    plan = FaultPlan([FaultRule("wire.decode", "latency", every=1,
                                latency_s=0.05)], seed=5)
    with chaos.armed(plan):
        # readahead off: the scenario isolates WIRE attribution — child-side
        # background reads would otherwise compete with the injected stall
        # for the slow-decile share on loaded hosts
        reader = make_batch_reader(
            "file://" + root, num_epochs=1, workers_count=2,
            reader_pool_type="process", wire_serializer="shm-view",
            provenance=True, io_options=dict(readahead=False))
        loader, rows, _ids = _run_loader(reader)
    assert rows == total, (rows, total)
    report = loader.attribution_report()
    _assert_exactly_once(loader, rows, "wire-stall")
    assert _leaked_total() - leaked0 == 0, "leaked leases under wire-stall"
    pids = {sp["pid"] for rec in loader.provenance.items().values()
            for sp in rec["spans"]}
    assert len(pids) >= 2, (
        "wire-stall item spans stayed in one process (%s) — the pool-pid "
        "provenance merge is broken" % pids)
    return report, {"delivered_rows": rows, "span_pids": len(pids)}


SCENARIOS = (
    ("remote-tail", scenario_remote_tail, "io.remote"),
    ("slow-transform", scenario_slow_transform, "transform"),
    ("wire-stall", scenario_wire_stall, "wire.decode"),
)


def measure_overhead(workdir, epochs=5):
    """BEST rows/s of the same thread-pool workload with provenance OFF vs
    ON (alternating epochs so host noise hits both arms; best-of like the
    trend gate — contention can only LOWER an epoch, so the envelopes are
    the comparable numbers and a shared-CI co-tenant cannot fake an
    overhead), plus row-set identity. Returns
    ``(off_best, on_best, overhead_fraction)``; the median delta is printed
    too for quiet-host runs."""
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    root = os.path.join(workdir, "overhead")
    os.makedirs(root)
    _make_store(root, files=3)

    def one_epoch(provenance):
        reader = make_batch_reader("file://" + root, num_epochs=1,
                                   workers_count=2,
                                   provenance=True if provenance else None)
        ids = []
        t0 = time.perf_counter()
        with DataLoader(reader, 64, to_device=False) as loader:
            for batch in loader:
                ids.extend(int(v) for v in batch["id"])
        return len(ids) / (time.perf_counter() - t0), sorted(ids)

    one_epoch(False)  # warmup: imports, footer parses, allocator
    # RANDOMIZED arm order (fixed seed): strict off-then-on alternation
    # couples each arm to a phase of the host's load/frequency drift and
    # measured a phantom 20% "overhead" that a shuffled schedule dissolves
    # to noise (±5% here)
    arms = [False] * epochs + [True] * epochs
    random.Random(41).shuffle(arms)
    off, on = [], []
    ids_off = ids_on = None
    for arm in arms:
        rate, ids = one_epoch(arm)
        if arm:
            on.append(rate)
            ids_on = ids
        else:
            off.append(rate)
            ids_off = ids
    assert ids_off == ids_on, "provenance changed the delivered row set"
    print("overhead medians: off %.0f vs on %.0f rows/s"
          % (statistics.median(off), statistics.median(on)))
    off_best = max(off)
    on_best = max(on)
    return off_best, on_best, max(0.0, 1.0 - on_best / off_best)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench attribution", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny stores, all scenarios + the "
                             "overhead measurement, hard culprit assertions")
    parser.add_argument("--scenario", choices=[s[0] for s in SCENARIOS],
                        default=None, help="run one scenario only")
    parser.add_argument("--skip-overhead", action="store_true",
                        help="skip the provenance on/off throughput arms")
    args = parser.parse_args(argv)

    results = {}
    failures = []
    for name, fn, culprit in SCENARIOS:
        if args.scenario and name != args.scenario:
            continue
        with tempfile.TemporaryDirectory(prefix="ptpu-attr-") as workdir:
            report, extra = fn(workdir, smoke=args.smoke)
        # the report's culprit is the SLOW-decile top (report.slow_top): an
        # injected bottleneck inflates the slow batches, while one-off costs
        # (pool-child cold start) can dominate the overall totals
        top = report.slow_top
        ok = top == culprit
        if not ok:
            failures.append("%s: expected culprit %r, got %r (slow shares: %s)"
                            % (name, culprit, top, report.slow_share))
        print("== %s ==" % name)
        print(report.render())
        print("expected culprit: %-12s report culprit: %-12s %s"
              % (culprit, top, "OK" if ok else "WRONG"))
        results[name] = {"culprit": top, "top_stage": report.top_stage,
                         "expected": culprit, "ok": ok,
                         "slow_share": report.slow_share,
                         "step_p99_s": report.step_p99_s, **extra}

    overhead = None
    if not args.scenario and not args.skip_overhead:
        with tempfile.TemporaryDirectory(prefix="ptpu-attr-") as workdir:
            off_best, on_best, overhead = measure_overhead(
                workdir, epochs=5 if args.smoke else 9)
        print("overhead: provenance off %.0f rows/s vs on %.0f rows/s "
              "best-of-epochs (delta %.2f%%; acceptance target <=1%% on a "
              "quiet host)" % (off_best, on_best, 100 * overhead))
        results["overhead"] = {"rows_per_s_off": round(off_best, 1),
                               "rows_per_s_on": round(on_best, 1),
                               "fraction": round(overhead, 4)}
        if args.smoke and overhead > 0.20:
            # the instrument itself costs ~perf_counter pairs per row group;
            # 20% headroom absorbs shared-CI noise, a real regression blows
            # straight through it
            failures.append("provenance overhead %.1f%% exceeds the 20%% "
                            "smoke ceiling" % (100 * overhead))

    summary = {"bench": "attribution", "scenarios": results,
               "failures": failures}
    print(json.dumps(summary, ensure_ascii=False))
    if failures:
        for failure in failures:
            print("FAIL:", failure)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
