"""Throughput CLI (reference petastorm/benchmark/cli.py, console script
``petastorm-throughput``): measure rows/sec of a reader config from the command line."""
from __future__ import annotations

import argparse


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("dataset_url")
    parser.add_argument("--batch", action="store_true",
                        help="use make_batch_reader (vanilla parquet) instead of make_reader")
    parser.add_argument("--pool-type", choices=["thread", "process", "dummy"],
                        default="thread")
    parser.add_argument("--workers-count", type=int, default=4)
    parser.add_argument("--schema-fields", nargs="*", default=None)
    parser.add_argument("--warmup-rows", type=int, default=1000)
    parser.add_argument("--measure-rows", type=int, default=10000)
    parser.add_argument("--loader", action="store_true",
                        help="measure through the JAX DataLoader (device feed + stage "
                             "counters + device-idle estimate) instead of the bare reader")
    parser.add_argument("--decode-on-device", action="store_true",
                        help="two-stage JPEG decode (requires --loader for the device half)")
    parser.add_argument("--loader-batch-size", type=int, default=256)
    args = parser.parse_args(argv)
    if args.decode_on_device and not args.loader:
        parser.error("--decode-on-device requires --loader: without the loader's device "
                     "half the reader yields stage-1 staging payloads, not images, and "
                     "the throughput number would be meaningless")

    from petastorm_tpu.benchmark.throughput import reader_throughput
    from petastorm_tpu.reader import make_batch_reader, make_reader

    factory = make_batch_reader if args.batch else make_reader
    kwargs = {}
    if args.decode_on_device:
        kwargs["decode_on_device"] = True
    reader = factory(args.dataset_url, schema_fields=args.schema_fields,
                     reader_pool_type=args.pool_type, workers_count=args.workers_count,
                     num_epochs=None, **kwargs)
    try:
        if args.loader:
            from petastorm_tpu.benchmark.throughput import loader_throughput
            from petastorm_tpu.loader import DataLoader

            loader = DataLoader(reader, args.loader_batch_size)
            bs = args.loader_batch_size
            result = loader_throughput(
                loader,
                warmup_batches=max(1, args.warmup_rows // bs),
                measure_batches=max(1, args.measure_rows // bs),
            )
        else:
            result = reader_throughput(reader, args.warmup_rows, args.measure_rows)
        print(result)
    finally:
        reader.stop()
        reader.join()


if __name__ == "__main__":
    main()
