"""Throughput CLI (reference petastorm/benchmark/cli.py, console script
``petastorm-throughput``): measure rows/sec of a reader config from the command line."""
from __future__ import annotations

import argparse


def _make_synthetic_step(target_ms):
    """A jitted device step calibrated to ~``target_ms`` per call on the CURRENT
    backend (a bf16 matmul chain — MXU work on TPU). The step folds a tiny
    dependency on the incoming batch so it cannot be reordered ahead of the
    transfer; operators probe "can this pipeline feed a step of X ms?" without
    writing model code."""
    import time

    import jax
    import jax.numpy as jnp

    x = jnp.ones((1024, 1024), jnp.bfloat16)

    @jax.jit
    def burn(n, seed, base):
        def body(_, a):
            return (a @ base) * jnp.bfloat16(1.0 / 1024.0)

        return jax.lax.fori_loop(0, n, body, base + seed)

    burn(8, jnp.bfloat16(0), x).block_until_ready()  # compile
    t0 = time.perf_counter()
    burn(8, jnp.bfloat16(0), x).block_until_ready()
    per_iter = (time.perf_counter() - t0) / 8.0
    n = max(1, int(round(target_ms / 1000.0 / max(per_iter, 1e-7))))

    import numpy as np

    def step(batch):
        seed = jnp.bfloat16(0)
        for v in batch.values():
            if hasattr(v, "dtype") and getattr(v.dtype, "kind", "O") in "biuf":
                if isinstance(v, np.ndarray):
                    # host batch (to_device=False paths): index on the HOST — an
                    # asarray here would ship the whole array to device per step
                    seed = jnp.bfloat16(float(v.ravel()[0]) * 1e-6)
                else:
                    # device array: one-element slice, no bulk transfer — the cheap
                    # dependency that orders the step after the batch's arrival
                    seed = v.ravel()[0].astype(jnp.bfloat16) * jnp.bfloat16(1e-6)
                break
        return burn(n, seed, x)

    return step


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "wire":
        # `petastorm-tpu-bench wire ...`: the process-pool wire micro-benchmark
        # (socket-pickle vs socket-arrow vs shm slabs) — see benchmark/wire.py
        from petastorm_tpu.benchmark import wire

        return wire.main(argv[1:])
    if argv and argv[0] == "io":
        # `petastorm-tpu-bench io ...`: the async read-path micro-benchmark
        # (cold sequential vs readahead vs readahead+coalesce vs memcache-warm)
        # — see benchmark/io.py
        from petastorm_tpu.benchmark import io as io_bench

        return io_bench.main(argv[1:])
    if argv and argv[0] == "remote":
        # `petastorm-tpu-bench remote ...`: the object-store read-path
        # benchmark under the CloudLatencyFS simulator (footer cache GET cut,
        # request hedging under injected tail, tiered warm-epoch speedup,
        # byte-identity) — see benchmark/remote.py
        from petastorm_tpu.benchmark import remote as remote_bench

        return remote_bench.main(argv[1:])
    if argv and argv[0] == "copies":
        # `petastorm-tpu-bench copies ...`: the copy-census micro-benchmark
        # (copying default path vs the ISSUE-6 leased path, bytes memcpy'd per
        # delivered batch + byte-identity) — see benchmark/copies.py
        from petastorm_tpu.benchmark import copies as copies_bench

        return copies_bench.main(argv[1:])
    if argv and argv[0] == "tabular":
        # `petastorm-tpu-bench tabular ...`: declarative tabular preprocessing
        # vs the equivalent per-batch pandas TransformSpec callable
        # (fused-vectorized rows/s, value identity, zero writable-copy census)
        # — see benchmark/tabular.py
        from petastorm_tpu.benchmark import tabular as tabular_bench

        return tabular_bench.main(argv[1:])
    if argv and argv[0] == "chaos":
        # `petastorm-tpu-bench chaos ...`: the chaos acceptance harness —
        # scripted kill/transient-IO/poison/corrupt/stall-heal scenarios
        # asserting delivered ∪ quarantined == plan with zero leaked leases
        # — see benchmark/chaos.py
        from petastorm_tpu.benchmark import chaos as chaos_bench

        return chaos_bench.main(argv[1:])
    if argv and argv[0] == "health":
        # `petastorm-tpu-bench health ...`: heartbeat-instrumentation overhead
        # (enabled vs disabled, plus beat/record primitive ns/op) — see
        # benchmark/health.py
        from petastorm_tpu.benchmark import health as health_bench

        return health_bench.main(argv[1:])
    if argv and argv[0] == "attribution":
        # `petastorm-tpu-bench attribution ...`: the provenance acceptance
        # harness — inject a known bottleneck (remote tail / slow transform /
        # wire stall) and assert the critical-path attribution report names
        # that culprit, with cross-pid span merge and the on/off overhead
        # measurement — see benchmark/attribution.py
        from petastorm_tpu.benchmark import attribution as attribution_bench

        return attribution_bench.main(argv[1:])
    if argv and argv[0] == "slo":
        # `petastorm-tpu-bench slo ...`: the temporal-plane acceptance harness
        # — calibrate a step-p99 SLO on a clean run, inject a CloudLatencyFS
        # remote tail, assert exactly one debounced slo_breach whose attached
        # attribution snapshot names io.remote, and measure the armed-vs-off
        # throughput delta — see benchmark/slo.py
        from petastorm_tpu.benchmark import slo as slo_bench

        return slo_bench.main(argv[1:])
    if argv and argv[0] == "autotune":
        # `petastorm-tpu-bench autotune ...`: the closed-loop controller's
        # acceptance harness — wrong initial knobs + injected latency must
        # converge live to >=80% of the hand-tuned arm, a consumer-bound run
        # must shrink the fleet under the chaos-style invariant, and a clean
        # run must see ZERO actuations at <=1% overhead — see
        # benchmark/autotune.py
        from petastorm_tpu.benchmark import autotune as autotune_bench

        return autotune_bench.main(argv[1:])
    if argv and argv[0] == "decompress":
        # `petastorm-tpu-bench decompress ...`: the compressed-page
        # pass-through acceptance harness — device-bound bytes/batch on
        # pass-through columns <=60% of the host-inflate twin, delivered-
        # batch byte identity, zero leaked leases, and the no-eligible-
        # columns store running classic with one warn-once degradation —
        # see benchmark/decompress.py
        from petastorm_tpu.benchmark import decompress as decompress_bench

        return decompress_bench.main(argv[1:])
    if argv and argv[0] == "shmcache":
        # `petastorm-tpu-bench shmcache ...`: the host-wide cache arena
        # acceptance harness — a second process attaches the first's mapped
        # warm set and must drain byte-identical batches with ZERO store
        # reads, >=90% arena hits, zero copy-census bytes on serves, and
        # host-wide resident bytes <=1.2x one process's warm set — see
        # benchmark/shmcache.py
        from petastorm_tpu.benchmark import shmcache as shmcache_bench

        return shmcache_bench.main(argv[1:])
    if argv and argv[0] == "tenants":
        # `petastorm-tpu-bench tenants ...`: the per-tenant accounting-plane
        # acceptance harness — two concurrent loaders on one host/arena, the
        # noisy tenant named by the usage report AND a per-tenant burn alert
        # (site + tenant), cross-tenant sums reconciled against the untagged
        # totals, tenant frame-header compat, and the tagged-vs-untagged
        # overhead arms — see benchmark/tenants.py
        from petastorm_tpu.benchmark import tenants as tenants_bench

        return tenants_bench.main(argv[1:])
    if argv and argv[0] == "fleet":
        # `petastorm-tpu-bench fleet ...`: the disaggregated data-service
        # acceptance harness — 3 trainers on one decode fleet vs 3 dedicated
        # pipelines (decode worker-seconds per delivered row cut >=2x),
        # mid-epoch detach+reattach watermark exactness, per-tenant QoS
        # naming the noisy neighbor, and a seeded link-death arm asserting
        # re-dispatch-not-quarantine — see benchmark/fleet.py
        from petastorm_tpu.benchmark import fleet as fleet_bench

        return fleet_bench.main(argv[1:])
    if argv and argv[0] == "diff":
        # `petastorm-tpu-bench diff run_a run_b`: regression forensics over
        # two trend entries — names WHICH site's critical-path self time
        # regressed ("rows/s -28%: io.remote self-time 2.3x") — see
        # petastorm_tpu/obs/diff.py
        from petastorm_tpu.obs import diff as diff_cli

        return diff_cli.main(argv[1:])
    if argv and argv[0] == "trend":
        # `petastorm-tpu-bench trend ...`: the CI throughput-regression gate —
        # median rows/s of a fixed synthetic workload appended to
        # BENCH_HISTORY.jsonl and compared against the stored median — see
        # benchmark/trend.py
        from petastorm_tpu.benchmark import trend as trend_bench

        return trend_bench.main(argv[1:])
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("dataset_url")
    parser.add_argument("--batch", action="store_true",
                        help="use make_batch_reader (vanilla parquet) instead of make_reader")
    parser.add_argument("--pool-type", choices=["thread", "process", "dummy"],
                        default="thread")
    parser.add_argument("--workers-count", type=int, default=4)
    parser.add_argument("--schema-fields", nargs="*", default=None)
    parser.add_argument("--warmup-rows", type=int, default=1000)
    parser.add_argument("--measure-rows", type=int, default=10000)
    parser.add_argument("--loader", action="store_true",
                        help="measure through the JAX DataLoader (device feed + stage "
                             "counters + device-idle estimate) instead of the bare reader")
    parser.add_argument("--decode-on-device", action="store_true",
                        help="two-stage JPEG decode (requires --loader for the device half)")
    parser.add_argument("--loader-batch-size", type=int, default=256)
    parser.add_argument("--trace", metavar="PATH", default=None,
                        help="write a chrome://tracing / Perfetto span trace of the "
                             "measured pipeline to PATH (requires --loader)")
    parser.add_argument("--report", action="store_true",
                        help="print the bottleneck analyzer's verdict (producer-"
                             "bound / wire-bound / consumer-bound, with stage "
                             "utilizations and p50/p90/p99 latencies) after the "
                             "measurement (requires --loader)")
    parser.add_argument("--overlap-step-ms", type=float, default=0.0,
                        help="overlap mode: keep the device busy with a calibrated "
                             "synthetic step of ~this many milliseconds per batch and "
                             "report the consumer's starvation as device idle (the "
                             "north-star metric) instead of drain-only rows/s; "
                             "requires --loader")
    args = parser.parse_args(argv)
    if args.decode_on_device and not args.loader:
        parser.error("--decode-on-device requires --loader: without the loader's device "
                     "half the reader yields stage-1 staging payloads, not images, and "
                     "the throughput number would be meaningless")
    if args.overlap_step_ms and not args.loader:
        parser.error("--overlap-step-ms requires --loader (the overlap runs on the "
                     "device batches the loader delivers)")
    if args.trace and not args.loader:
        parser.error("--trace requires --loader (the spans are the loader's "
                     "pipeline stages)")
    if args.report and not args.loader:
        parser.error("--report requires --loader (the analyzer reads the "
                     "loader's stage counters)")

    from petastorm_tpu.benchmark.throughput import reader_throughput
    from petastorm_tpu.reader import make_batch_reader, make_reader

    factory = make_batch_reader if args.batch else make_reader
    kwargs = {}
    if args.decode_on_device:
        kwargs["decode_on_device"] = True
    reader = factory(args.dataset_url, schema_fields=args.schema_fields,
                     reader_pool_type=args.pool_type, workers_count=args.workers_count,
                     num_epochs=None, **kwargs)
    try:
        if args.loader:
            from petastorm_tpu.benchmark.throughput import loader_throughput
            from petastorm_tpu.loader import DataLoader

            tracer = None
            if args.trace:
                from petastorm_tpu.trace import TraceRecorder

                tracer = TraceRecorder()
            loader_kwargs = {}
            if args.report:
                # per-stage histograms ride into the report's p50/p90/p99 lines;
                # a PRIVATE registry so the one-shot report never mixes with (or
                # leaks into) the process-wide default registry
                from petastorm_tpu.obs.metrics import MetricsRegistry

                loader_kwargs["metrics"] = MetricsRegistry()
            bs = args.loader_batch_size
            xfer0 = None
            if args.decode_on_device:
                from petastorm_tpu.ops.jpeg import transfer_byte_counters

                xfer0 = transfer_byte_counters()  # delta, not process-lifetime total
            try:
                # the with-block matters: an abandoned pipeline torn down at
                # interpreter exit can kill a daemon transfer thread mid C++
                # dispatch (observed: 'FATAL: exception not rethrown' abort)
                with DataLoader(reader, args.loader_batch_size,
                                trace=tracer, **loader_kwargs) as loader:
                    if args.overlap_step_ms:
                        from petastorm_tpu.benchmark.throughput import (
                            overlap_throughput,
                        )

                        step = _make_synthetic_step(args.overlap_step_ms)
                        result = overlap_throughput(
                            loader, step, step_repeats=1,
                            warmup_batches=max(1, args.warmup_rows // bs),
                            measure_batches=max(1, args.measure_rows // bs),
                        )
                    else:
                        result = loader_throughput(
                            loader,
                            warmup_batches=max(1, args.warmup_rows // bs),
                            measure_batches=max(1, args.measure_rows // bs),
                        )
            finally:
                if tracer is not None:
                    # dump in finally: the trace matters MOST when the run dies
                    # mid-measure (the spans up to the failure show where)
                    tracer.dump(args.trace)
            if xfer0 is not None:
                xfer = transfer_byte_counters()
                raw = xfer["raw"] - xfer0["raw"]
                shipped = xfer["shipped"] - xfer0["shipped"]
                if raw:
                    # printed as the shipped/raw RATIO — same semantics as the
                    # artifact key `coeff_bytes_shipped_ratio` (ADVICE r4: the old
                    # "x0.42 narrowing" phrasing read as a speedup factor)
                    print("coefficient transfer: shipped %.1f MB of %.1f MB raw "
                          "int16 (%.2f of raw shipped)"
                          % (shipped / 1e6, raw / 1e6, shipped / raw))
            if args.report:
                # stats cover the measured window (loader_throughput resets them)
                report = loader.bottleneck_report()
        else:
            result = reader_throughput(reader, args.warmup_rows, args.measure_rows)
        print(result)
        if args.report:
            print(report.render())
    finally:
        reader.stop()
        reader.join()


if __name__ == "__main__":
    main()
