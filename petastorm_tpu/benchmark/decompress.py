"""``petastorm-tpu-bench decompress`` — the compressed-page pass-through
acceptance harness (ISSUE 14).

Arms:

- ``passthrough``: a snappy-compressed fixed-width store read with
  ``pagedec=on`` through a device-bound ``DataLoader``. Measures the
  device-bound bytes per batch on the pass-through columns (compressed pages
  + page tables, from ``ptpu_pagedec_bytes_compressed_total``) against the
  host-inflate twin's raw array bytes, asserts the ≤60%-of-raw bar, byte
  identity of every delivered batch vs the classic arm (``--check``), and a
  zero ``ptpu_lease_leaked_total`` delta.
- ``classic``: the identical read with ``pagedec=off`` — the identity twin
  and the raw-bytes denominator.
- ``ineligible``: a store with no eligible column (strings + incompressible
  float noise): ``pagedec=on`` must degrade per column to the classic path
  with a single warn-once ``pagedec_ineligible`` degradation and no
  measurable rows/s overhead vs ``pagedec=off`` (asserted at a loose CI
  noise ceiling).

The last line is a one-line JSON document (``"bench": "decompress"``) for
scripts; ``--smoke`` enforces every acceptance bar (wired into ci.yml).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time


def _leaked_total():
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().counter("ptpu_lease_leaked_total").value


def _counter(name):
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().counter(name).value


def _make_store(root, rows=60_000, row_group_size=5_000, eligible=True,
                seed=7):
    """A deterministic parquet store. ``eligible=True`` writes compressible
    fixed-width columns (the realistic feature-table shape: quantized floats,
    low-cardinality categoricals, monotonic ids); ``eligible=False`` writes
    only shapes the classifier must refuse (strings, float noise)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(root)
    rng = np.random.default_rng(seed)
    n = rows
    if eligible:
        cols = {
            "feat": pa.array(np.repeat(rng.normal(size=-(-n // 64))
                                       .astype(np.float32), 64)[:n]),
            "quant": pa.array((rng.integers(0, 255, size=n) / 8.0)
                              .astype(np.float32)),
            "cat": pa.array(rng.integers(0, 17, size=n).astype(np.int64)),
            "id": pa.array(np.arange(n, dtype=np.int32)),
        }
    else:
        cols = {
            "s": pa.array(["row-%d-%d" % (i, i * 31 % 997) for i in range(n)]),
            "noise": pa.array(rng.normal(size=n)),  # f64 noise: no saving
        }
    pq.write_table(pa.table(cols), os.path.join(root, "part-0.parquet"),
                   compression="snappy", row_group_size=row_group_size)
    return n


def _drain(url, pagedec, batch_size, check=False):
    """One epoch through a device-bound loader; returns (rows, seconds,
    batches, delivered) — ``delivered`` only collected under ``check``."""
    import numpy as np

    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    delivered = []
    rows = 0
    batches = 0
    with make_batch_reader(url, reader_pool_type="thread", workers_count=1,
                           shuffle_row_groups=False,
                           io_options={"pagedec": pagedec}) as reader:
        with DataLoader(reader, batch_size, to_device=True,
                        last_batch="partial") as loader:
            t0 = time.perf_counter()
            for b in loader:
                batches += 1
                host = {k: np.asarray(v) for k, v in b.items()}
                rows += len(next(iter(host.values())))
                if check:
                    delivered.append(host)
            dt = time.perf_counter() - t0
    return rows, dt, batches, delivered


def run(workdir, batch_size=2048, rows=60_000, check=True, smoke=False):
    failures = []
    url = "file://" + workdir + "/eligible"
    total = _make_store(os.path.join(workdir, "eligible"), rows=rows)

    # classic twin first: identity target + the raw-bytes denominator
    leaked0 = _leaked_total()
    classic_rows, classic_s, classic_batches, classic_batches_data = _drain(
        url, "off", batch_size, check=check)
    comp0 = _counter("ptpu_pagedec_bytes_compressed_total")
    saved0 = _counter("ptpu_pagedec_bytes_saved_h2d_total")
    pages0 = _counter("ptpu_pagedec_pages_total")
    pt_rows, pt_s, pt_batches, pt_batches_data = _drain(
        url, "on", batch_size, check=check)
    leak_delta = _leaked_total() - leaked0
    shipped = _counter("ptpu_pagedec_bytes_compressed_total") - comp0
    saved = _counter("ptpu_pagedec_bytes_saved_h2d_total") - saved0
    pages = _counter("ptpu_pagedec_pages_total") - pages0

    if pt_rows != classic_rows:
        failures.append("row counts differ: classic %d vs pass-through %d"
                        % (classic_rows, pt_rows))
    if check:
        import numpy as np

        if len(classic_batches_data) != len(pt_batches_data):
            failures.append("batch counts differ under --check")
        else:
            for i, (a, b) in enumerate(zip(classic_batches_data,
                                           pt_batches_data)):
                for k in a:
                    if not np.array_equal(a[k], b[k]):
                        failures.append(
                            "delivered batch %d column %r differs from the "
                            "classic twin" % (i, k))
                        break
                else:
                    continue
                break
    # raw denominator: what the classic path would hand the device for
    # exactly the columns that passed through — shipped + saved IS that raw
    # volume (the saved counter is raw-minus-shipped per column), so columns
    # that declined (e.g. an incompressible id) don't flatter the ratio
    raw_total = shipped + saved
    raw_per_batch = raw_total / max(1, pt_batches)
    shipped_per_batch = shipped / max(1, pt_batches)
    ratio = shipped_per_batch / raw_per_batch if raw_per_batch else None
    if ratio is None:
        failures.append("no raw-bytes denominator measured")
    elif ratio > 0.60:
        failures.append(
            "pass-through device-bound bytes/batch %.0f is %.0f%% of the "
            "raw twin's %.0f — the <=60%% bar failed"
            % (shipped_per_batch, 100 * ratio, raw_per_batch))
    if shipped <= 0 or pages <= 0:
        failures.append("pass-through shipped no pages (did eligibility "
                        "classify the store away?)")
    if leak_delta:
        failures.append("ptpu_lease_leaked_total moved by %d" % leak_delta)

    # ineligible arm: classic fallback, warn-once, no measurable overhead
    inurl = "file://" + workdir + "/ineligible"
    _make_store(os.path.join(workdir, "ineligible"), rows=max(2000, rows // 6),
                eligible=False)
    from petastorm_tpu.obs.log import degradation_counts

    off_rows, off_s, _b, _d = _drain(inurl, "off", batch_size, check=False)
    ineligible0 = degradation_counts().get("pagedec_ineligible", 0)
    comp_in0 = _counter("ptpu_pagedec_bytes_compressed_total")
    on_rows, on_s, _b, _d = _drain(inurl, "on", batch_size, check=False)
    ineligible_hits = degradation_counts().get("pagedec_ineligible", 0) \
        - ineligible0
    if on_rows != off_rows:
        failures.append("ineligible arm delivered %d rows vs %d classic"
                        % (on_rows, off_rows))
    if _counter("ptpu_pagedec_bytes_compressed_total") != comp_in0:
        failures.append("ineligible arm still shipped compressed pages")
    off_rate = off_rows / off_s if off_s else 0.0
    on_rate = on_rows / on_s if on_s else 0.0
    # loose CI-noise ceiling; the design target is "no measurable overhead"
    if off_rate and on_rate < 0.5 * off_rate:
        failures.append(
            "pagedec=on on an ineligible store ran at %.0f rows/s vs "
            "%.0f classic (>2x overhead — the classifier is not cheap "
            "enough)" % (on_rate, off_rate))

    result = {
        "bench": "decompress",
        "rows": total,
        "classic_rows_s": round(classic_rows / classic_s, 1),
        "passthrough_rows_s": round(pt_rows / pt_s, 1),
        "raw_bytes_per_batch": int(raw_per_batch),
        "shipped_bytes_per_batch": int(shipped_per_batch),
        "h2d_ratio": round(ratio, 4) if ratio is not None else None,
        "bytes_saved_total": int(saved),
        "pages_shipped": int(pages),
        "byte_identity_checked": bool(check),
        "host_inflate_columns": int(
            _counter("ptpu_pagedec_host_inflate_columns_total")),
        "lease_leak_delta": int(leak_delta),
        "ineligible_classic_rows_s": round(off_rate, 1),
        "ineligible_pagedec_rows_s": round(on_rate, 1),
        "ineligible_degradations": int(ineligible_hits),
        "ok": not failures,
        "failures": failures,
    }
    return result, failures


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench decompress", description=__doc__)
    parser.add_argument("--rows", type=int, default=60_000)
    parser.add_argument("--batch-size", type=int, default=2048)
    parser.add_argument("--check", action="store_true", default=True,
                        help="assert delivered-batch byte identity vs the "
                             "classic twin (default on)")
    parser.add_argument("--no-check", dest="check", action="store_false")
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: smaller store, every acceptance bar "
                             "enforced (non-zero exit on failure)")
    args = parser.parse_args(argv)
    rows = 24_000 if args.smoke else args.rows
    workdir = tempfile.mkdtemp(prefix="ptpu-decompress-")
    try:
        result, failures = run(workdir, batch_size=args.batch_size,
                               rows=rows, check=args.check,
                               smoke=args.smoke)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    ratio = result["h2d_ratio"]
    print("pass-through: %d B/batch shipped vs %d B/batch raw (%s of raw), "
          "%d pages, %.1f MB saved; rows/s classic %.0f vs pass-through %.0f"
          % (result["shipped_bytes_per_batch"], result["raw_bytes_per_batch"],
             ("%.0f%%" % (100 * ratio)) if ratio is not None else "n/a",
             result["pages_shipped"], result["bytes_saved_total"] / 1e6,
             result["classic_rows_s"], result["passthrough_rows_s"]))
    print("ineligible store: classic %.0f rows/s vs pagedec=on %.0f rows/s "
          "(%d pagedec_ineligible degradation(s), all columns classic)"
          % (result["ineligible_classic_rows_s"],
             result["ineligible_pagedec_rows_s"],
             result["ineligible_degradations"]))
    for failure in failures:
        print("FAIL: %s" % failure)
    print(json.dumps(result))
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
