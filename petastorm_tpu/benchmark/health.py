"""Health-layer overhead micro-benchmark: heartbeats on vs off.

The ISSUE-5 acceptance bar is that heartbeat instrumentation enabled costs
≤1% of pipeline throughput. This benchmark measures it two ways:

1. **Primitive cost** — ``Heartbeat.beat`` / ``FlightRecorder.record`` /
   ``HealthMonitor.observe_worker`` in a tight loop (ns/op). The loader stamps
   a handful of beats per *batch* (not per row), so even a microsecond-scale
   beat is noise next to one row group of parquet decode.
2. **End-to-end** — the same synthetic-parquet loader run (thread pool,
   ``to_device=False``) with ``health=None`` vs ``health=HealthOptions(...)``,
   alternating A/B/A/B to cancel drift; the score is the enabled/disabled
   throughput ratio.

``--smoke`` is the CI preset: tiny dataset, asserts the two modes deliver
IDENTICAL row sets and that the enabled run produces a parseable health
report, prints the overhead ratio without asserting it (shared CI cores make
timing assertions flaky; the measured number lands in docs/observability.md).

Run as ``petastorm-tpu-bench health`` (or ``python -m
petastorm_tpu.benchmark.cli health``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def _write_dataset(root, files, rows_per_file):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    for i in range(files):
        base = i * rows_per_file
        table = pa.table({
            "id": np.arange(base, base + rows_per_file, dtype=np.int64),
            "x": rng.random(rows_per_file),
            "y": rng.integers(0, 1000, rows_per_file),
        })
        pq.write_table(table, os.path.join(root, "part_%03d.parquet" % i),
                       row_group_size=max(64, rows_per_file // 8))


def _run_epoch(root, batch_size, health):
    """One full pass; returns (rows, seconds, id checksum, report).

    Only the BATCH LOOP is timed: reader/pool construction, teardown and the
    on-demand health report are fixed costs amortized over a training run,
    and folding them into a sub-second benchmark epoch would report setup
    noise as per-row overhead."""
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    reader = make_batch_reader("file://" + root, num_epochs=1,
                               reader_pool_type="thread", workers_count=2)
    rows = 0
    checksum = 0
    report = None
    # last_batch="partial": every row is delivered, so the identity checksum
    # is order-independent (with "drop" the dropped tail depends on worker
    # completion order)
    with DataLoader(reader, batch_size, to_device=False, last_batch="partial",
                    health=health) as loader:
        t0 = time.perf_counter()
        for batch in loader:
            rows += len(batch["id"])
            checksum += int(batch["id"].sum())
        dt = time.perf_counter() - t0
        if health is not None:
            report = loader.health_report()
    return rows, dt, checksum, report


def _bench_primitives(iters):
    """ns/op for the three hot health primitives."""
    from petastorm_tpu.obs.flight import FlightRecorder
    from petastorm_tpu.obs.health import HealthMonitor, HealthOptions
    from petastorm_tpu.obs.metrics import MetricsRegistry

    monitor = HealthMonitor(HealthOptions(poll_interval_s=3600.0),
                            registry=MetricsRegistry())
    hb = monitor.register("bench", "worker")
    t0 = time.perf_counter()
    for _ in range(iters):
        hb.beat("working")
    beat_ns = (time.perf_counter() - t0) / iters * 1e9
    rec = FlightRecorder(1024)
    t0 = time.perf_counter()
    for i in range(iters):
        rec.record("span", name="read", dur_s=0.001)
    record_ns = (time.perf_counter() - t0) / iters * 1e9
    t0 = time.perf_counter()
    for _ in range(iters):
        monitor.observe_worker(0, 0.001)
    observe_ns = (time.perf_counter() - t0) / iters * 1e9
    return {"beat_ns": round(beat_ns, 1), "record_ns": round(record_ns, 1),
            "observe_worker_ns": round(observe_ns, 1)}


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-bench health", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--files", type=int, default=8)
    parser.add_argument("--rows-per-file", type=int, default=20_000)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--repeats", type=int, default=3,
                        help="A/B pairs per mode (alternated to cancel drift)")
    parser.add_argument("--prim-iters", type=int, default=200_000)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: tiny dataset, identity + health-report "
                             "assertions, no timing assertions")
    args = parser.parse_args(argv)
    if args.smoke:
        args.files, args.rows_per_file, args.repeats = 4, 2_000, 2
        args.prim_iters = 20_000

    from petastorm_tpu.obs.health import HealthOptions

    prims = _bench_primitives(args.prim_iters)
    print("primitives: beat %.0fns  flight.record %.0fns  observe_worker %.0fns"
          % (prims["beat_ns"], prims["record_ns"],
             prims["observe_worker_ns"]))

    with tempfile.TemporaryDirectory(prefix="ptpu-health-bench-") as root:
        _write_dataset(root, args.files, args.rows_per_file)

        def health_opts():
            # generous thresholds: the benchmark measures stamping cost, not
            # stall handling (nothing here should ever trip the watchdog)
            return HealthOptions(stall_threshold_s=300.0, poll_interval_s=1.0,
                                 flight_path=os.path.join(root, "flight.json"))

        off_rates = []
        on_rates = []
        checksums = set()
        report = None
        # warmups, one per mode: page cache, module imports, thread spin-up
        _run_epoch(root, args.batch_size, None)
        _run_epoch(root, args.batch_size, health_opts())
        for _ in range(args.repeats):
            rows, dt, ck, _ = _run_epoch(root, args.batch_size, None)
            off_rates.append(rows / dt)
            checksums.add((rows, ck))
            rows, dt, ck, report = _run_epoch(root, args.batch_size,
                                              health_opts())
            on_rates.append(rows / dt)
            checksums.add((rows, ck))

        # MEDIAN of per-epoch rates: on a shared/oversubscribed host (CI, this
        # 2-core container) single epochs swing ±30%, and a mean would let one
        # descheduled epoch report scheduler noise as instrumentation cost
        off_rps = float(np.median(off_rates))
        on_rps = float(np.median(on_rates))
        overhead = (off_rps - on_rps) / off_rps if off_rps else 0.0
        result = {
            "metric": "health_overhead_fraction",
            "value": round(overhead, 4),
            "unit": "fraction",
            "rows_per_sec_disabled": round(off_rps, 1),
            "rows_per_sec_enabled": round(on_rps, 1),
            **prims,
            "smoke": bool(args.smoke),
        }
        if args.smoke:
            # correctness, not timing: both modes deliver the same rows, and
            # the enabled run can introspect itself
            assert len(checksums) == 1, \
                "health on/off delivered different row sets: %s" % checksums
            assert report is not None and report["heartbeats"], report
            assert report["stalls_total"] == 0, report["stalls_total"]
            assert json.dumps(report, default=str)
            print("smoke: identical rows across modes; health report "
                  "parseable; %d heartbeat actors" % len(report["heartbeats"]))
        print(json.dumps(result))
        return 0


if __name__ == "__main__":
    sys.exit(main())
