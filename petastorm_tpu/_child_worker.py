"""Clean child-process entry point for :class:`petastorm_tpu.workers.ProcessExecutor`.

Children are started as ``python -m petastorm_tpu._child_worker <socket>`` — a fresh
interpreter that NEVER re-imports the user's ``__main__`` (unlike multiprocessing spawn/
forkserver, which fork-bombs unguarded scripts) and never forks a threaded parent
(deadlock hazard under JAX). This is the same design as the reference's
``exec_in_new_process`` bootstrap (petastorm/workers_pool/exec_in_new_process.py ~L20),
with ``multiprocessing.connection`` replacing ZeroMQ.

Protocol: parent sends sys.path, the serializer name (an ``shm``-family name is
followed by the slab-ring attach config — segment names + slab size), a health
config dict (``stack_dump_dir`` + ``ping_interval_s``, ISSUE 5), then the
pickled worker; the child answers ``("pid", pid)`` (ISSUE 7: the parent ties
the connection to its OS process — accept order is not spawn order — so the
stall-heal tier can kill the right hung child); then items. On the socket wire each item message is
``(item, hints)``; on the shm wire it is ``(slab_id_or_None, item, hints)`` —
the slab is the parent's grant for this item's result (None = ring starved,
serialize over the socket). ``hints`` are the driver's remaining claimed plan
items (ISSUE 4): the child hands them to ``worker.prefetch`` so its readahead
pool reads the NEXT row groups while the current one decodes. Child answers
``("ok", kind, nframes, trace_blob)`` followed by ``nframes`` raw frames from the
wire serializer (pickle-5 out-of-band buffers, Arrow IPC, or a slab descriptor — see
petastorm_tpu/serializers.py), or ``("exc", exception)``; ``None`` item = shut down.

Health piggyback (ISSUE 5): the child interleaves ``("hb", wall_ts)`` heartbeat
messages on the same pipe — one right after receiving each item (proves the
pipe delivered and the child is about to work) and one per ``ping_interval_s``
while idle in ``poll()`` — and the driver drains them before every result
header, stamping the child's heartbeat. A child hung inside ``worker(item)``
sends nothing, so its heartbeat age grows: exactly the stall signal. On
``stack_dump_dir`` the child registers ``faulthandler`` on ``SIGUSR1`` writing
all-thread stacks to ``<dir>/stacks-<pid>.txt``, which the parent signals and
collects into the flight record when the watchdog trips.

``trace_blob`` is the cross-process trace piggyback (ISSUE 3):
``(pid, wall_anchor, perf_anchor, [(name, t0, dur), ...])`` — the child's spans
around THIS item (``child.work`` = the worker call, ``child.serialize`` = wire
encode), with ``t0`` from the child's ``perf_counter`` and one (wall, perf)
anchor pair sampled at child start for clock alignment. Recording is two
``perf_counter`` pairs per ITEM (a row group, not a row) — noise next to the
worker's parquet IO/decode — so it is always on and the parent merges the spans
into its :class:`petastorm_tpu.trace.TraceRecorder` only when one is attached
(``set_trace``), discarding them otherwise.
"""
import os
import pickle
import sys
import time
from multiprocessing.connection import Client


def main():
    address = sys.argv[1]
    authkey = sys.stdin.buffer.read(32)
    # tenant adoption (ISSUE 18): BEFORE the transport dial, so the tcp hello
    # already carries the slug and every charge this child makes — tier bytes,
    # arena admits, worker seconds — bills the owning tenant. The parent set
    # PTPU_TENANT in our env at exec time; absent/invalid ⇒ untagged.
    from petastorm_tpu.obs import tenant as _tenant_mod

    _tenant_mod.attach_from_env()
    link_down = ()  # a dead pipe cannot heal: EOF/reset = parent gone
    if address.startswith("tcp:"):
        # framed tcp transport (ISSUE 15): the child dials the parent's hub
        # and REDIALS with jittered backoff on any link death — a healed link
        # surfaces as TransportLinkDown (caught by the work loop, which
        # discards the broken conversation and awaits the re-dispatch);
        # an unreachable parent surfaces as EOFError like a closed pipe.
        from petastorm_tpu.errors import TransportLinkDown
        from petastorm_tpu.transport.tcp import connect_child_tcp

        conn = connect_child_tcp(address, authkey)
        link_down = TransportLinkDown
    else:
        conn = Client(address, authkey=authkey)
    serializer = None
    worker = None
    # clock-alignment anchors: one wall/perf pair, sampled back to back so the
    # parent can map this child's perf_counter values onto the shared wall clock
    wall_anchor = time.time()
    perf_anchor = time.perf_counter()
    pid = os.getpid()
    try:
        # Bootstrap recvs are unbounded by design (GL-R001 disables below): the
        # parent sends every handshake message back-to-back right after accept,
        # and if it dies instead the closed pipe raises EOFError — handled.
        # parent's sys.path first, so the worker pickle can resolve user modules
        for entry in conn.recv():  # graftlint: disable=GL-R001 (bootstrap; EOF on parent death)
            if entry not in sys.path:
                sys.path.append(entry)
        from petastorm_tpu.serializers import make_serializer

        serializer_name = conn.recv()  # graftlint: disable=GL-R001 (bootstrap; EOF on parent death)
        serializer = make_serializer(serializer_name)
        shm_wire = serializer_name.startswith("shm")
        if shm_wire:
            slab_names, slab_bytes = conn.recv()  # graftlint: disable=GL-R001 (bootstrap; EOF on parent death)
            serializer.bind_slabs(slab_names, slab_bytes)
        health_cfg = conn.recv()  # graftlint: disable=GL-R001 (bootstrap; EOF on parent death)
        ping_s = float(health_cfg.get("ping_interval_s") or 0)
        dump_dir = health_cfg.get("stack_dump_dir")
        if dump_dir:
            # stall-evidence hook: SIGUSR1 → faulthandler dumps ALL thread
            # stacks (worker + its readahead IO threads) to a parent-readable
            # file; registration costs nothing until the watchdog signals
            import faulthandler
            import signal

            if hasattr(signal, "SIGUSR1"):
                try:
                    dump_file = open(
                        os.path.join(dump_dir, "stacks-%d.txt" % pid), "w")
                    faulthandler.register(signal.SIGUSR1, file=dump_file,
                                          all_threads=True)
                except OSError:
                    pass  # no dump file = driver stacks only, never a crash
        worker = conn.recv()  # graftlint: disable=GL-R001 (bootstrap; EOF on parent death)
        # pid ack: ties this connection to its OS process in the parent's
        # bookkeeping (accept order is not spawn order) — the heal tier kills
        # hung children by exactly this mapping (ISSUE 7)
        conn.send(("pid", pid))
        if hasattr(conn, "mark_ready"):
            # tcp steady state: transport heartbeats + chaos sites engage
            # only after the bootstrap handshake completed
            conn.mark_ready()
        # chaos bootstrap (ISSUE 7): a parent armed while spawning exports its
        # FaultPlan as PTPU_CHAOS_SPEC; in-child hook sites (child.item, plus
        # the worker's own reader.read/io.readahead) evaluate this process's
        # copy. in_child=True opts into the 'kill' action — os._exit mid-item,
        # exactly a crashed child.
        from petastorm_tpu import chaos as _chaos

        _chaos.arm_from_env(in_child=True)
        # host-wide cache arena (ISSUE 17): a parent that owns a mapped warm
        # set exports PTPU_ARENA_ATTACH; attaching here — before the first
        # item — means even a freshly RESPAWNED child's first read of a warm
        # piece maps shared footers/columns instead of refilling cold.
        # Failure-tolerant: attach trouble degrades warn-once inside resolve.
        from petastorm_tpu.io import arena as _arena_mod

        _arena_mod.attach_from_env()
        # provenance (ISSUE 10): children always record their per-item causal
        # spans (a handful of perf_counter pairs per row-group item — the same
        # always-on justification as the trace piggyback above) and ship them
        # in slot 5 of the trace blob; the parent merges them only when a
        # ProvenanceRecorder is attached, discarding otherwise.
        from petastorm_tpu.obs import provenance as _prov

        _prov.arm_child()
        prefetch = getattr(worker, "prefetch", None)
        while True:
          # one indent level for the whole conversation: a TcpTransport link
          # death ANYWHERE in it (item receive, result/exc send) lands in the
          # except at the bottom — the transport already redialed, the broken
          # conversation's result is discarded, and the loop waits for the
          # parent's re-dispatch. Pipe links never raise it (empty tuple).
          try:
            if ping_s:
                # idle heartbeat: prove liveness while waiting for work (the
                # driver drains these; they never interleave with result frames
                # because this thread is the only sender)
                while not conn.poll(ping_s):
                    conn.send(("hb", time.time()))
            # unbounded by design: waiting for the next item IS this process's
            # job; the parent's teardown closes the pipe (EOFError, handled) and
            # with a health config the ping loop above bounds each poll anyway
            msg = conn.recv()  # graftlint: disable=GL-R001 (parent teardown closes the pipe)
            if msg is None:
                return
            if isinstance(msg, tuple) and len(msg) == 2 and msg[0] == "ctl":
                # live knob control frame (ISSUE 14 satellite, PR 13's
                # declared leftover): the parent's KnobSet retune reaches
                # ALREADY-RUNNING children here instead of only ones spawned
                # after it. Unambiguous on the wire: item messages carry a
                # (piece, partition) tuple first, never a string. The ack
                # (applied values) is drained by the driver like heartbeats —
                # the autotune harness asserts a retune lands respawn-free.
                applied = {}
                for knob, value in (msg[1] or {}).items():
                    fn = getattr(worker, "apply_%s" % knob, None)
                    if fn is None:
                        continue
                    try:
                        applied[knob] = fn(value)
                    except Exception as e:  # noqa: BLE001 — a bad retune must not kill the child
                        from petastorm_tpu.obs.log import degradation

                        degradation("ctl_child_apply_failed",
                                    "pool-child knob %r apply failed: %s",
                                    knob, e)
                conn.send(("ctl_ack", applied))
                continue
            if ping_s:
                conn.send(("hb", time.time()))  # item received, about to work
            if shm_wire:
                slab_id, item, hints = msg
                serializer.set_slab(slab_id)
            else:
                item, hints = msg
            if hints and prefetch is not None:
                # issue the driver's claimed-next reads on this child's IO pool
                # before working the item — the prefetch itself never raises
                prefetch(hints)
            _prov.begin_item(item)
            prov_blob = None
            try:
                try:
                    t0 = time.perf_counter()
                    if _chaos.ACTIVE is not None:
                        _chaos.ACTIVE.hit("child.item", key=_chaos.item_key(item))
                    result = worker(item)
                    t1 = time.perf_counter()
                    kind, frames = serializer.serialize(result)
                    t2 = time.perf_counter()
                    # mirrored into the provenance record so the parent's
                    # wire.roundtrip span folds to wire overhead only (the
                    # finer reader/transform spans nest inside child.work)
                    _prov.add_span("child.work", t0, t1 - t0)
                    _prov.add_span("child.serialize", t1, t2 - t1)
                except Exception as e:  # noqa: BLE001 - ship to parent
                    try:
                        pickle.dumps(e)
                        conn.send(("exc", e))
                    except link_down:
                        raise  # to the conversation-level handler below
                    except Exception:  # unpicklable exception: reconstruct
                        conn.send(("exc", RuntimeError(
                            "%s: %s" % (type(e).__name__, e))))
                    continue
            finally:
                # end_item returns the piggyback blob (epoch, ordinal, spans,
                # annotations) — collected on EVERY exit path so a failed
                # attempt's context never bleeds into the next item (GL-O003)
                prov_blob = _prov.end_item()
            spans = [("child.work", t0, t1 - t0),
                     ("child.serialize", t1, t2 - t1)]
            conn.send(("ok", kind, len(frames),
                       (pid, wall_anchor, perf_anchor, spans, prov_blob)))
            for frame in frames:
                conn.send_bytes(frame)
          except link_down:
            # the link died but REDIALED (an unreachable parent raises
            # EOFError instead, handled with the pipe's below): whatever this
            # conversation was — a result half-sent, an item half-received —
            # is void; the parent's in-flight ledger re-dispatches it.
            continue
    except (EOFError, BrokenPipeError, ConnectionResetError):
        return
    finally:
        if worker is not None and hasattr(worker, "close"):
            try:
                worker.close()  # stop the readahead IO pool before exiting
            except Exception:  # noqa: BLE001 — teardown must reach conn.close
                pass  # graftlint: disable=GL-O002 (child exit path: nowhere left to report)
        if serializer is not None and hasattr(serializer, "close"):
            serializer.close()  # detach (never unlink) any attached slabs
        conn.close()


if __name__ == "__main__":
    main()
