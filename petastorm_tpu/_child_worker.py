"""Clean child-process entry point for :class:`petastorm_tpu.workers.ProcessExecutor`.

Children are started as ``python -m petastorm_tpu._child_worker <socket>`` — a fresh
interpreter that NEVER re-imports the user's ``__main__`` (unlike multiprocessing spawn/
forkserver, which fork-bombs unguarded scripts) and never forks a threaded parent
(deadlock hazard under JAX). This is the same design as the reference's
``exec_in_new_process`` bootstrap (petastorm/workers_pool/exec_in_new_process.py ~L20),
with ``multiprocessing.connection`` replacing ZeroMQ.

Protocol: parent sends sys.path, the serializer name (an ``shm``-family name is
followed by the slab-ring attach config — segment names + slab size), then the
pickled worker; then items. On the socket wire each item message is
``(item, hints)``; on the shm wire it is ``(slab_id_or_None, item, hints)`` —
the slab is the parent's grant for this item's result (None = ring starved,
serialize over the socket). ``hints`` are the driver's remaining claimed plan
items (ISSUE 4): the child hands them to ``worker.prefetch`` so its readahead
pool reads the NEXT row groups while the current one decodes. Child answers
``("ok", kind, nframes, trace_blob)`` followed by ``nframes`` raw frames from the
wire serializer (pickle-5 out-of-band buffers, Arrow IPC, or a slab descriptor — see
petastorm_tpu/serializers.py), or ``("exc", exception)``; ``None`` item = shut down.

``trace_blob`` is the cross-process trace piggyback (ISSUE 3):
``(pid, wall_anchor, perf_anchor, [(name, t0, dur), ...])`` — the child's spans
around THIS item (``child.work`` = the worker call, ``child.serialize`` = wire
encode), with ``t0`` from the child's ``perf_counter`` and one (wall, perf)
anchor pair sampled at child start for clock alignment. Recording is two
``perf_counter`` pairs per ITEM (a row group, not a row) — noise next to the
worker's parquet IO/decode — so it is always on and the parent merges the spans
into its :class:`petastorm_tpu.trace.TraceRecorder` only when one is attached
(``set_trace``), discarding them otherwise.
"""
import os
import pickle
import sys
import time
from multiprocessing.connection import Client


def main():
    address = sys.argv[1]
    authkey = sys.stdin.buffer.read(32)
    conn = Client(address, authkey=authkey)
    serializer = None
    worker = None
    # clock-alignment anchors: one wall/perf pair, sampled back to back so the
    # parent can map this child's perf_counter values onto the shared wall clock
    wall_anchor = time.time()
    perf_anchor = time.perf_counter()
    pid = os.getpid()
    try:
        # parent's sys.path first, so the worker pickle can resolve user modules
        for entry in conn.recv():
            if entry not in sys.path:
                sys.path.append(entry)
        from petastorm_tpu.serializers import make_serializer

        serializer_name = conn.recv()
        serializer = make_serializer(serializer_name)
        shm_wire = serializer_name.startswith("shm")
        if shm_wire:
            slab_names, slab_bytes = conn.recv()
            serializer.bind_slabs(slab_names, slab_bytes)
        worker = conn.recv()
        prefetch = getattr(worker, "prefetch", None)
        while True:
            msg = conn.recv()
            if msg is None:
                return
            if shm_wire:
                slab_id, item, hints = msg
                serializer.set_slab(slab_id)
            else:
                item, hints = msg
            if hints and prefetch is not None:
                # issue the driver's claimed-next reads on this child's IO pool
                # before working the item — the prefetch itself never raises
                prefetch(hints)
            try:
                t0 = time.perf_counter()
                result = worker(item)
                t1 = time.perf_counter()
                kind, frames = serializer.serialize(result)
                t2 = time.perf_counter()
            except Exception as e:  # noqa: BLE001 - ship to parent
                try:
                    pickle.dumps(e)
                    conn.send(("exc", e))
                except Exception:  # unpicklable exception: reconstruct
                    conn.send(("exc", RuntimeError("%s: %s" % (type(e).__name__, e))))
                continue
            spans = [("child.work", t0, t1 - t0),
                     ("child.serialize", t1, t2 - t1)]
            conn.send(("ok", kind, len(frames),
                       (pid, wall_anchor, perf_anchor, spans)))
            for frame in frames:
                conn.send_bytes(frame)
    except (EOFError, BrokenPipeError, ConnectionResetError):
        return
    finally:
        if worker is not None and hasattr(worker, "close"):
            try:
                worker.close()  # stop the readahead IO pool before exiting
            except Exception:  # noqa: BLE001 — teardown must reach conn.close
                pass
        if serializer is not None and hasattr(serializer, "close"):
            serializer.close()  # detach (never unlink) any attached slabs
        conn.close()


if __name__ == "__main__":
    main()
