"""Clean child-process entry point for :class:`petastorm_tpu.workers.ProcessExecutor`.

Children are started as ``python -m petastorm_tpu._child_worker <socket>`` — a fresh
interpreter that NEVER re-imports the user's ``__main__`` (unlike multiprocessing spawn/
forkserver, which fork-bombs unguarded user scripts) and never forks a threaded parent
(deadlock hazard under JAX). This is the same design as the reference's
``exec_in_new_process`` bootstrap (petastorm/workers_pool/exec_in_new_process.py ~L20),
with ``multiprocessing.connection`` replacing ZeroMQ.

Protocol: parent sends the pickled worker once, then items; child answers ("ok", result) or
("exc", exception); ``None`` item = shut down.
"""
import pickle
import sys
from multiprocessing.connection import Client


def main():
    address = sys.argv[1]
    authkey = sys.stdin.buffer.read(32)
    conn = Client(address, authkey=authkey)
    try:
        # parent's sys.path first, so the worker pickle can resolve user modules
        for entry in conn.recv():
            if entry not in sys.path:
                sys.path.append(entry)
        worker = conn.recv()
        while True:
            item = conn.recv()
            if item is None:
                return
            try:
                result = worker(item)
            except Exception as e:  # noqa: BLE001 - ship to parent
                try:
                    pickle.dumps(e)
                    conn.send(("exc", e))
                except Exception:  # unpicklable exception: reconstruct
                    conn.send(("exc", RuntimeError("%s: %s" % (type(e).__name__, e))))
                continue
            conn.send(("ok", result))
    except (EOFError, BrokenPipeError, ConnectionResetError):
        return
    finally:
        conn.close()


if __name__ == "__main__":
    main()
