"""Clean child-process entry point for :class:`petastorm_tpu.workers.ProcessExecutor`.

Children are started as ``python -m petastorm_tpu._child_worker <socket>`` — a fresh
interpreter that NEVER re-imports the user's ``__main__`` (unlike multiprocessing spawn/
forkserver, which fork-bombs unguarded user scripts) and never forks a threaded parent
(deadlock hazard under JAX). This is the same design as the reference's
``exec_in_new_process`` bootstrap (petastorm/workers_pool/exec_in_new_process.py ~L20),
with ``multiprocessing.connection`` replacing ZeroMQ.

Protocol: parent sends sys.path, the serializer name (an ``shm``-family name is
followed by the slab-ring attach config — segment names + slab size), then the
pickled worker; then items. On the socket wire each item message is the item itself;
on the shm wire it is ``(slab_id_or_None, item)`` — the parent's slab grant for this
item's result (None = ring starved, serialize over the socket). Child answers
``("ok", kind, nframes)`` followed by ``nframes`` raw frames from the wire serializer
(pickle-5 out-of-band buffers, Arrow IPC, or a slab descriptor — see
petastorm_tpu/serializers.py), or ``("exc", exception)``; ``None`` item = shut down.
"""
import pickle
import sys
from multiprocessing.connection import Client


def main():
    address = sys.argv[1]
    authkey = sys.stdin.buffer.read(32)
    conn = Client(address, authkey=authkey)
    serializer = None
    try:
        # parent's sys.path first, so the worker pickle can resolve user modules
        for entry in conn.recv():
            if entry not in sys.path:
                sys.path.append(entry)
        from petastorm_tpu.serializers import make_serializer

        serializer_name = conn.recv()
        serializer = make_serializer(serializer_name)
        shm_wire = serializer_name.startswith("shm")
        if shm_wire:
            slab_names, slab_bytes = conn.recv()
            serializer.bind_slabs(slab_names, slab_bytes)
        worker = conn.recv()
        while True:
            msg = conn.recv()
            if msg is None:
                return
            if shm_wire:
                slab_id, item = msg
                serializer.set_slab(slab_id)
            else:
                item = msg
            try:
                result = worker(item)
                kind, frames = serializer.serialize(result)
            except Exception as e:  # noqa: BLE001 - ship to parent
                try:
                    pickle.dumps(e)
                    conn.send(("exc", e))
                except Exception:  # unpicklable exception: reconstruct
                    conn.send(("exc", RuntimeError("%s: %s" % (type(e).__name__, e))))
                continue
            conn.send(("ok", kind, len(frames)))
            for frame in frames:
                conn.send_bytes(frame)
    except (EOFError, BrokenPipeError, ConnectionResetError):
        return
    finally:
        if serializer is not None and hasattr(serializer, "close"):
            serializer.close()  # detach (never unlink) any attached slabs
        conn.close()


if __name__ == "__main__":
    main()
