"""Hot-path timing rule: durations must come from a monotonic clock.

``time.time()`` is the WALL clock: NTP slews and steps move it mid-run, so a
duration computed as the difference of two ``time.time()`` samples can come out
wrong — or negative — exactly when a long pipeline run crosses a clock
adjustment. Every per-stage timer in this codebase (``PipelineStats``,
``TraceRecorder``, the slab ring's acquire wait, every benchmark window) uses
``time.perf_counter()`` for that reason; GL-O001 keeps it that way.

The rule flags a subtraction whose operands BOTH derive from ``time.time()``
(a direct call, or a name assigned from one in the same scope) — the
two-samples-of-the-wall-clock pattern that encodes a duration. Legitimate
wall-clock uses stay clean: timestamps for logs/artifacts, deadline arithmetic
(``time.time() + 10``), and comparisons against file mtimes (one operand is not
a wall-clock sample).
"""
from __future__ import annotations

import ast

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule
from petastorm_tpu.analysis.rules._astutil import attr_chain, walk_scope


def _wall_clock_aliases(ctx):
    """Dotted call chains that mean ``time.time`` in this file: the module form
    plus any ``from time import time [as x]`` binding."""
    aliases = {"time.time"}
    for node in ctx.by_type(ast.ImportFrom):
        if node.module == "time":
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or "time")
    for node in ctx.by_type(ast.Import):
        for a in node.names:
            if a.name == "time" and a.asname:
                aliases.add("%s.time" % a.asname)
    return aliases


def _scopes(ctx):
    """Module, every class body, and every function/method body — each is one
    name-resolution scope for the assigned-from-time.time() tracking (walked
    with the shared ``walk_scope`` helper, which stops at nested scopes)."""
    yield ctx.tree
    yield from ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


class WallClockDurationRule(Rule):
    """GL-O001: duration computed from ``time.time()`` samples."""

    rule_id = "GL-O001"
    severity = Severity.WARNING
    description = "time.time() used to compute a duration"
    fix_hint = ("use time.perf_counter() for durations: the wall clock is "
                "adjusted by NTP slews/steps mid-run, so time.time() deltas "
                "can be wrong or negative; keep time.time() for timestamps")

    def check(self, tree, ctx):
        aliases = _wall_clock_aliases(ctx)

        def is_wall_call(node):
            return isinstance(node, ast.Call) and attr_chain(node.func) in aliases

        for scope in _scopes(ctx):
            sampled = set()  # names assigned from a time.time() call in scope
            for node in walk_scope(scope):
                if isinstance(node, ast.Assign) and is_wall_call(node.value):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            sampled.add(target.id)

            def derives(node):
                return is_wall_call(node) or (
                    isinstance(node, ast.Name) and node.id in sampled)

            for node in walk_scope(scope):
                if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                        and derives(node.left) and derives(node.right):
                    yield ctx.finding(
                        self, node,
                        "duration computed from time.time() samples (wall "
                        "clock); use time.perf_counter()")
