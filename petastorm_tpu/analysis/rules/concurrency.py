"""Concurrency rules: lock discipline, blocking teardown, unmanaged threads.

These target the executor/loader layer (``workers.py``, ``loader.py``,
``reader.py``): classes mixing worker threads with shared mutable attributes,
where the classic latent bugs are a write that bypasses the lock every other
access holds, an untimed ``Queue.get()``/``Thread.join()`` on a shutdown path
(the 300s teardown hangs of VERDICT r4), and threads that outlive the process
because nobody daemonized or joined them.
"""
from __future__ import annotations

import ast
import re

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule
from petastorm_tpu.analysis.rules._astutil import (
    attr_chain,
    call_kwarg,
    self_attr,
    walk_scope,
)

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}
#: types that synchronize internally — mutating them without the class lock is
#: fine (Event.set/clear, Queue.put/get, Semaphore.release are all thread-safe)
_SELF_SYNC_CTORS = {"threading.Event", "Event", "threading.Semaphore",
                    "threading.BoundedSemaphore", "Semaphore",
                    "queue.Queue", "Queue", "queue.SimpleQueue", "SimpleQueue",
                    "queue.LifoQueue", "queue.PriorityQueue",
                    "multiprocessing.Queue", "mp.Queue"}
_THREAD_CTORS = {"threading.Thread", "Thread"}
_QUEUE_CTORS = {"queue.Queue", "Queue", "queue.SimpleQueue", "SimpleQueue",
                "multiprocessing.Queue", "mp.Queue", "queue.LifoQueue",
                "queue.PriorityQueue"}
#: method calls that mutate their receiver in place (list/deque/dict/set API)
_MUTATORS = {"append", "extend", "insert", "pop", "popleft", "appendleft",
             "remove", "clear", "update", "add", "discard", "setdefault"}
_TEARDOWN_METHODS = {"stop", "close", "shutdown", "join", "terminate", "reset",
                     "__exit__", "__del__"}


def _ctor_chain(value):
    """Dotted ctor name when ``value`` is a plain constructor call, else None."""
    if isinstance(value, ast.Call):
        return attr_chain(value.func)
    return None


def _iter_methods(cls):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _AccessCollector:
    """Walk one method body recording self-attribute accesses with the set of
    ``with self.<lock>`` regions active at each access. Nested function bodies
    are walked with an EMPTY active set: a closure may run on another thread,
    so a lock held at definition time guards nothing at call time."""

    def __init__(self, lock_attrs):
        self.lock_attrs = lock_attrs
        #: (attr, is_write, node, frozenset(active_locks))
        self.accesses = []

    def collect(self, method):
        for stmt in method.body:
            self._visit(stmt, frozenset())

    def _visit(self, node, active):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._visit(child, frozenset())
            return
        if isinstance(node, ast.With):
            locks_here = set()
            for item in node.items:
                a = self_attr(item.context_expr)
                if a in self.lock_attrs:
                    locks_here.add(a)
                self._visit(item.context_expr, active)
            inner = active | frozenset(locks_here)
            for child in node.body:
                self._visit(child, inner)
            return
        self._record(node, active)
        for child in ast.iter_child_nodes(node):
            self._visit(child, active)

    def _record(self, node, active):
        attr = self_attr(node)
        if attr is not None and attr not in self.lock_attrs:
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.accesses.append((attr, is_write, node, active))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            recv = self_attr(node.func.value)
            if recv is not None and recv not in self.lock_attrs:
                self.accesses.append((recv, True, node, active))


class LockDisciplineRule(Rule):
    """GL-C001: an attribute accessed under ``with self.<lock>`` somewhere in the
    class is written elsewhere without holding any of those locks."""

    rule_id = "GL-C001"
    severity = Severity.ERROR
    description = ("shared attribute written outside the lock that guards its "
                   "other accesses")
    fix_hint = ("hold the same `with self.<lock>:` the other accesses hold (or "
                "move the write into a locked helper)")

    def check(self, tree, ctx):
        for cls in ctx.by_type(ast.ClassDef):
            lock_attrs, self_sync_attrs = set(), set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign):
                    chain = _ctor_chain(node.value)
                    for tgt in node.targets:
                        a = self_attr(tgt)
                        if not a:
                            continue
                        if chain in _LOCK_CTORS:
                            lock_attrs.add(a)
                        elif chain in _SELF_SYNC_CTORS:
                            self_sync_attrs.add(a)
            if not lock_attrs:
                continue
            # attrs EVER rebound to a self-synchronizing object are exempt from
            # lock discipline (their own methods synchronize); the lock attrs
            # themselves are excluded inside _AccessCollector
            lock_attrs = lock_attrs | self_sync_attrs
            per_method = []
            for method in _iter_methods(cls):
                collector = _AccessCollector(lock_attrs)
                collector.collect(method)
                per_method.append((method, collector.accesses))
            guarded = {}  # attr -> set of locks it is accessed under
            for _method, accesses in per_method:
                for attr, _w, _node, active in accesses:
                    if active:
                        guarded.setdefault(attr, set()).update(active)
            for method, accesses in per_method:
                if method.name == "__init__":
                    continue  # construction precedes any concurrent access
                for attr, is_write, node, active in accesses:
                    if not is_write or attr not in guarded:
                        continue
                    if active & guarded[attr]:
                        continue
                    yield ctx.finding(
                        self, node,
                        "attribute `self.%s` is written in `%s.%s` without "
                        "holding `self.%s`, which guards its other accesses"
                        % (attr, cls.name, method.name,
                           "`/`self.".join(sorted(guarded[attr]))))


def _untimed_blocking_call(node, method_attr):
    """True for ``X.<method_attr>(...)`` forms that can block forever: no
    timeout and not explicitly non-blocking. ``get()``, ``get(True)`` and
    ``get(block=True)`` all block; ``get(False)``/``get(timeout=...)``/
    ``join(5)`` do not."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == method_attr):
        return False
    if call_kwarg(node, "timeout") is not None:
        return False
    if node.args:
        first = node.args[0]
        if method_attr in ("join", "result"):
            # Thread.join(timeout) / Future.result(timeout): the first
            # positional IS the timeout — join(5) is timed, join(None) blocks
            return isinstance(first, ast.Constant) and first.value is None
        # Queue.get(block, timeout): the FIRST positional is block, not a
        # timeout — get(5) sets block=5 (truthy) and still blocks forever.
        # A second positional supplies the timeout; a dynamic block flag is
        # assumed deliberate.
        if len(node.args) >= 2:
            return False
        return isinstance(first, ast.Constant) and bool(first.value)
    block = call_kwarg(node, "block")
    if block is not None:
        # block=True without timeout blocks forever; block=<dynamic> is assumed
        # deliberate
        return isinstance(block, ast.Constant) and bool(block.value)
    return True


def _is_submit_call(value):
    """True for ``<anything>.submit(...)`` — an executor-built Future."""
    return isinstance(value, ast.Call) and \
        isinstance(value.func, ast.Attribute) and value.func.attr == "submit"


class BlockingTeardownRule(Rule):
    """GL-C002: untimed ``Queue.get()`` / ``Thread.join()`` /
    ``Future.result()`` inside stop/close/shutdown/join paths — a wedged
    worker then hangs teardown forever."""

    rule_id = "GL-C002"
    severity = Severity.ERROR
    description = ("blocking Queue.get()/Thread.join()/Future.result() without "
                   "a timeout on a stop/shutdown path")
    fix_hint = ("pass a timeout (`.join(timeout=...)` / `.get(timeout=...)` / "
                "`.result(timeout=...)`) or use `.get_nowait()` so teardown "
                "cannot hang on a wedged worker")

    def check(self, tree, ctx):
        for cls in ctx.by_type(ast.ClassDef):
            thread_attrs, queue_attrs, thread_list_attrs = set(), set(), set()
            future_attrs, future_list_attrs = set(), set()
            for method in _iter_methods(cls):
                local_threads = set()
                for node in ast.walk(method):
                    if isinstance(node, ast.Assign):
                        chain = _ctor_chain(node.value)
                        for tgt in node.targets:
                            a = self_attr(tgt)
                            if chain in _THREAD_CTORS:
                                if a:
                                    thread_attrs.add(a)
                                elif isinstance(tgt, ast.Name):
                                    local_threads.add(tgt.id)
                            elif chain in _QUEUE_CTORS and a:
                                queue_attrs.add(a)
                            elif _is_submit_call(node.value) and a:
                                # self._flush_future = pool.submit(...)
                                future_attrs.add(a)
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "append" and node.args:
                        arg = node.args[0]
                        a = self_attr(node.func.value)
                        if a is None:
                            continue
                        if isinstance(arg, ast.Name) and arg.id in local_threads:
                            thread_list_attrs.add(a)
                        elif _is_submit_call(arg):
                            # self._futures.append(pool.submit(...))
                            future_list_attrs.add(a)
            if not (thread_attrs or queue_attrs or thread_list_attrs
                    or future_attrs or future_list_attrs):
                continue
            for method in _iter_methods(cls):
                if method.name not in _TEARDOWN_METHODS:
                    continue
                for finding in self._check_teardown(
                        method, cls, ctx, thread_attrs, queue_attrs,
                        thread_list_attrs, future_attrs, future_list_attrs):
                    yield finding

    def _check_teardown(self, method, cls, ctx, thread_attrs, queue_attrs,
                        thread_list_attrs, future_attrs, future_list_attrs):
        # loop vars bound from a tracked attr list: for t in self._threads:
        loop_threads, loop_futures = set(), set()
        for node in ast.walk(method):
            if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                it = self_attr(node.iter)
                if it in thread_list_attrs:
                    loop_threads.add(node.target.id)
                elif it in future_list_attrs:
                    loop_futures.add(node.target.id)
        for node in ast.walk(method):
            if not isinstance(node, ast.Call):
                continue
            if _untimed_blocking_call(node, "join"):
                recv = node.func.value
                a = self_attr(recv)
                if a in thread_attrs or (
                        isinstance(recv, ast.Name) and recv.id in loop_threads):
                    yield ctx.finding(
                        self, node,
                        "`%s.%s` joins a worker thread with no timeout — a "
                        "wedged worker hangs teardown forever"
                        % (cls.name, method.name))
            elif _untimed_blocking_call(node, "get"):
                a = self_attr(node.func.value)
                if a in queue_attrs:
                    yield ctx.finding(
                        self, node,
                        "`%s.%s` blocks on `self.%s.get()` with no timeout on "
                        "a shutdown path" % (cls.name, method.name, a))
            elif _untimed_blocking_call(node, "result"):
                recv = node.func.value
                a = self_attr(recv)
                if a in future_attrs or (
                        isinstance(recv, ast.Name) and recv.id in loop_futures):
                    yield ctx.finding(
                        self, node,
                        "`%s.%s` blocks on an executor future's `.result()` "
                        "with no timeout on a shutdown path — a wedged task "
                        "hangs teardown forever" % (cls.name, method.name))


class ThreadHandlingRule(Rule):
    """GL-C003: a thread started without ``daemon=True`` and never joined keeps
    the process alive after main exits (or leaks silently under pytest)."""

    rule_id = "GL-C003"
    severity = Severity.WARNING
    description = "thread started without daemon=True or a matching join()"
    fix_hint = ("pass `daemon=True` to threading.Thread(...), or join the "
                "thread on every exit path")

    def check(self, tree, ctx):
        scopes = [tree] + ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef)
        for scope in scopes:
            scope_src = None  # unparsed lazily: only scopes with a Thread ctor pay
            for node in walk_scope(scope):
                if not isinstance(node, ast.Call):
                    continue
                if attr_chain(node.func) not in _THREAD_CTORS:
                    continue
                daemon = call_kwarg(node, "daemon")
                if daemon is not None and not (
                        isinstance(daemon, ast.Constant) and daemon.value is False):
                    continue  # daemon=True (or a dynamic flag: assume intentional)
                if scope_src is None:
                    scope_src = ast.unparse(scope)
                if self._is_handled(node, scope, scope_src, ctx):
                    continue
                yield ctx.finding(
                    self, node,
                    "thread created without daemon=True and without visible "
                    "join handling in `%s`"
                    % getattr(scope, "name", "<module>"))

    def _is_handled(self, call, scope, scope_src, ctx):
        parent = ctx.parent(call)
        # threading.Thread(...).start() with no binding: nobody can ever join it
        if isinstance(parent, ast.Attribute):
            return False
        if not (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            # passed/returned/stored somewhere we can't track: don't guess
            return True
        name = parent.targets[0].id
        # joined, daemonized after the fact, or handed to a container that the
        # surrounding code joins (textual check — this is a heuristic rule).
        # Word boundaries matter: `fmt.join(...)` must not count as `t.join(...)`.
        esc = re.escape(name)
        if re.search(r"\b%s\.join\(" % esc, scope_src) or \
                re.search(r"\b%s\.daemon\s*=\s*True\b" % esc, scope_src):
            return True
        if re.search(r"\.append\(\s*%s\s*\)" % esc, scope_src) and \
                ".join(" in scope_src:
            return True
        return False


_OPTIONS_SEGMENT = re.compile(r"(?:^|_)opt(?:ion)?s$", re.IGNORECASE)

#: scopes where writing an options field IS the contract: the options class's
#: own methods (construction/unpickle/normalization) and the sanctioned live
#: actuation seam (control/knobs.py KnobSet)
_OPTIONS_OWNER_CLASS = re.compile(r"Options$")
_SANCTIONED_CLASSES = {"KnobSet"}


class OptionsMutationRule(Rule):
    """GL-C004: post-construction mutation of an ``*Options`` struct field.

    The ``IoOptions``/``RemoteIoOptions``/``RecoveryOptions``/... structs are
    construction-frozen config: one instance is shared across readers, crosses
    the pool-child pickle wire, and is read lock-free by worker threads.
    Mutating a field after construction (``reader._io_options.readahead_depth
    = 8``) silently retunes OTHER pipelines sharing the struct, never reaches
    components that copied the value at build time, and races every lock-free
    reader. Live retunes go through the sanctioned seam instead
    (ISSUE 13): ``petastorm_tpu.control.KnobSet.apply()`` / the component's
    ``apply_*()`` setters, which are bounded, thread-safe, and observable
    (``ptpu_ctl_*``).

    Exempt: methods of classes named ``*Options`` (their ``__init__``/
    ``normalize`` own the fields) and the ``KnobSet`` seam itself.
    """

    rule_id = "GL-C004"
    severity = Severity.WARNING
    description = ("post-construction mutation of an *Options struct field "
                   "outside the sanctioned KnobSet.apply() seam")
    fix_hint = ("route live retunes through petastorm_tpu.control.KnobSet"
                ".apply() or the component's apply_*() setter (options "
                "structs are shared, pickled config — mutating them races "
                "lock-free readers and skips components that copied the "
                "value); or justify with '# graftlint: disable=GL-C004'")

    def check(self, tree, ctx):
        exempt = set()
        for node in ctx.by_type(ast.ClassDef):
            if _OPTIONS_OWNER_CLASS.search(node.name) \
                    or node.name in _SANCTIONED_CLASSES:
                for sub in ast.walk(node):
                    exempt.add(id(sub))
        for node in ctx.by_type(ast.Assign, ast.AugAssign):
            if id(node) in exempt:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for target in targets:
                for finding in self._check_target(node, target, ctx):
                    yield finding

    def _check_target(self, node, target, ctx):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from self._check_target(node, elt, ctx)
            return
        if not isinstance(target, ast.Attribute):
            return
        chain = attr_chain(target.value)
        if chain is None:
            return
        segments = chain.split(".")
        if not any(_OPTIONS_SEGMENT.search(seg) for seg in segments):
            return
        yield ctx.finding(
            self, node,
            "field %r assigned on options object `%s` after construction — "
            "options structs are frozen config; use the KnobSet/apply_*() "
            "seam for live retunes" % (target.attr, chain))
