"""Resource-lifecycle rule: readers/executors/loaders must not leak.

Every one of these objects owns background threads, child processes, sockets, or
device buffers; an instance abandoned without ``stop()``/``close()`` leaks them
until interpreter exit (and under pytest, across the whole session). The rule
tracks constructor calls of the project's closeable types through their enclosing
function and requires one of the accepted ownership outcomes below.
"""
from __future__ import annotations

import ast

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule
from petastorm_tpu.analysis.rules._astutil import call_func_name, walk_scope

#: Constructors/factories returning objects that expose close()/stop() and
#: support the context-manager protocol. Project types only — stdlib `open()`
#: etc. is the standard linters' turf. ``SharedMemory`` is the one stdlib
#: exception: a segment constructed without a ``close()``/``unlink()`` path
#: outlives the process in ``/dev/shm`` (not just the interpreter), and the shm
#: wire (petastorm_tpu/parallel/shm_ring.py) makes it a recurring project idiom
#: — so the PR-1 analyzer covers it alongside the ring's own types.
CLOSEABLE_FACTORIES = frozenset({
    "make_reader", "make_batch_reader", "Reader",
    "make_executor", "ThreadExecutor", "ProcessExecutor", "SyncExecutor",
    "DataLoader", "InMemDataLoader", "BatchedDataLoader",
    "make_weighted_reader", "WeightedSamplingReader",
    "SharedMemory", "SlabRing", "SlabClient",
    # ISSUE-4 async-IO runtime: a ReadaheadPool owns live IO threads
    # (shutdown() is its closer) and a MemCache pins process-wide bytes
    # (clear() releases them)
    "ReadaheadPool", "MemCache",
    # ISSUE-6 lease contract: constructing a Lease IS the acquire (refcount 1
    # over someone else's buffers — release() is its closer; leaking one
    # strands a slab/staging slot until GC, counted ptpu_lease_leaked_total),
    # and a PinnedStagingPool owns mlock'd host slabs (close() unpins/unmaps)
    "Lease", "PinnedStagingPool",
    # ISSUE-8 remote tier: a RemoteReadEngine owns the ranged-GET thread pool
    # (shutdown() is its closer); FooterCache pins parsed-footer bytes and
    # TieredCache pins the mem tier's process-wide bytes (clear() releases
    # both)
    "RemoteReadEngine", "FooterCache", "TieredCache",
    # ISSUE-17 host-wide cache arena: a CacheArena owns named /dev/shm
    # segments (creator: close() unlinks the whole set; attacher: close()/
    # detach() drops the mappings and deregisters the pid) — leaking one
    # strands host-wide shared memory past process exit, same failure class
    # as a bare SharedMemory
    "CacheArena",
})

#: calls that merely CONSUME an iterable without taking ownership of it
_CONSUMERS = frozenset({"list", "iter", "next", "enumerate", "sorted", "zip",
                        "sum", "min", "max", "len", "tuple", "set", "dict",
                        "print", "repr", "str", "isinstance", "type"})

_CLOSERS = frozenset({"stop", "close", "join", "terminate", "shutdown", "unlink",
                      "clear", "release", "detach"})


class ResourceLifecycleRule(Rule):
    """GL-L001: a closeable constructed but not consumed via ``with``, closed in
    a ``finally``, or handed off (returned / yielded / stored / wrapped by
    another closeable that assumes ownership). Covers ``SharedMemory`` (and the
    slab-ring types built on it): a segment with no ``close()``/``unlink()``
    path leaks a ``/dev/shm`` file past process exit."""

    rule_id = "GL-L001"
    severity = Severity.ERROR
    description = ("reader/executor/loader/shared-memory segment constructed "
                   "without a context manager or try/finally close")
    fix_hint = ("use `with make_reader(...) as r:` (or close in a `finally:`); "
                "passing a reader into DataLoader(...) transfers ownership to "
                "the loader's own `with` block; a SharedMemory segment needs a "
                "close()+unlink() (creator) or close() (attacher) path")

    def check(self, tree, ctx):
        scopes = [tree] + ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef)
        for scope in scopes:
            scope_nodes = list(walk_scope(scope))
            for node in scope_nodes:
                if not isinstance(node, ast.Call):
                    continue
                name = call_func_name(node)
                if name not in CLOSEABLE_FACTORIES:
                    continue
                ok, tracked = self._call_context_ok(node, ctx)
                if ok:
                    continue
                if tracked is not None and self._name_ok(tracked, scope_nodes, ctx,
                                                         factory=name):
                    continue
                yield ctx.finding(
                    self, node,
                    "`%s(...)` result is never closed: not used as a context "
                    "manager, closed in a finally, or handed off" % name)
        yield from self._check_double_release(ctx)

    # -- lease release discipline (ISSUE 6) ----------------------------------------------

    def _check_double_release(self, ctx):
        """Flag an UNBALANCED ``x.release()`` in one straight-line statement
        list: each name gets one implied base reference plus one per
        ``x.retain()`` seen earlier in the list; a release past that budget is
        the caller bug :class:`petastorm_tpu.errors.LeaseError` catches at
        runtime. Conservative: the scan STOPS at the first compound statement
        in a list (a branch may retain or release — what it did to any
        refcount is unknowable, and a wrong guess in either direction makes
        false positives), and a rebind/del of the name resets its tracking —
        so conditional release patterns never false-positive. Teardown blocks
        stay covered: a ``finally:`` body is its own statement list."""
        for stmts in self._stmt_lists(ctx):
            state = {}  # name -> [extra_refs_from_retains, base_release_lineno]
            for stmt in stmts:
                if self._clears_tracking(stmt):
                    break
                self._absorb_retains_and_rebinds(stmt, state)
                if not (isinstance(stmt, ast.Expr)
                        and isinstance(stmt.value, ast.Call)):
                    continue
                target = self._release_target(stmt.value)
                if target is None:
                    continue
                entry = state.setdefault(target, [0, None])
                if entry[0] > 0:
                    entry[0] -= 1  # consumes a retain() seen earlier
                elif entry[1] is None:
                    entry[1] = stmt.lineno  # the implied base reference
                else:
                    yield ctx.finding(
                        self, stmt.value,
                        "`%s.release()` called again after the release on "
                        "line %d with no retain() between: the lease contract "
                        "is exactly-once release per retain (double release "
                        "raises LeaseError at runtime)" % (target, entry[1]),
                        fix_hint="drop the extra release(), or retain() once "
                                 "per holder")

    @staticmethod
    def _stmt_lists(ctx):
        for node in ctx.walk():
            for field in ("body", "orelse", "finalbody"):
                stmts = getattr(node, field, None)
                if isinstance(stmts, list) and stmts \
                        and isinstance(stmts[0], ast.stmt):
                    yield stmts

    @staticmethod
    def _dotted_name(expr):
        parts = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        parts.append(expr.id)
        return ".".join(reversed(parts))

    @classmethod
    def _release_target(cls, call):
        """Dotted receiver of a bare ``<recv>.release()`` call, else None."""
        if isinstance(call.func, ast.Attribute) and call.func.attr == "release" \
                and not call.args and not call.keywords:
            return cls._dotted_name(call.func.value)
        return None

    @staticmethod
    def _clears_tracking(stmt):
        """ANY compound statement wipes per-list tracking: its branch bodies
        are separate lists, and what they did to a refcount is unknowable."""
        compound = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.Try,
                    ast.With, ast.AsyncWith)
        if hasattr(ast, "Match"):
            compound += (ast.Match,)
        return isinstance(stmt, compound)

    @classmethod
    def _flatten_targets(cls, targets):
        """Expand tuple/list/starred assignment targets so a rebind inside
        ``lease, other = make_two()`` still resets ``lease``'s tracking."""
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                yield from cls._flatten_targets(t.elts)
            elif isinstance(t, ast.Starred):
                yield from cls._flatten_targets([t.value])
            else:
                yield t

    @classmethod
    def _absorb_retains_and_rebinds(cls, stmt, state):
        """Fold one simple statement into the release-budget state: every
        ``x.retain()`` anywhere in it grants one extra release; any rebind or
        ``del`` of a name drops that name's tracking (a different lease now)."""
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "retain":
                name = cls._dotted_name(sub.func.value)
                if name is not None:
                    state.setdefault(name, [0, None])[0] += 1
            elif isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for t in cls._flatten_targets(targets):
                    name = cls._dotted_name(t)
                    if name is not None:
                        state.pop(name, None)
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    name = cls._dotted_name(t)
                    if name is not None:
                        state.pop(name, None)

    def _call_context_ok(self, call, ctx):
        """(resolved?, tracked_name): classify the constructor call by its parent.

        Returns (True, None) when the call site itself is fine, (False, name)
        when the result is bound to a local name that must be followed, and
        (False, None) when the result is plainly dropped."""
        parent = ctx.parent(call)
        if isinstance(parent, ast.withitem):
            return True, None
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom, ast.Await)):
            return True, None  # ownership moves to the caller
        if isinstance(parent, ast.keyword):
            parent = ctx.parent(parent)
        if isinstance(parent, ast.Call):
            outer = call_func_name(parent)
            if outer in CLOSEABLE_FACTORIES or outer == "closing":
                # wrapped by another closeable (DataLoader closes its reader on
                # __exit__) or contextlib.closing — the wrapper is now tracked
                return self._call_context_ok(parent, ctx)
            if outer in _CONSUMERS:
                return False, None  # list(make_reader(...)) consumes AND leaks
            return True, None  # passed to unknown callee: assume it takes ownership
        if isinstance(parent, ast.Assign):
            if len(parent.targets) == 1 and isinstance(parent.targets[0], ast.Name):
                return False, parent.targets[0].id
            return True, None  # attribute/subscript/tuple target: escapes tracking
        if isinstance(parent, (ast.Starred, ast.Subscript, ast.Attribute,
                               ast.IfExp, ast.BoolOp)):
            return True, None  # too dynamic to judge
        if isinstance(parent, ast.Expr):
            if self._in_pytest_raises(parent, ctx):
                return True, None  # the constructor is EXPECTED to raise
            return False, None  # bare statement: constructed and dropped
        return True, None

    @staticmethod
    def _in_pytest_raises(node, ctx):
        """True inside a ``with pytest.raises(...):`` body — a bare constructor
        call there asserts the constructor throws, so nothing is ever built."""
        while node is not None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(node, ast.With):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call) and \
                            call_func_name(item.context_expr) == "raises":
                        return True
            node = ctx.parent(node)
        return False

    def _name_ok(self, name, scope_nodes, ctx, factory=None):
        """True when the bound name reaches an accepted ownership outcome
        anywhere in the enclosing scope."""
        for node in scope_nodes:
            # Lease only: a straight-line `name.release()` statement counts.
            # Unlike readers/shm segments, a lease missed on an exception path
            # does not leak an OS resource — the GC safety net reclaims it and
            # counts ptpu_lease_leaked_total — so the static bar is the happy
            # path, with the double-release check guarding the other side.
            if factory == "Lease" and isinstance(node, ast.Call) \
                    and self._release_target(node) == name:
                return True
            # with name: / with wrapper(name):
            if isinstance(node, ast.withitem) and self._expr_uses_name(
                    node.context_expr, name):
                return True
            # try: ... finally: name.stop()/close()/join()
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) and \
                                isinstance(sub.func, ast.Attribute) and \
                                sub.func.attr in _CLOSERS and \
                                isinstance(sub.func.value, ast.Name) and \
                                sub.func.value.id == name:
                            return True
            # return name / yield name (ownership to caller / fixture finalizer);
            # only the BARE name counts — `return list(reader)` returns the
            # consumed rows, the reader itself still leaks
            if isinstance(node, (ast.Return, ast.Yield)) and node.value is not None \
                    and self._is_bare_name(node.value, name):
                return True
            # self.x = name / container[k] = name: lifetime escapes the function
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name) \
                    and node.value.id == name:
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets):
                    return True
            # passed onward: DataLoader(name, ...) transfers ownership; any other
            # non-consumer call is assumed to take it too (addfinalizer, helpers)
            if isinstance(node, ast.Call):
                callee = call_func_name(node)
                args = list(node.args) + [kw.value for kw in node.keywords]
                # elements of literal list/tuple args transfer too:
                # WeightedSamplingReader([r1, r2], ...) owns both readers
                for container in list(args):
                    if isinstance(container, (ast.List, ast.Tuple)):
                        args.extend(container.elts)
                for arg in args:
                    if isinstance(arg, ast.Name) and arg.id == name:
                        if callee not in _CONSUMERS:
                            return True
                    # name.stop passed as a callback (request.addfinalizer(r.stop))
                    if isinstance(arg, ast.Attribute) and \
                            isinstance(arg.value, ast.Name) and \
                            arg.value.id == name and arg.attr in _CLOSERS:
                        return True
        return False

    @staticmethod
    def _expr_uses_name(expr, name):
        return any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(expr))

    @staticmethod
    def _is_bare_name(expr, name):
        if isinstance(expr, ast.Name):
            return expr.id == name
        if isinstance(expr, (ast.Tuple, ast.List, ast.Dict)):
            values = expr.values if isinstance(expr, ast.Dict) else expr.elts
            return any(isinstance(e, ast.Name) and e.id == name for e in values)
        return False
