"""Small AST helpers shared by the rule visitors."""
from __future__ import annotations

import ast


def attr_chain(node):
    """Dotted-name string for a Name/Attribute chain ('jax.jit'), else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node):
    """Attribute name when ``node`` is ``self.<attr>``, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def call_func_name(call):
    """Last segment of a Call's func ('make_reader' for pkg.make_reader(...))."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def call_kwarg(call, name):
    """The keyword argument node named ``name``, or None."""
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def literal_strings(node):
    """String constants inside a str/tuple/list literal, or None when not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def literal_ints(node):
    """Int constants inside an int/tuple/list literal, or None when not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def walk_scope(node):
    """Yield nodes of ``node``'s body WITHOUT descending into nested function or
    class definitions (their bodies are separate execution scopes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(child))
