"""Schema/codec contract rule for literal ``UnischemaField`` declarations.

A field whose codec cannot faithfully store its declared numpy dtype fails at
runtime — at encode (object arrays through ``NdarrayCodec``'s
``allow_pickle=False`` save), at decode (int32 values round-tripped through an
int8 storage column), or silently (float64 truncated to float32) — always far
from the schema declaration that caused it. The checks mirror
``petastorm_tpu/codecs.py`` + ``petastorm_tpu/types.py`` exactly; anything the
rule cannot resolve statically is skipped, never guessed.
"""
from __future__ import annotations

import ast

import numpy as np

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule
from petastorm_tpu.analysis.rules._astutil import attr_chain, call_func_name, call_kwarg

#: scalar type tag -> (numpy storage dtype or None for object-backed, allowed
#: field dtype kinds). Mirrors petastorm_tpu/types.py.
_SCALAR_TAGS = {
    "BooleanType": ("bool_", "b"),
    "ByteType": ("int8", "iu"),
    "ShortType": ("int16", "iu"),
    "IntegerType": ("int32", "iu"),
    "LongType": ("int64", "iu"),
    "FloatType": ("float32", "fiu"),
    "DoubleType": ("float64", "fiu"),
    "StringType": (None, "USO"),
    "BinaryType": (None, "SO"),
    "DateType": (None, "MO"),
    "TimestampType": (None, "MO"),
    "DecimalType": (None, "O"),
}

#: exact-integer bits representable by each float storage width
_FLOAT_EXACT_BITS = {4: 24, 8: 53}

#: declarative tabular ops whose output is integer ids by contract
#: (petastorm_tpu/ops/tabular.py): op name -> positional index of ``out=``
_INT_OUTPUT_OPS = {"HashField": 2, "Bucketize": 3, "VocabLookup": 3,
                   "FeatureCross": 2}
#: declarative ops whose output is floating by contract
_FLOAT_OUTPUT_OPS = {"Normalize": 1, "Standardize": 1}


def _resolve_dtype(node, numpy_aliases):
    """AST dtype expression -> np.dtype, or None when not statically literal."""
    try:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return np.dtype(node.value)
        chain = attr_chain(node)
        if chain and "." in chain:
            root, rest = chain.split(".", 1)
            if root in numpy_aliases and "." not in rest:
                attr = getattr(np, rest, None)
                if attr is not None:
                    return np.dtype(attr)
        if isinstance(node, ast.Call) and call_func_name(node) == "dtype" \
                and node.args:
            return _resolve_dtype(node.args[0], numpy_aliases)
    except TypeError:
        return None
    return None


def _resolve_shape(node):
    """AST shape expression -> ('known', tuple) or ('unknown', None)."""
    if isinstance(node, ast.Constant) and node.value is None:
        return "known", None
    if isinstance(node, (ast.Tuple, ast.List)):
        dims = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and (
                    elt.value is None or isinstance(elt.value, int)):
                dims.append(elt.value)
            else:
                return "unknown", None
        return "known", tuple(dims)
    return "unknown", None


def _resolve_codec(node):
    """AST codec expression -> ('scalar', tag) | ('ndarray',) | ('image', fmt)
    | ('none',) | None when not statically resolvable."""
    if isinstance(node, ast.Constant) and node.value is None:
        return ("none",)
    if not isinstance(node, ast.Call):
        return None
    name = call_func_name(node)
    if name == "ScalarCodec":
        if not node.args:
            return None
        tag_call = node.args[0]
        if isinstance(tag_call, ast.Call):
            tag = call_func_name(tag_call)
            if tag in _SCALAR_TAGS:
                return ("scalar", tag)
        return None
    if name in ("NdarrayCodec", "CompressedNdarrayCodec"):
        return ("ndarray",)
    if name == "CompressedImageCodec":
        fmt_node = node.args[0] if node.args else call_kwarg(node, "image_codec")
        if fmt_node is None:
            fmt = "png"
        elif isinstance(fmt_node, ast.Constant) and isinstance(fmt_node.value, str):
            fmt = "jpeg" if fmt_node.value == "jpg" else fmt_node.value
        else:
            return None
        return ("image", fmt)
    return None


def _int_range_fits(field_dtype, storage_dtype):
    lo, hi = np.iinfo(field_dtype).min, np.iinfo(field_dtype).max
    slo, shi = np.iinfo(storage_dtype).min, np.iinfo(storage_dtype).max
    return lo >= slo and hi <= shi


class SchemaCodecContractRule(Rule):
    """GL-S001: literal ``UnischemaField`` whose codec and numpy dtype are
    incompatible per codecs.py — plus declarative tabular-op dtype contracts
    (ISSUE 9): hash/bucketize/vocab/cross ids must land in integer fields,
    normalize/standardize outputs in floating ones. Ops are correlated with
    literal ``UnischemaField`` declarations in the same module by output
    field name; anything not statically resolvable is skipped, never
    guessed."""

    rule_id = "GL-S001"
    severity = Severity.ERROR
    description = "UnischemaField codec cannot faithfully store the declared dtype"
    fix_hint = ("pick the codec whose storage type covers the field dtype (see "
                "petastorm_tpu/types.py for the ScalarCodec storage map); "
                "declarative op outputs must match the op's dtype contract "
                "(ops/tabular.py)")

    def check(self, tree, ctx):
        declared = {}   # field name -> resolved np.dtype (literal declarations)
        op_calls = []
        for node in ctx.by_type(ast.Call):
            name = call_func_name(node)
            if name == "UnischemaField":
                yield from self._check_field(node, ctx)
                fname, dtype_node, _shape, _codec = self._field_args(node)
                if fname != "?" and dtype_node is not None:
                    dtype = _resolve_dtype(dtype_node, ctx.numpy_aliases)
                    if dtype is not None:
                        declared[fname] = dtype
            elif name in _INT_OUTPUT_OPS or name in _FLOAT_OUTPUT_OPS:
                op_calls.append((name, node))
        for name, node in op_calls:
            yield from self._check_tabular_op(name, node, declared, ctx)

    def _op_out_name(self, op_name, call):
        """The op's EXPLICIT output field name (constant string), or None.

        Only an explicit ``out=`` is correlated with field declarations:
        when ``out`` defaults to the input field the op legitimately
        REPLACES the stored declaration (int32 source → float32 normalize is
        valid code), so flagging against the stored dtype would be a false
        positive."""
        node = call_kwarg(call, "out")
        out_pos = (_INT_OUTPUT_OPS.get(op_name)
                   if op_name in _INT_OUTPUT_OPS
                   else _FLOAT_OUTPUT_OPS[op_name])
        if node is None and len(call.args) > out_pos:
            node = call.args[out_pos]
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def _check_tabular_op(self, op_name, call, declared, ctx):
        integer = op_name in _INT_OUTPUT_OPS
        want_kinds = "iu" if integer else "f"
        contract = "integer ids" if integer else "floating values"
        dtype_node = call_kwarg(call, "dtype")
        if dtype_node is not None:
            dtype = _resolve_dtype(dtype_node, ctx.numpy_aliases)
            if dtype is not None and dtype.kind not in want_kinds:
                yield ctx.finding(
                    self, call,
                    "%s produces %s but declares dtype=%s — use %s dtype"
                    % (op_name, contract, dtype,
                       "an integer" if integer else "a floating"))
                return
        out = self._op_out_name(op_name, call)
        if out is None:
            return
        field_dtype = declared.get(out)
        if field_dtype is not None and field_dtype.kind not in want_kinds:
            yield ctx.finding(
                self, call,
                "%s writes %s into field %r, but that UnischemaField is "
                "declared %s — declare %s field"
                % (op_name, contract, out, field_dtype,
                   "an integer" if integer else "a floating"))

    def _field_args(self, call):
        """(name, dtype_node, shape_node, codec_node) by position/keyword."""
        sig = ["name", "numpy_dtype", "shape", "codec", "nullable"]
        bound = dict(zip(sig, call.args))
        for kw in call.keywords:
            if kw.arg in sig:
                bound[kw.arg] = kw.value
        name_node = bound.get("name")
        name = name_node.value if isinstance(name_node, ast.Constant) else "?"
        return (name, bound.get("numpy_dtype"), bound.get("shape"),
                bound.get("codec"))

    def _check_field(self, call, ctx):
        name, dtype_node, shape_node, codec_node = self._field_args(call)
        if dtype_node is None or codec_node is None:
            return
        codec = _resolve_codec(codec_node)
        if codec is None or codec == ("none",):
            return
        dtype = _resolve_dtype(dtype_node, ctx.numpy_aliases)
        if dtype is None:
            return
        shape_known, shape = _resolve_shape(shape_node) if shape_node is not None \
            else ("unknown", None)

        if codec[0] == "scalar":
            tag = codec[1]
            if shape_known and shape:
                yield ctx.finding(
                    self, call,
                    "field %r: ScalarCodec(%s) cannot store a tensor of shape "
                    "%r — use NdarrayCodec" % (name, tag, shape))
                return
            storage_name, kinds = _SCALAR_TAGS[tag]
            if dtype.kind not in kinds:
                yield ctx.finding(
                    self, call,
                    "field %r: dtype %s is not storable via ScalarCodec(%s) "
                    "(storage %s)" % (name, dtype, tag,
                                      storage_name or tag.replace("Type", "").lower()))
                return
            if storage_name is not None:
                storage = np.dtype(storage_name)
                if dtype.kind in "iu" and storage.kind in "iu":
                    if not _int_range_fits(dtype, storage):
                        yield ctx.finding(
                            self, call,
                            "field %r: %s values overflow the %s storage column "
                            "of ScalarCodec(%s)" % (name, dtype, storage, tag))
                elif dtype.kind == "f" and storage.kind == "f":
                    if dtype.itemsize > storage.itemsize:
                        yield ctx.finding(
                            self, call,
                            "field %r: %s silently truncates to %s through "
                            "ScalarCodec(%s)" % (name, dtype, storage, tag))
                elif dtype.kind in "iu" and storage.kind == "f":
                    exact_bits = _FLOAT_EXACT_BITS[storage.itemsize]
                    if np.iinfo(dtype).max > (1 << exact_bits):
                        yield ctx.finding(
                            self, call,
                            "field %r: %s integers exceed the exact-integer "
                            "range of %s storage (ScalarCodec(%s))"
                            % (name, dtype, storage, tag))
        elif codec[0] == "ndarray":
            if dtype.kind == "O":
                yield ctx.finding(
                    self, call,
                    "field %r: object dtype cannot round-trip through "
                    "NdarrayCodec (np.save(allow_pickle=False) raises at "
                    "write time)" % name)
        elif codec[0] == "image":
            fmt = codec[1]
            allowed = ("uint8",) if fmt == "jpeg" else ("uint8", "uint16")
            if str(dtype) not in allowed:
                yield ctx.finding(
                    self, call,
                    "field %r: CompressedImageCodec(%r) stores %s images only, "
                    "dtype is %s" % (name, fmt, "/".join(allowed), dtype))
                return
            if shape_known and shape is not None:
                if len(shape) not in (2, 3):
                    yield ctx.finding(
                        self, call,
                        "field %r: CompressedImageCodec expects a (H, W) or "
                        "(H, W, C) image shape, got rank %d"
                        % (name, len(shape)))
                elif len(shape) == 3 and isinstance(shape[2], int):
                    ok_ch = (1, 3) if fmt == "jpeg" else (1, 3, 4)
                    if shape[2] not in ok_ch:
                        yield ctx.finding(
                            self, call,
                            "field %r: CompressedImageCodec(%r) supports %s "
                            "channels, shape declares %d"
                            % (name, fmt,
                               "/".join(str(c) for c in ok_ch), shape[2]))
