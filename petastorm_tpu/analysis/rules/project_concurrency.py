"""Whole-program concurrency rules (ISSUE 16): the deadlock shapes per-file
analysis cannot see.

GL-C005 — blocking under a lock. PR 13's live deadlock: ``_DONE`` was posted
while holding ``_active_lock``; a worker blocked on the full results queue
could never drain it, and every collector wedged. The blocking ``put`` lived
in a helper method, three screens from the ``with`` block — so the rule
follows ONE call-graph hop: a direct unbounded blocking call under a tracked
lock fires at the call, and a call to a method whose body blocks fires at the
call site naming the inner location.

GL-C006 — lock-order cycles. Each function contributes (held → acquired)
edges to a global lock-order graph keyed by unified lock identity; any cycle
(ABBA or longer) is reported once with a witness path for every direction.
Warning, not error: two locks acquired in both orders from different
call stacks may still be serialized by a third — the graph can't see that —
but in this codebase every such cycle so far has been a real bug.
"""
from __future__ import annotations

from petastorm_tpu.analysis.engine import ProjectRule
from petastorm_tpu.analysis.findings import Finding, Severity


class BlockingUnderLockRule(ProjectRule):
    rule_id = "GL-C005"
    severity = Severity.ERROR
    description = ("unbounded blocking call reached while holding a lock "
                   "(direct or through one call hop)")
    fix_hint = ("compute under the lock, block outside it — or use the timed "
                "variant (timeout=...) and re-check a stop condition in a loop")

    def check_project(self, project):
        for module in project.modules:
            for cls in module.classes.values():
                for method in cls.methods.values():
                    yield from self._check_method(project, module, cls,
                                                  method)

    def _check_method(self, project, module, cls, method):
        for event in project.lock_region_events(module, cls, method):
            kind = event[0]
            if kind == "block":
                _, site, held = event
                if not held or self._cond_wait_ok(site, held):
                    continue
                yield self._finding(
                    project, module, site.node,
                    "%s while %s is held" % (
                        site.reason, self._held_label(project, held)),
                )
            elif kind == "call":
                _, call, (owner, funcdef), held = event
                if not held:
                    continue
                summary = project.summary(
                    module, owner if owner is not None else cls
                    if funcdef in cls.methods.values() else None, funcdef)
                for site in summary["blocking"]:
                    if self._cond_wait_ok(site, held):
                        continue
                    yield self._finding(
                        project, module, call,
                        "call to `%s()` blocks while %s is held: %s at "
                        "%s:%d" % (
                            funcdef.name,
                            self._held_label(project, held),
                            site.reason,
                            module.rel_label(),
                            site.node.lineno,
                        ),
                    )
                    break  # one finding per call site, not one per inner site

    @staticmethod
    def _cond_wait_ok(site, held):
        """``with self._cond: self._cond.wait()`` is THE condition-variable
        idiom — wait releases the lock while blocked. It is only clean when
        the condition's own lock is the sole lock held; any other lock stays
        held across the wait and the finding stands."""
        return site.cond_key is not None and held == {site.cond_key}

    @staticmethod
    def _held_label(project, held):
        labels = sorted(project.lock_label(k) for k in held)
        return "`%s`" % "`, `".join(labels)

    def _finding(self, project, module, node, message):
        ctx = module.ctx
        line = getattr(node, "lineno", 1)
        return Finding(
            rule_id=self.rule_id,
            severity=self.severity,
            path=module.path,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            fix_hint=self.fix_hint,
            code=ctx.code_at(line),
            end_line=getattr(node, "end_lineno", None) or line,
        )


class LockOrderCycleRule(ProjectRule):
    rule_id = "GL-C006"
    severity = Severity.WARNING
    description = ("inconsistent lock acquisition order across the project "
                   "(ABBA deadlock candidate)")
    fix_hint = ("pick one global order for these locks and acquire them in "
                "that order everywhere (or merge them into one lock)")

    def check_project(self, project):
        # edges[(a, b)] = first witness: a held while b acquired
        edges = {}
        for module in project.modules:
            for cls in module.classes.values():
                for method in cls.methods.values():
                    qual = "%s.%s" % (cls.qualname, method.name)
                    self._collect_edges(project, module, cls, method, qual,
                                        edges)
        yield from self._report_cycles(project, edges)

    def _collect_edges(self, project, module, cls, method, qual, edges):
        for event in project.lock_region_events(module, cls, method):
            kind = event[0]
            if kind == "acquire":
                _, key, node, held = event
                for h in held:
                    self._add_edge(edges, h, key, qual, module, node, None)
            elif kind == "call":
                _, call, (owner, funcdef), held = event
                if not held:
                    continue
                summary = project.summary(module, owner, funcdef)
                for key, node in summary["acquires"]:
                    for h in held:
                        self._add_edge(edges, h, key, qual, module, call,
                                       funcdef.name)

    @staticmethod
    def _add_edge(edges, held_key, acquired_key, qual, module, node, via):
        if held_key == acquired_key:
            return  # re-entry of the same identity is RLock territory, not order
        edge = (held_key, acquired_key)
        if edge not in edges:
            edges[edge] = (qual, module, node, via)

    def _report_cycles(self, project, edges):
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        reported = set()
        # ABBA pairs first: both single edges exist, report once per pair
        for (a, b) in sorted(edges, key=self._edge_sort_key):
            if (b, a) not in edges or a > b or (a, b) in reported:
                continue
            reported.add((a, b))
            yield self._pair_finding(project, edges, a, b)
        # longer cycles: any strongly connected component of size >= 3
        for scc in _sccs(adj):
            if len(scc) < 3:
                continue
            cycle = self._representative_cycle(adj, scc)
            if cycle is None:
                continue
            key = tuple(sorted(cycle))
            if key in reported:
                continue
            reported.add(key)
            yield self._cycle_finding(project, edges, cycle)

    @staticmethod
    def _edge_sort_key(edge):
        return edge

    def _pair_finding(self, project, edges, a, b):
        qual1, module1, node1, via1 = edges[(a, b)]
        qual2, module2, node2, via2 = edges[(b, a)]
        la, lb = project.lock_label(a), project.lock_label(b)
        message = (
            "lock order cycle between `%s` and `%s`: %s and %s" % (
                la, lb,
                self._witness(module1, node1, qual1, via1, la, lb),
                self._witness(module2, node2, qual2, via2, lb, la),
            ))
        return self._finding(module1, node1, message)

    def _cycle_finding(self, project, edges, cycle):
        labels = [project.lock_label(k) for k in cycle]
        witnesses = []
        for i, key in enumerate(cycle):
            nxt = cycle[(i + 1) % len(cycle)]
            qual, module, node, via = edges[(key, nxt)]
            witnesses.append(self._witness(
                module, node, qual, via,
                project.lock_label(key), project.lock_label(nxt)))
        first = edges[(cycle[0], cycle[1 % len(cycle)])]
        message = "lock order cycle through `%s`: %s" % (
            "` -> `".join(labels + [labels[0]]), "; ".join(witnesses))
        return self._finding(first[1], first[2], message)

    @staticmethod
    def _witness(module, node, qual, via, held_label, acquired_label):
        where = "%s:%d" % (module.rel_label(), node.lineno)
        if via:
            return "%s takes `%s` then `%s` via %s() (%s)" % (
                qual, held_label, acquired_label, via, where)
        return "%s takes `%s` then `%s` (%s)" % (
            qual, held_label, acquired_label, where)

    @staticmethod
    def _representative_cycle(adj, scc):
        """One concrete cycle inside an SCC, by DFS from its smallest node."""
        scc_set = set(scc)
        start = min(scc)
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) >= 3:
                    return path
                if nxt in scc_set and nxt not in path:
                    stack.append((nxt, path + [nxt]))
        return None

    def _finding(self, module, node, message):
        line = getattr(node, "lineno", 1)
        return Finding(
            rule_id=self.rule_id,
            severity=self.severity,
            path=module.path,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            fix_hint=self.fix_hint,
            code=module.ctx.code_at(line),
            end_line=getattr(node, "end_lineno", None) or line,
        )


def _sccs(adj):
    """Tarjan's strongly connected components, iterative."""
    index_counter = [0]
    index, lowlink = {}, {}
    on_stack, stack = set(), []
    result = []
    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = lowlink[root] = index_counter[0]
        index_counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = lowlink[nxt] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    lowlink[node] = min(lowlink[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                result.append(comp)
    return result
