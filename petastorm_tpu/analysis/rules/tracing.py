"""JAX tracing-hazard rules for jitted functions.

Inside ``@jax.jit``/``pjit`` bodies, three host-side habits turn into runtime
tracer errors or silent trace-time freezing:

- ``np.*`` calls materialize tracers on host (ConcretizationTypeError) or bake a
  trace-time constant into the compiled program;
- a Python ``if``/``while`` on a traced value raises TracerBoolConversionError
  (use ``jnp.where``/``lax.cond``, or mark the argument static);
- host I/O (print/open/time/logging) executes once at trace time, not per step —
  ``jax.debug.print`` is the traced alternative.

Both decorator form (``@jax.jit``, ``@functools.partial(jax.jit, ...)``) and
call form (``return jax.jit(fn)`` on a local ``def fn``) are recognized, and
``static_argnames``/``static_argnums`` are honored when declared literally.
"""
from __future__ import annotations

import ast

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule
from petastorm_tpu.analysis.rules._astutil import (
    attr_chain,
    call_kwarg,
    literal_ints,
    literal_strings,
)

_JIT_CHAINS = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}
_PARTIAL_CHAINS = {"functools.partial", "partial"}

#: np attributes that are fine inside a trace: dtype/type metadata queries that
#: never touch array *values*
_NP_ALLOWED = {"dtype", "iinfo", "finfo", "result_type", "promote_types",
               "can_cast", "broadcast_shapes"}

#: static (trace-time) array attributes — branching on these is fine
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"isinstance", "len", "hasattr", "getattr", "callable", "type",
                 "issubclass"}

_IO_NAMES = {"print", "open", "input", "breakpoint"}
_IO_ROOTS = {"time", "os", "sys", "logging", "shutil", "socket", "subprocess",
             "io", "pathlib", "requests", "logger", "log"}
_IO_EXEMPT_PREFIXES = ("jax.debug.", "os.path.")


def _jit_call_info(call):
    """(is_jit, static_names, static_nums, fn_arg) for a Call node that may be
    ``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``; fn_arg is the first
    positional argument (the wrapped function) or None."""
    chain = attr_chain(call.func)
    if chain in _JIT_CHAINS:
        jit_kw = call
        fn_arg = call.args[0] if call.args else None
    elif chain in _PARTIAL_CHAINS and call.args \
            and attr_chain(call.args[0]) in _JIT_CHAINS:
        jit_kw = call
        fn_arg = call.args[1] if len(call.args) > 1 else None
    else:
        return False, (), (), None
    names = literal_strings(call_kwarg(jit_kw, "static_argnames")) or ()
    nums = literal_ints(call_kwarg(jit_kw, "static_argnums")) or ()
    return True, tuple(names), tuple(nums), fn_arg


def _decorated_jits(ctx):
    """(funcdef, static_names, static_nums) for decorator-form jitted functions."""
    out = []
    for node in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef):
        for dec in node.decorator_list:
            if attr_chain(dec) in _JIT_CHAINS:
                out.append((node, (), ()))
                break
            if isinstance(dec, ast.Call):
                is_jit, names, nums, _ = _jit_call_info(dec)
                if is_jit:
                    out.append((node, names, nums))
                    break
    return out


def _call_form_jits(ctx):
    """(funcdef, static_names, static_nums) for ``jax.jit(fn)`` where ``fn``
    resolves to a def earlier in the file (nearest preceding def wins)."""
    defs = ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef)
    out = []
    for node in ctx.by_type(ast.Call):
        is_jit, names, nums, fn_arg = _jit_call_info(node)
        if not is_jit or not isinstance(fn_arg, ast.Name):
            continue
        candidates = [d for d in defs
                      if d.name == fn_arg.id and d.lineno <= node.lineno]
        if candidates:
            out.append((max(candidates, key=lambda d: d.lineno), names, nums))
    return out


def _traced_params(funcdef, static_names, static_nums):
    args = list(funcdef.args.posonlyargs) + list(funcdef.args.args)
    names = [a.arg for a in args]
    if names and names[0] == "self":
        names = names[1:]
    static = set(static_names)
    for i in static_nums:
        if 0 <= i < len(names):
            static.add(names[i])
    names += [a.arg for a in funcdef.args.kwonlyargs]
    return {n for n in names if n not in static}


def _jitted_functions(ctx):
    """Deduped [(funcdef, traced_param_names)] across both recognition forms.
    Cached on the FileContext — all three tracing rules share one computation."""
    cached = ctx.cache.get("tracing.jitted")
    if cached is not None:
        return cached
    seen = {}
    for funcdef, names, nums in _decorated_jits(ctx) + _call_form_jits(ctx):
        if funcdef not in seen:
            seen[funcdef] = _traced_params(funcdef, names, nums)
    result = list(seen.items())
    ctx.cache["tracing.jitted"] = result
    return result


class NumpyInJitRule(Rule):
    """GL-J001: ``np.*`` call inside a jitted function."""

    rule_id = "GL-J001"
    severity = Severity.WARNING
    description = "numpy call inside a @jax.jit function"
    fix_hint = ("use jnp.* (traced) instead; np.* on a tracer raises "
                "ConcretizationTypeError, and on static values it bakes a "
                "trace-time constant into the program")

    def check(self, tree, ctx):
        aliases = ctx.numpy_aliases
        for funcdef, _params in _jitted_functions(ctx):
            for node in ast.walk(funcdef):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if not chain or "." not in chain:
                    continue
                root, rest = chain.split(".", 1)
                if root in aliases and rest.split(".")[-1] not in _NP_ALLOWED:
                    yield ctx.finding(
                        self, node,
                        "`%s(...)` inside jitted `%s` runs on host at trace "
                        "time" % (chain, funcdef.name))


class TracedBranchRule(Rule):
    """GL-J002: Python ``if``/``while`` on a traced argument inside a jitted
    function (raises TracerBoolConversionError at run time)."""

    rule_id = "GL-J002"
    severity = Severity.ERROR
    description = "Python branch on a traced value inside a @jax.jit function"
    fix_hint = ("use jnp.where / jax.lax.cond (traced), or declare the argument "
                "in static_argnames if it is genuinely static")

    def check(self, tree, ctx):
        for funcdef, params in _jitted_functions(ctx):
            if not params:
                continue
            for node in ast.walk(funcdef):
                if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    hit = self._traced_names_in_test(node.test, params)
                    if hit:
                        kind = {"If": "if", "While": "while",
                                "IfExp": "conditional expression"}[
                                    type(node).__name__]
                        finding = ctx.finding(
                            self, node,
                            "%s-branch on traced argument%s `%s` of jitted "
                            "`%s`" % (kind, "s" if len(hit) > 1 else "",
                                      "`, `".join(sorted(hit)), funcdef.name))
                        # an If/While node's end_lineno spans its whole BODY; a
                        # suppression comment must sit on the header, not
                        # anywhere inside the branch
                        finding.end_line = getattr(
                            node.test, "end_lineno", None) or finding.line
                        yield finding

    def _traced_names_in_test(self, test, params):
        """Traced parameter names the test's truthiness actually depends on.
        Identity checks (`x is None`), static metadata (`x.shape`, `x.ndim`,
        `x.dtype`, `x.size`) and trace-time-static calls (isinstance/len/...)
        are pruned before collecting names."""
        hits = set()

        def visit(node):
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                return
            if isinstance(node, ast.Call):
                func_name = node.func.id if isinstance(node.func, ast.Name) else None
                if func_name in _STATIC_CALLS:
                    return
                # a call's VALUE is traced if its args are — or if it is a METHOD
                # call on a traced value (`x.any()`, `x.sum()`); walk the
                # receiver too, but not the bare function Name itself
                if isinstance(node.func, ast.Attribute):
                    visit(node.func.value)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    visit(arg)
                return
            if isinstance(node, ast.Name) and node.id in params:
                hits.add(node.id)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(test)
        return hits


class HostIoInJitRule(Rule):
    """GL-J003: host I/O inside a jitted function executes at trace time only."""

    rule_id = "GL-J003"
    severity = Severity.WARNING
    description = "host I/O inside a @jax.jit function"
    fix_hint = ("host I/O runs once at trace time, not per step; use "
                "jax.debug.print / jax.debug.callback, or hoist it out of the "
                "jitted function")

    def check(self, tree, ctx):
        for funcdef, _params in _jitted_functions(ctx):
            for node in ast.walk(funcdef):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                flagged = None
                if isinstance(node.func, ast.Name) and node.func.id in _IO_NAMES:
                    flagged = node.func.id
                elif chain and "." in chain:
                    if any(chain.startswith(p) for p in _IO_EXEMPT_PREFIXES):
                        continue
                    if chain.split(".", 1)[0] in _IO_ROOTS:
                        flagged = chain
                if flagged:
                    yield ctx.finding(
                        self, node,
                        "`%s(...)` inside jitted `%s` executes at trace time, "
                        "not per step" % (flagged, funcdef.name))
