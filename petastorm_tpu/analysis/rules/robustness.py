"""Robustness rule family (ISSUE 7, extended by ISSUES 11 and 15).

GL-R001: unbounded blocking calls in pipeline code.
GL-R002: stat-then-open TOCTOU windows — validating a path via
``os.stat``/``os.path.getsize``/``os.path.getmtime`` and later ``open()``-ing
it without re-checking a validation token.
GL-R003: unbounded sockets — a ``socket.socket()`` that reaches a blocking
use (``recv``/``accept``/``connect``) with no ``settimeout`` anywhere on the
same receiver chain (ISSUE 15: the framed transport's contract is that every
socket wait ticks and re-checks its deadline/stop condition).

At pod scale the failure mode that hurts most is not a crash but a *hang*: a
thread parked forever in ``queue.get()`` / ``Connection.recv()`` /
``Thread.join()`` / ``Event.wait()`` with no timeout pins a TPU slice until a
human notices. Every blocking wait in pipeline code must either carry a
timeout (and handle its expiry — degrade, retry, or re-check a stop event) or
justify its unboundedness with an inline
``# graftlint: disable=GL-R001`` comment (e.g. a receive that is bounded by a
``poll(timeout)`` loop right above it, or a child process whose whole job is
waiting for the next item and whose parent kills it on teardown).

GL-R001 tracks variables assigned from the blocking-primitive constructors —
``queue.Queue``/``SimpleQueue``/``LifoQueue``/``PriorityQueue``,
``threading.Thread``/``Timer``/``multiprocessing.Process``,
``threading.Event``, ``multiprocessing.connection.Client`` (and
``Listener.accept()``) — across the whole module (including ``self.<attr>``
assignments, so a queue built in ``__init__`` and drained in ``run`` is still
typed), then flags:

=========  ==============  ==========================================
kind       method          flagged when
=========  ==============  ==========================================
queue      ``get``         no ``timeout`` (kwarg or 2nd positional)
                           and not explicitly non-blocking
                           (``get(False)`` / ``get(block=False)``)
thread     ``join``        no timeout argument
event      ``wait``        no timeout argument
conn       ``recv``        always — ``Connection.recv`` has no timeout
                           parameter; bound it with a ``poll(t)`` loop
                           and carry the inline disable
=========  ==============  ==========================================

Receivers the tracker cannot type are left alone — swallowing a specific
``dict.get(key)`` or ``", ".join(parts)`` as a false positive would drown the
real findings.
"""
from __future__ import annotations

import ast

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule
from petastorm_tpu.analysis.rules._astutil import (
    attr_chain,
    call_func_name,
    call_kwarg,
)

#: constructor name (last dotted segment) -> tracked kind
_CONSTRUCTORS = {
    "Queue": "queue",
    "SimpleQueue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "JoinableQueue": "queue",
    "Thread": "thread",
    "Timer": "thread",
    "Process": "thread",
    "Event": "event",
    "Client": "conn",
    # ISSUE 16: executors — a chain typed "executor" seeds future typing
    # (x = pool.submit(...) → x is a future; fs = [pool.submit(...) ...] → a
    # future list whose loop variables are futures)
    "ThreadPoolExecutor": "executor",
    "ProcessPoolExecutor": "executor",
}

#: kind -> method name whose unbounded form is flagged
_BLOCKING_METHOD = {
    "queue": "get",
    "thread": "join",
    "event": "wait",
    "conn": "recv",
    "future": "result",
}


def _wait_aliases(ctx):
    """Dotted chains that mean ``concurrent.futures.wait`` in this file.
    Only forms actually importing the futures machinery register — a bare
    ``wait(...)`` matches nothing unless ``from concurrent.futures import
    wait`` appears."""
    aliases = set()
    for node in ctx.by_type(ast.Import):
        for a in node.names:
            if a.name == "concurrent.futures":
                aliases.add("%s.wait" % (a.asname or "concurrent.futures"))
    for node in ctx.by_type(ast.ImportFrom):
        if node.module == "concurrent":
            for a in node.names:
                if a.name == "futures":
                    aliases.add("%s.wait" % (a.asname or "futures"))
        elif node.module == "concurrent.futures":
            for a in node.names:
                if a.name == "wait":
                    aliases.add(a.asname or "wait")
    return aliases


def _is_false_const(node):
    return isinstance(node, ast.Constant) and node.value is False


class UnboundedBlockingCallRule(Rule):
    """GL-R001: ``queue.get()`` / ``Connection.recv()`` / ``Thread.join()`` /
    ``Event.wait()`` without a timeout in pipeline code."""

    rule_id = "GL-R001"
    severity = Severity.WARNING
    description = ("unbounded blocking call (queue.get/Connection.recv/"
                   "Thread.join/Event.wait without a timeout) — a silent-hang "
                   "hazard at pod scale")
    fix_hint = ("pass a timeout and handle its expiry (re-check a stop event, "
                "degrade, or raise), bound a Connection.recv with a poll(t) "
                "loop, or justify the unbounded wait with an inline "
                "'# graftlint: disable=GL-R001' comment")

    def check(self, tree, ctx):
        kinds = self._collect_kinds(ctx)
        wait_aliases = _wait_aliases(ctx)
        for node in ctx.by_type(ast.Call):
            if wait_aliases and attr_chain(node.func) in wait_aliases:
                if not self._wait_has_timeout(node):
                    yield ctx.finding(
                        self, node,
                        "futures.wait() without a timeout blocks forever if "
                        "any task wedges — a hung pipeline instead of a "
                        "diagnosable failure")
                continue
            if not kinds or not isinstance(node.func, ast.Attribute):
                continue
            recv = attr_chain(node.func.value)
            kind = kinds.get(recv)
            if kind is None or node.func.attr != _BLOCKING_METHOD.get(kind):
                continue
            if kind == "conn":
                yield ctx.finding(
                    self, node,
                    "%s.recv() blocks forever (Connection.recv has no timeout "
                    "parameter): a dead or wedged peer hangs this thread — "
                    "bound it with a poll(timeout) loop" % recv)
                continue
            if self._has_timeout(node, kind):
                continue
            what = "executor task" if kind == "future" else kind
            yield ctx.finding(
                self, node,
                "%s.%s() without a timeout blocks forever if the %s never "
                "delivers — a hung pipeline instead of a diagnosable failure"
                % (recv, node.func.attr, what))

    @staticmethod
    def _collect_kinds(ctx):
        """Map of assigned-name chain (``q``, ``self._results``) -> kind, from
        constructor assignments anywhere in the module. A second pass types
        FUTURES off the executors found in the first: ``x = pool.submit(...)``
        makes ``x`` a future, a list built from ``submit`` results (listcomp
        or ``.append``) makes its ``for``-loop and comprehension variables
        futures."""
        kinds = {}
        assigns = ctx.by_type(ast.Assign)
        for node in assigns:
            if not isinstance(node.value, ast.Call):
                continue
            name = call_func_name(node.value)
            kind = _CONSTRUCTORS.get(name)
            if kind is None and name == "accept":
                # conn = listener.accept() — the other way a Connection is born
                kind = "conn"
            if kind is None:
                continue
            for target in node.targets:
                chain = attr_chain(target)
                if chain is not None:
                    kinds[chain] = kind
        def is_submit(call):
            return isinstance(call, ast.Call) and \
                isinstance(call.func, ast.Attribute) and \
                call.func.attr == "submit" and \
                kinds.get(attr_chain(call.func.value)) == "executor"
        futlists = set()
        for node in assigns:
            value = node.value
            targets = [attr_chain(t) for t in node.targets]
            if is_submit(value):
                for chain in targets:
                    if chain is not None:
                        kinds[chain] = "future"
            elif isinstance(value, ast.ListComp) and is_submit(value.elt):
                futlists.update(c for c in targets if c is not None)
        for node in ctx.by_type(ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "append" and node.args and \
                    is_submit(node.args[0]):
                chain = attr_chain(node.func.value)
                if chain is not None:
                    futlists.add(chain)
        if futlists:
            for node in ctx.by_type(ast.For):
                if isinstance(node.target, ast.Name) and \
                        attr_chain(node.iter) in futlists:
                    kinds[node.target.id] = "future"
            for node in ctx.by_type(ast.ListComp, ast.SetComp,
                                    ast.GeneratorExp):
                for gen in node.generators:
                    if isinstance(gen.target, ast.Name) and \
                            attr_chain(gen.iter) in futlists:
                        kinds[gen.target.id] = "future"
        return kinds

    @staticmethod
    def _wait_has_timeout(call):
        """``futures.wait(fs, timeout, return_when)``: 2nd positional or a
        non-None ``timeout`` kwarg bounds it."""
        timeout = call_kwarg(call, "timeout")
        if timeout is None and len(call.args) >= 2:
            timeout = call.args[1]
        return timeout is not None and not (
            isinstance(timeout, ast.Constant) and timeout.value is None)

    @staticmethod
    def _has_timeout(call, kind):
        def bounded(node):
            # an explicit None is "no timeout" spelled out — still unbounded
            return node is not None and not (
                isinstance(node, ast.Constant) and node.value is None)

        if bounded(call_kwarg(call, "timeout")):
            return True
        if kind == "queue":
            # queue.get(block, timeout): non-blocking get(False) is bounded,
            # and a 2nd positional IS the timeout
            if len(call.args) >= 2:
                return bounded(call.args[1])
            if len(call.args) == 1 and _is_false_const(call.args[0]):
                return True
            block = call_kwarg(call, "block")
            if block is not None and _is_false_const(block):
                return True
            return False
        # thread.join(timeout) / event.wait(timeout): 1st positional is it
        return len(call.args) >= 1 and bounded(call.args[0])


#: socket methods whose unbounded form blocks forever on a quiet peer
_SOCKET_BLOCKING = frozenset(("recv", "recv_into", "recvfrom", "accept",
                              "connect"))


class UnboundedSocketRule(Rule):
    """GL-R003 (ISSUE 15): a raw socket used to block without a timeout.

    At pod scale a socket parked forever in ``recv()``/``accept()`` against a
    dead or half-open peer is the same silent hang GL-R001 polices for queues
    and pipes — except the peer is now a *network* away, where "gone without
    a FIN" is the common failure, not the exotic one. The transport plane's
    contract (``petastorm_tpu/transport/tcp.py``) is that every socket
    carries a tick timeout and every wait re-checks its deadline between
    ticks; this rule keeps that true for future socket code.

    Tracking mirrors GL-R001's receiver typing: variables (or ``self.<attr>``
    chains) assigned from ``socket.socket(...)`` / ``socket.create_connection
    (...)`` — including the first element of a ``conn, addr = srv.accept()``
    tuple unpack — are typed as sockets module-wide. A blocking call
    (``recv``/``recv_into``/``recvfrom``/``accept``/``connect``) on a tracked
    chain is flagged unless the chain is BOUNDED somewhere in the module:

    - ``<chain>.settimeout(x)`` with a non-None ``x`` (a ``settimeout(None)``
      re-flags it — that is "blocking forever" spelled out);
    - ``<chain>.setblocking(False)`` (non-blocking mode);
    - the socket came from ``socket.create_connection(..., timeout=...)``
      (the stdlib applies the timeout to the returned socket).

    Untyped receivers are left alone (same philosophy as GL-R001: drowning
    real findings in false positives helps nobody); justified unbounded
    sockets carry an inline ``# graftlint: disable=GL-R003`` with the reason.
    """

    rule_id = "GL-R003"
    severity = Severity.WARNING
    description = ("unbounded socket: blocking use (recv/accept/connect) of a "
                   "socket with no settimeout on its chain — a dead or "
                   "half-open peer hangs this thread forever")
    fix_hint = ("call settimeout(t) on the socket before blocking (and "
                "re-check a deadline/stop condition per tick), use "
                "create_connection(..., timeout=...), or justify with an "
                "inline '# graftlint: disable=GL-R003' comment")

    def check(self, tree, ctx):
        socks, bounded = self._collect(ctx)
        if not socks:
            return
        for node in ctx.by_type(ast.Call):
            if not isinstance(node.func, ast.Attribute):
                continue
            recv = attr_chain(node.func.value)
            if recv not in socks or recv in bounded:
                continue
            if node.func.attr not in _SOCKET_BLOCKING:
                continue
            yield ctx.finding(
                self, node,
                "%s.%s() on a socket with no settimeout anywhere on its "
                "chain blocks forever if the peer is gone or half-open — "
                "bound it with settimeout(t) and re-check a deadline per "
                "tick" % (recv, node.func.attr))

    @staticmethod
    def _collect(ctx):
        """``(socket chains, bounded chains)`` from module-wide assignments:
        a chain is bounded by a non-None ``settimeout``, a
        ``setblocking(False)``, or a ``create_connection`` timeout."""
        socks = set()
        bounded = set()
        for node in ctx.by_type(ast.Assign, ast.Call):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                name = call_func_name(node.value)
                ctor = name in ("socket", "create_connection")
                if name == "accept":
                    # conn, addr = srv.accept(): the FIRST unpack element is
                    # the new socket (plain targets get the tuple, untracked)
                    for target in node.targets:
                        if isinstance(target, (ast.Tuple, ast.List)) \
                                and target.elts:
                            chain = attr_chain(target.elts[0])
                            if chain is not None:
                                socks.add(chain)
                    continue
                if not ctor:
                    continue
                timeout = call_kwarg(node.value, "timeout")
                has_timeout = name == "create_connection" and (
                    len(node.value.args) >= 2
                    or (timeout is not None
                        and not (isinstance(timeout, ast.Constant)
                                 and timeout.value is None)))
                for target in node.targets:
                    chain = attr_chain(target)
                    if chain is None:
                        continue
                    socks.add(chain)
                    if has_timeout:
                        bounded.add(chain)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                recv = attr_chain(node.func.value)
                if recv is None:
                    continue
                if node.func.attr == "settimeout" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and arg.value is None:
                        bounded.discard(recv)  # "block forever", spelled out
                    else:
                        bounded.add(recv)
                elif node.func.attr == "setblocking" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value is False:
                    bounded.add(recv)
        return socks, bounded


#: callables whose dotted name (or bare from-import name) marks their first
#: argument as a stat-VALIDATED path
_STAT_CALLS = frozenset((
    "os.stat", "os.path.getsize", "os.path.getmtime",
    "stat", "getsize", "getmtime",
))

#: callables that OPEN their first argument (builtin + the common stdlib/pyarrow
#: spellings pipeline code uses)
_OPEN_CALLS = frozenset(("open", "os.open", "io.open"))

#: method names (last attribute segment) that open their first argument on a
#: filesystem object (pyarrow fs / fsspec)
_OPEN_METHODS = frozenset(("open_input_file", "open_input_stream",
                           "open_output_stream"))


class StatThenOpenRule(Rule):
    """GL-R002 (ISSUE 11): a path validated via ``os.stat``/``os.path.getsize``/
    ``os.path.getmtime`` and later ``open()``-ed in the same function without
    re-checking a validation token.

    The gap between the stat and the open is a TOCTOU window: under a mutable
    dataset the file can be rewritten (or replaced) in between, so whatever
    the stat "validated" — a cache entry, a size-derived read plan, a
    generation check — no longer describes the bytes the open returns. The
    mutable-dataset plane exists precisely because this window is real
    (docs/robustness.md "Mutable datasets"); code that must live with it
    re-validates AFTER the open (``fstat``/``source.size()``/a
    generation-token check à la ``FooterCache.get(..., stat_token=)``) or
    carries an inline ``# graftlint: disable=GL-R002`` naming why the window
    is benign.

    Tracking is deliberately narrow — a variable (or ``self.<attr>``) passed
    as the stat call's first argument, later passed as the first argument of
    an open call in the SAME function scope — so findings are real: untyped
    receivers and computed path expressions are left alone.
    """

    rule_id = "GL-R002"
    severity = Severity.WARNING
    description = ("stat-then-open TOCTOU: path validated by os.stat/getsize/"
                   "getmtime, then open()ed without re-checking a validation "
                   "token — under a mutable dataset the bytes opened may not "
                   "be the bytes validated")
    fix_hint = ("re-validate AFTER the open (fstat the handle / compare the "
                "open source's size / a generation-token check), or justify "
                "the window with an inline '# graftlint: disable=GL-R002' "
                "comment")

    def check(self, tree, ctx):
        scopes = [tree] + ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef)
        for scope in scopes:
            yield from self._check_scope(scope, ctx)

    def _check_scope(self, scope, ctx):
        from petastorm_tpu.analysis.rules._astutil import walk_scope

        statted = {}  # arg chain -> stat call line
        opens = []    # (node, chain, line)
        for node in walk_scope(scope):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            callee = attr_chain(node.func) or call_func_name(node)
            if callee is None:
                continue
            target = attr_chain(node.args[0])
            if target is None:
                continue
            line = getattr(node, "lineno", 0)
            if callee in _STAT_CALLS:
                prev = statted.get(target)
                statted[target] = min(prev, line) if prev is not None else line
            elif callee in _OPEN_CALLS or \
                    (isinstance(node.func, ast.Attribute)
                     and node.func.attr in _OPEN_METHODS):
                opens.append((node, target, line))
        for node, target, line in opens:
            stat_line = statted.get(target)
            if stat_line is not None and stat_line < line:
                yield ctx.finding(
                    self, node,
                    "%r is opened here after being validated by a stat-family "
                    "call on line %d — a TOCTOU window: the file can change "
                    "between the two (re-validate after the open, or disable "
                    "with a justification)" % (target, stat_line))
