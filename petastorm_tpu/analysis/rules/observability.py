"""Observability rule: broad exceptions must not be silently swallowed.

``except Exception: pass`` (and the bare ``except: pass``) is the
anti-observability pattern: whatever failed — a wire fallback, a cache write,
a child teardown — leaves no log line, no counter, no flight-recorder event.
In a pipeline whose production failure mode is "silently limping" (ISSUE 5),
every swallowed broad exception is a place the degradation log
(:func:`petastorm_tpu.obs.log.degradation`) should have fired instead: it
costs one counter increment, warn-onces the log, and mirrors the event into
any live flight recorder.

GL-O002 flags a handler that (a) catches ``Exception``/``BaseException`` (or
a tuple containing one, or nothing at all — the bare ``except:``) AND (b) does
nothing but ``pass``. Narrow handlers (``except OSError: pass`` on a
best-effort unlink) stay clean — swallowing a *specific* expected error is a
decision; swallowing everything is a blindfold. Handlers that log, count,
re-raise, or otherwise act are clean whatever they catch. Genuinely-silent
teardown paths (interpreter shutdown, best-effort kills) carry an inline
``# graftlint: disable=GL-O002`` with their justification.
"""
from __future__ import annotations

import ast

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule

_BROAD = ("Exception", "BaseException")


def _is_broad(type_node):
    """True when the handler's exception spec includes Exception/BaseException
    (direct name, dotted ``builtins.Exception``, or inside a tuple) — or is
    absent entirely (bare ``except:``)."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(elt) for elt in type_node.elts)
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    return False


class SilentExceptionSwallowRule(Rule):
    """GL-O002: ``except Exception: pass`` / bare ``except: pass``."""

    rule_id = "GL-O002"
    severity = Severity.WARNING
    description = ("broad exception silently swallowed (except Exception/bare "
                   "except whose body is only pass)")
    fix_hint = ("route it through petastorm_tpu.obs.log.degradation(cause, ...) "
                "so it is counted and greppable, narrow the except to the "
                "specific expected error, or justify the silence with an "
                "inline '# graftlint: disable=GL-O002' comment")

    def check(self, tree, ctx):
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                what = "bare except" if node.type is None \
                    else "except %s" % ast.unparse(node.type)
                yield ctx.finding(
                    self, node,
                    "%s swallows every error silently — anti-observability "
                    "(no log, no counter, no flight-record event)" % what)
