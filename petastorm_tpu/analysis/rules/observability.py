"""Observability rule: broad exceptions must not be silently swallowed.

``except Exception: pass`` (and the bare ``except: pass``) is the
anti-observability pattern: whatever failed — a wire fallback, a cache write,
a child teardown — leaves no log line, no counter, no flight-recorder event.
In a pipeline whose production failure mode is "silently limping" (ISSUE 5),
every swallowed broad exception is a place the degradation log
(:func:`petastorm_tpu.obs.log.degradation`) should have fired instead: it
costs one counter increment, warn-onces the log, and mirrors the event into
any live flight recorder.

GL-O002 flags a handler that (a) catches ``Exception``/``BaseException`` (or
a tuple containing one, or nothing at all — the bare ``except:``) AND (b) does
nothing but ``pass``. Narrow handlers (``except OSError: pass`` on a
best-effort unlink) stay clean — swallowing a *specific* expected error is a
decision; swallowing everything is a blindfold. Handlers that log, count,
re-raise, or otherwise act are clean whatever they catch. Genuinely-silent
teardown paths (interpreter shutdown, best-effort kills) carry an inline
``# graftlint: disable=GL-O002`` with their justification.
"""
from __future__ import annotations

import ast

from petastorm_tpu.analysis.findings import Severity
from petastorm_tpu.analysis.engine import Rule

_BROAD = ("Exception", "BaseException")


def _is_broad(type_node):
    """True when the handler's exception spec includes Exception/BaseException
    (direct name, dotted ``builtins.Exception``, or inside a tuple) — or is
    absent entirely (bare ``except:``)."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(elt) for elt in type_node.elts)
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    return False


#: provenance/trace span-open calls paired with their mandatory closers:
#: ``begin_item`` arms a THREAD-GLOBAL item context — left open it
#: misattributes every later span on that thread to the wrong item;
#: ``open_span`` returns a handle whose ``close()`` records the span — left
#: open the region silently never appears in any attribution report.
_SPAN_OPENERS = {"begin_item": "end_item", "open_span": "close"}


def _call_name(node):
    """Trailing identifier of a call's func (``x.y.begin_item`` → begin_item)."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _finally_calls(scope_body):
    """Every call name appearing inside ANY ``finally`` block of the scope
    (nested function defs excluded — their finallys protect their own opens),
    plus the receiver names of attribute calls (``h.close()`` → ``h``)."""
    names = set()
    receivers = set()
    for node in _walk_scope(scope_body):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for stmt in node.finalbody:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    name = _call_name(sub)
                    if name:
                        names.add(name)
                    if isinstance(sub.func, ast.Attribute) and \
                            isinstance(sub.func.value, ast.Name):
                        receivers.add((sub.func.value.id, sub.func.attr))
    return names, receivers


def _walk_scope(body):
    """Walk statements of one function scope WITHOUT descending into nested
    function/class definitions (each is its own span-pairing scope)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue  # a nested scope: its opens/finallys are its own
        stack.extend(ast.iter_child_nodes(node))


class UnpairedSpanRule(Rule):
    """GL-O003: a trace/provenance span opened without a finally-guarded close.

    ``provenance.begin_item(...)`` must be paired with ``end_item()`` in a
    ``finally`` block of the same function, and an ``open_span(...)`` handle
    must be assigned and ``<handle>.close()``'d in a ``finally`` (or opened as
    a ``with`` context). An exception between open and close otherwise leaks
    the thread's item context (every later span on that thread lands on the
    WRONG item) or silently drops the span from the attribution report — the
    observability analog of a leaked resource, enforced statically like
    GL-L001's closers."""

    rule_id = "GL-O003"
    severity = Severity.WARNING
    description = ("trace/provenance span opened without a finally-guarded "
                   "close (begin_item without end_item in a finally; "
                   "open_span handle without .close() in a finally)")
    fix_hint = ("wrap the region in try/finally with end_item()/"
                "<handle>.close() in the finally (or use the `with "
                "provenance.span(...)` context manager), or justify with an "
                "inline '# graftlint: disable=GL-O003' comment")

    def check(self, tree, ctx):
        scopes = [tree.body]
        scopes.extend(n.body for n in ctx.by_type(ast.FunctionDef,
                                                  ast.AsyncFunctionDef))
        for body in scopes:
            yield from self._check_scope(body, ctx)

    def _check_scope(self, body, ctx):
        with_exprs = set()
        assigned_to = {}  # open-call node -> assigned simple name (or None)
        opens = []
        for node in _walk_scope(body):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_exprs.add(id(item.context_expr))
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    assigned_to[id(node.value)] = node.targets[0].id
            if isinstance(node, ast.Call) and _call_name(node) in _SPAN_OPENERS:
                opens.append(node)
        if not opens:
            return
        closer_names, closer_receivers = _finally_calls(body)
        for call in opens:
            if id(call) in with_exprs:
                continue  # `with open_span(...)`-style: closed by __exit__
            opener = _call_name(call)
            closer = _SPAN_OPENERS[opener]
            if opener == "begin_item":
                if closer in closer_names:
                    continue
            else:  # open_span: the HANDLE must be closed
                name = assigned_to.get(id(call))
                if name is not None and (name, closer) in closer_receivers:
                    continue
            yield ctx.finding(
                self, call,
                "%s(...) is not paired with a finally-guarded %s — an "
                "exception here leaks the span/item context and poisons "
                "every later attribution on this thread" % (opener, closer))


def _is_time_sleep(node):
    """``time.sleep(...)`` or a bare ``sleep(...)`` call."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "sleep" and isinstance(func.value, ast.Name) \
            and func.value.id == "time"
    return isinstance(func, ast.Name) and func.id == "sleep"


def _walk_loop(body):
    """Walk a loop body WITHOUT descending into nested function/class
    definitions (a closure's sleep is its own loop's business)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class SleepyPollLoopRule(Rule):
    """GL-O004: a monitor/controller loop that watches an Event but sleeps
    with ``time.sleep()``.

    The pattern ``while not stop.is_set(): ...; time.sleep(t)`` (or the
    body-check variant ``while True: if stop.is_set(): break; time.sleep(t)``)
    is an *unkillable poll loop*: ``stop.set()`` does nothing until the
    current sleep expires, so teardown latency is the poll interval — and a
    long interval wedges joins, atexit hooks and test teardown behind it.
    The watcher/health/reporter threads each shipped this bug once before
    converging on ``stop_event.wait(timeout)``, which sleeps the same amount
    but wakes IMMEDIATELY on ``set()``. Loops that sleep without any Event in
    sight (deadline polls, retry backoff, CLI redraw loops) are clean — there
    is nothing to wake them.
    """

    rule_id = "GL-O004"
    severity = Severity.WARNING
    description = ("poll loop watching an Event but sleeping with "
                   "time.sleep() — stop() cannot wake it until the sleep "
                   "expires (use <event>.wait(timeout))")
    fix_hint = ("replace `while not ev.is_set(): ...; time.sleep(t)` with "
                "`while not ev.wait(t): ...` (same cadence, wakes immediately "
                "on set()), or justify with an inline "
                "'# graftlint: disable=GL-O004' comment")

    def check(self, tree, ctx):
        for node in ctx.by_type(ast.While):
            watches_event = any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "is_set"
                for sub in ast.walk(node.test))
            sleeps = []
            for sub in _walk_loop(node.body):
                if isinstance(sub, ast.Call) and _is_time_sleep(sub):
                    sleeps.append(sub)
                elif not watches_event and isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "is_set":
                    # body-check variant: `if stop.is_set(): break` + sleep
                    watches_event = True
            if not watches_event:
                continue
            for sleep_call in sleeps:
                yield ctx.finding(
                    self, sleep_call,
                    "this loop watches an Event (is_set) but sleeps with "
                    "time.sleep() — stop()/set() cannot wake it until the "
                    "sleep expires; use <event>.wait(timeout) as the loop "
                    "condition instead")


#: registry metric-factory method names whose keyword arguments (minus
#: ``help``) become label dimensions on the series name
_METRIC_FACTORIES = ("counter", "gauge", "histogram")

#: call names whose return value has unbounded cardinality — a label built
#: from one mints a fresh series per process/occurrence/path and walks the
#: registry straight into the DEFAULT_MAX_SERIES cap
_UNBOUNDED_CALLS = frozenset((
    "getpid", "getppid", "get_ident", "get_native_id",
    "uuid1", "uuid3", "uuid4", "uuid5",
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "token_hex", "token_urlsafe", "hexdigest", "urandom",
    "mkdtemp", "mkstemp", "gettempdir", "getcwd",
    "abspath", "realpath", "basename", "dirname", "normpath", "expanduser",
))

#: through these the taint flows unchanged (str(pid) is as unbounded as pid)
_TAINT_TRANSPARENT = ("str", "repr", "format")


def _tainted(expr, env, depth=0):
    """The unbounded source feeding ``expr``, or None. ``env`` maps local
    names to their taint reason (loop targets over unbounded iterables,
    one-hop assignments from tainted expressions)."""
    if depth > 6:
        return None
    if isinstance(expr, ast.Call):
        name = _call_name(expr)
        if name in _UNBOUNDED_CALLS:
            return "%s()" % name
        if name in _TAINT_TRANSPARENT:
            for arg in expr.args:
                reason = _tainted(arg, env, depth + 1)
                if reason:
                    return reason
        return None
    if isinstance(expr, ast.Attribute) and expr.attr == "pid":
        return ".pid"
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.BinOp):  # "%s" % pid, prefix + path
        return _tainted(expr.left, env, depth + 1) \
            or _tainted(expr.right, env, depth + 1)
    if isinstance(expr, ast.JoinedStr):  # f"w{os.getpid()}"
        for value in expr.values:
            if isinstance(value, ast.FormattedValue):
                reason = _tainted(value.value, env, depth + 1)
                if reason:
                    return reason
    return None


def _bounded_iter(expr):
    """True when a ``for`` target over ``expr`` stays a bounded label set:
    a literal tuple/list/set of constants, or (by convention) an ALL-CAPS
    module constant like ``TIERS`` — a closed enum frozen at import time."""
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return all(isinstance(e, ast.Constant) for e in expr.elts)
    if isinstance(expr, ast.Name):
        return expr.id.isupper()
    if isinstance(expr, ast.Attribute):
        return expr.attr.isupper()
    return False


class UnboundedLabelRule(Rule):
    """GL-O005: a metric label value that flows from an unbounded source.

    Every distinct label value mints a separate series; the temporal plane
    caps total series at ``DEFAULT_MAX_SERIES`` and then silently drops new
    ones. A label built from a pid, uuid, timestamp, filesystem path, or a
    loop variable over an open-ended collection is the classic way to burn
    that budget: the dashboard goes blind precisely when the fleet scales.
    Bounded enums (a loop over an ALL-CAPS constant tuple like ``TIERS``)
    and validated slugs (``tenant=`` labels pass through
    :class:`petastorm_tpu.obs.tenant.TenantContext`, which enforces a
    bounded closed-alphabet grammar precisely so this rule never has to
    flag them) stay clean."""

    rule_id = "GL-O005"
    severity = Severity.WARNING
    description = ("metric label value flows from an unbounded source "
                   "(pid/uuid/time/path call or a loop variable over an "
                   "open-ended iterable) — each value mints a new series "
                   "and exhausts the cardinality cap")
    fix_hint = ("label with a bounded validated slug (see obs.tenant"
                ".TenantContext), a fixed enum, or aggregate the dimension "
                "away; justify a genuinely bounded dynamic label with an "
                "inline '# graftlint: disable=GL-O005' comment")

    def check(self, tree, ctx):
        scopes = [tree.body]
        scopes.extend(n.body for n in ctx.by_type(ast.FunctionDef,
                                                  ast.AsyncFunctionDef))
        for body in scopes:
            yield from self._check_scope(body, ctx)

    def _check_scope(self, body, ctx):
        env = {}
        calls = []
        for node in _walk_scope(body):
            if isinstance(node, (ast.For, ast.AsyncFor)) \
                    and not _bounded_iter(node.iter):
                for target in ast.walk(node.target):
                    if isinstance(target, ast.Name):
                        env[target.id] = ("the loop over %s"
                                          % (ast.unparse(node.iter)[:40]))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                reason = _tainted(node.value, {})
                if reason:
                    env[node.targets[0].id] = reason
            elif isinstance(node, ast.Call) \
                    and _call_name(node) in _METRIC_FACTORIES \
                    and isinstance(node.func, ast.Attribute):
                calls.append(node)
        for call in calls:
            for kw in call.keywords:
                if kw.arg is None or kw.arg == "help":
                    continue
                reason = _tainted(kw.value, env)
                if reason:
                    yield ctx.finding(
                        self, call,
                        "label %s= flows from %s — an unbounded label value "
                        "mints a fresh series per occurrence and exhausts "
                        "the registry's cardinality cap"
                        % (kw.arg, reason))


#: provenance/trace span-recording calls whose time arguments MUST be
#: ``perf_counter`` samples: spans land on the recorder's perf timeline and
#: cross-process blobs are aligned through a (wall, perf) anchor pair, so a
#: wall-clock sample fed here is on the wrong timeline entirely
_SPAN_SINKS = frozenset((
    "add_span", "add_item_span", "batch_span", "transfer_span",
))


class WallClockSpanRule(Rule):
    """GL-O006: a wall-clock sample fed to a span sink (or a ``perf_anchor``).

    The provenance/trace planes keep every span on the process-local
    ``perf_counter`` timeline; wall time enters exactly once, as the
    ``(wall, perf)`` anchor pair that clock-aligns cross-process and
    cross-wire merges (``absorb_child``, the fleet ``merge_exports``). A
    ``time.time()`` sample passed as a span endpoint puts the span on the
    wrong timeline — after anchor alignment it lands decades off and every
    fold/merge built on it is garbage; a wall sample passed as a
    ``perf_anchor=`` poisons the alignment base itself, skewing EVERY span
    absorbed through it. GL-O001 catches wall-minus-wall durations; this
    rule catches the wall value escaping into the span plane before any
    subtraction happens. Keyword arguments whose names start with ``wall``
    (``wall_anchor=``) are the one sanctioned wall entry point and stay
    clean."""

    rule_id = "GL-O006"
    severity = Severity.WARNING
    description = ("wall-clock (time.time()) sample fed to a provenance/"
                   "trace span sink or perf_anchor — spans live on the "
                   "perf_counter timeline; anchored fleet merges break")
    fix_hint = ("sample time.perf_counter() for span endpoints and "
                "perf anchors; wall time belongs only in wall_anchor= "
                "(the clock-alignment pair), or justify with an inline "
                "'# graftlint: disable=GL-O006' comment")

    def check(self, tree, ctx):
        from petastorm_tpu.analysis.rules._astutil import attr_chain, \
            walk_scope
        from petastorm_tpu.analysis.rules.hotpath import _scopes, \
            _wall_clock_aliases

        aliases = _wall_clock_aliases(ctx)

        def is_wall_call(node):
            return isinstance(node, ast.Call) \
                and attr_chain(node.func) in aliases

        for scope in _scopes(ctx):
            sampled = set()  # names assigned from a time.time() call in scope
            for node in walk_scope(scope):
                if isinstance(node, ast.Assign) and is_wall_call(node.value):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            sampled.add(target.id)

            def derives(node):
                return is_wall_call(node) or (
                    isinstance(node, ast.Name) and node.id in sampled)

            for node in walk_scope(scope):
                if not isinstance(node, ast.Call):
                    continue
                if _call_name(node) in _SPAN_SINKS:
                    for arg in node.args:
                        if derives(arg):
                            yield ctx.finding(
                                self, node,
                                "span endpoint derives from time.time() — "
                                "spans live on the perf_counter timeline; "
                                "after anchor alignment this span lands on "
                                "the wrong clock and breaks every fold/"
                                "merge over it")
                            break
                    for kw in node.keywords:
                        if kw.arg and not kw.arg.startswith("wall") \
                                and derives(kw.value):
                            yield ctx.finding(
                                self, node,
                                "span %s= derives from time.time() — spans "
                                "live on the perf_counter timeline; wall "
                                "time enters only through wall_anchor="
                                % kw.arg)
                else:
                    for kw in node.keywords:
                        if kw.arg == "perf_anchor" and derives(kw.value):
                            yield ctx.finding(
                                self, node,
                                "perf_anchor= derives from time.time() — a "
                                "wall sample as the perf anchor skews the "
                                "alignment base of EVERY span absorbed "
                                "through it")


class SilentExceptionSwallowRule(Rule):
    """GL-O002: ``except Exception: pass`` / bare ``except: pass``."""

    rule_id = "GL-O002"
    severity = Severity.WARNING
    description = ("broad exception silently swallowed (except Exception/bare "
                   "except whose body is only pass)")
    fix_hint = ("route it through petastorm_tpu.obs.log.degradation(cause, ...) "
                "so it is counted and greppable, narrow the except to the "
                "specific expected error, or justify the silence with an "
                "inline '# graftlint: disable=GL-O002' comment")

    def check(self, tree, ctx):
        for node in ctx.by_type(ast.ExceptHandler):
            if not _is_broad(node.type):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                what = "bare except" if node.type is None \
                    else "except %s" % ast.unparse(node.type)
                yield ctx.finding(
                    self, node,
                    "%s swallows every error silently — anti-observability "
                    "(no log, no counter, no flight-record event)" % what)
