"""Rule registry: the project-specific rule families."""
from petastorm_tpu.analysis.rules.concurrency import (
    BlockingTeardownRule,
    LockDisciplineRule,
    OptionsMutationRule,
    ThreadHandlingRule,
)
from petastorm_tpu.analysis.rules.hotpath import WallClockDurationRule
from petastorm_tpu.analysis.rules.lifecycle import ResourceLifecycleRule
from petastorm_tpu.analysis.rules.observability import (
    SilentExceptionSwallowRule,
    SleepyPollLoopRule,
    UnboundedLabelRule,
    UnpairedSpanRule,
    WallClockSpanRule,
)
from petastorm_tpu.analysis.rules.project_concurrency import (
    BlockingUnderLockRule,
    LockOrderCycleRule,
)
from petastorm_tpu.analysis.rules.robustness import (
    StatThenOpenRule,
    UnboundedBlockingCallRule,
    UnboundedSocketRule,
)
from petastorm_tpu.analysis.rules.schema import SchemaCodecContractRule
from petastorm_tpu.analysis.rules.tracing import (
    HostIoInJitRule,
    NumpyInJitRule,
    TracedBranchRule,
)

#: every registered rule class, in reporting order
ALL_RULES = [
    LockDisciplineRule,
    BlockingTeardownRule,
    ThreadHandlingRule,
    OptionsMutationRule,
    ResourceLifecycleRule,
    NumpyInJitRule,
    TracedBranchRule,
    HostIoInJitRule,
    SchemaCodecContractRule,
    WallClockDurationRule,
    SilentExceptionSwallowRule,
    UnpairedSpanRule,
    SleepyPollLoopRule,
    UnboundedLabelRule,
    WallClockSpanRule,
    UnboundedBlockingCallRule,
    StatThenOpenRule,
    UnboundedSocketRule,
]

#: whole-program rules, run once over the ProjectContext after the per-file
#: phase (ISSUE 16)
ALL_PROJECT_RULES = [
    BlockingUnderLockRule,
    LockOrderCycleRule,
]

__all__ = ([cls.__name__ for cls in ALL_RULES]
           + [cls.__name__ for cls in ALL_PROJECT_RULES]
           + ["ALL_RULES", "ALL_PROJECT_RULES"])
