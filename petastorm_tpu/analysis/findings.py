"""Findings model: what a rule reports and how it prints."""
from __future__ import annotations

from dataclasses import dataclass, field


class Severity:
    """Finding severities. Both fail the build (exit 1) — the split is advisory:
    ``ERROR`` findings are near-certain defects, ``WARNING`` findings are hazards
    a human should either fix or baseline with a justification."""

    ERROR = "error"
    WARNING = "warning"


@dataclass
class Finding:
    """One rule violation at a source location."""

    rule_id: str
    severity: str
    path: str  # as given to the engine (CLI prints it verbatim)
    line: int
    col: int
    message: str
    fix_hint: str = ""
    #: stripped source text of ``line`` — the baseline matches on this, not the
    #: line number, so unrelated edits above a baselined finding don't unbaseline it
    code: str = field(default="", repr=False)
    #: last line of the flagged node — an inline suppression anywhere on a
    #: multi-line statement (the natural trailing-comment spot) must apply
    end_line: int = field(default=0, repr=False)

    def format(self, show_hint=True):
        text = "%s:%d:%d: %s [%s] %s" % (
            self.path, self.line, self.col, self.severity, self.rule_id, self.message)
        if show_hint and self.fix_hint:
            text += "\n    hint: %s" % self.fix_hint
        return text

    def to_dict(self):
        return {
            "rule": self.rule_id,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fix_hint": self.fix_hint,
            "code": self.code,
        }
