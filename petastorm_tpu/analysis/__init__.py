"""graftlint: project-native static analysis for petastorm_tpu.

The hot paths of this codebase are exactly the places generic linters go blind:
lock discipline across the executor/loader thread pools (``workers.py``,
``loader.py``), clean reader/executor shutdown, JAX tracing hazards inside
``@jax.jit`` bodies, and the Unischema field/codec contract. ``graftlint`` is an
AST-based rule engine with four project-specific rule families:

- **concurrency** (``GL-C0xx``): shared mutable attributes written outside the
  lock that otherwise guards them; untimed blocking ``Queue.get()``/``join()``
  on stop/shutdown paths; threads started without daemon-or-join handling.
- **resource lifecycle** (``GL-L0xx``): readers/executors/loaders constructed
  but never consumed via a context manager or try/finally.
- **JAX tracing** (``GL-J0xx``): ``np.*`` calls, Python branches on traced
  values, and host I/O inside jitted functions.
- **schema/codec contracts** (``GL-S0xx``): literal ``UnischemaField``
  declarations whose codec cannot faithfully store the declared numpy dtype.

A second, whole-program phase runs after the per-file rules over the same
parsed trees: :class:`~petastorm_tpu.analysis.project.ProjectContext` resolves
lock identities and a one-hop call graph across the corpus, and the project
rules flag blocking-under-lock hangs (GL-C005) and lock-order cycles
(GL-C006) that no single file shows — the PR 13 controller deadlock shape.

Entry points: the ``petastorm-tpu-lint`` console script (exit 0 clean / 1 new
findings / 2 internal error), ``python -m petastorm_tpu.analysis``, or
:func:`analyze_paths` programmatically. Intentional violations are suppressed
inline (``# graftlint: disable=<rule-id>``) or through the checked-in baseline
(``.graftlint-baseline.json``); see docs/static_analysis.md.
"""
from petastorm_tpu.analysis.baseline import Baseline
from petastorm_tpu.analysis.engine import (
    analyze_paths,
    analyze_source,
    default_project_rules,
    default_rules,
)
from petastorm_tpu.analysis.findings import Finding, Severity

__all__ = [
    "Baseline",
    "Finding",
    "Severity",
    "analyze_paths",
    "analyze_source",
    "default_project_rules",
    "default_rules",
]
