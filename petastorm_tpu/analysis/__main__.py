"""``python -m petastorm_tpu.analysis`` — same entry as petastorm-tpu-lint."""
import sys

from petastorm_tpu.analysis.cli import main

sys.exit(main())
