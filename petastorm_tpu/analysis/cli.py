"""``petastorm-tpu-lint`` console script.

Exit codes: 0 = clean (baselined findings allowed), 1 = new findings,
2 = not a lint result — an internal analyzer error, a bad path, or a
command-line usage error (argparse's own convention is also 2). Automation
should branch on 0 vs 1 and treat 2 as "the lint did not run". CI runs this
after ruff (see .github/workflows/ci.yml); developers run it locally as

    petastorm-tpu-lint petastorm_tpu/ tests/ examples/
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

from petastorm_tpu.analysis.baseline import Baseline
from petastorm_tpu.analysis.engine import (
    analyze_paths,
    default_project_rules,
    default_rules,
    iter_python_files,
)

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL_ERROR = 2


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="petastorm-tpu-lint",
        description="Project-native static analysis: concurrency, resource "
                    "lifecycle, JAX tracing, and schema/codec contract rules. "
                    "See docs/static_analysis.md.")
    parser.add_argument("paths", nargs="*", default=["petastorm_tpu"],
                        help="files or directories to analyze "
                             "(default: petastorm_tpu)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: nearest "
                             ".graftlint-baseline.json above the first path)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file (report everything)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept the current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--ignore", default=None,
                        help="comma-separated rule ids to skip")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text",
                        help="output format ('github' emits workflow-command "
                             "annotations — ::error file=...,line=... — so "
                             "findings annotate the PR diff)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also print findings covered by the baseline")
    return parser


def _pick_rules(args):
    """(per-file rules, project rules) after --select/--ignore filtering —
    one id namespace across both registries, so ``--select GL-C005`` runs
    just the project phase and ``--ignore GL-C006`` drops it."""
    rules = default_rules()
    project_rules = default_project_rules()
    if args.select:
        wanted = {r.strip() for r in args.select.split(",")}
        rules = [r for r in rules if r.rule_id in wanted]
        project_rules = [r for r in project_rules if r.rule_id in wanted]
        missing = wanted - {r.rule_id for r in rules} \
            - {r.rule_id for r in project_rules}
        if missing:
            raise ValueError("unknown rule id(s): %s" % ", ".join(sorted(missing)))
    if args.ignore:
        dropped = {r.strip() for r in args.ignore.split(",")}
        rules = [r for r in rules if r.rule_id not in dropped]
        project_rules = [r for r in project_rules
                         if r.rule_id not in dropped]
    return rules, project_rules


def _resolve_baseline(args):
    if args.no_baseline:
        return None
    if args.baseline:
        if os.path.isfile(args.baseline):
            return Baseline.load(args.baseline)
        return Baseline({}, path=args.baseline)  # --write-baseline target
    found = Baseline.find(os.path.dirname(os.path.abspath(args.paths[0]))
                          if os.path.isfile(args.paths[0]) else args.paths[0])
    return Baseline.load(found) if found else None


def _gh_escape(value, in_property=False):
    """Escape per the GitHub workflow-command rules: ``%``/CR/LF always, and
    ``,``/``:`` additionally inside property values."""
    value = str(value).replace("%", "%25").replace("\r", "%0D") \
        .replace("\n", "%0A")
    if in_property:
        value = value.replace(",", "%2C").replace(":", "%3A")
    return value


def _gh_annotation(finding):
    """One ``::error``/``::warning`` workflow command for a finding. Paths are
    repo-relative (annotations only attach to the diff when they match the
    checkout's paths)."""
    level = "error" if str(finding.severity) == "error" else "warning"
    path = os.path.relpath(finding.path).replace(os.sep, "/")
    props = "file=%s,line=%d,col=%d,title=%s" % (
        _gh_escape(path, in_property=True), finding.line, finding.col,
        _gh_escape(finding.rule_id, in_property=True))
    message = finding.message
    if finding.fix_hint:
        message += " — " + finding.fix_hint
    return "::%s %s::%s" % (level, props, _gh_escape(message))


def run(argv=None):
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for rule in default_rules() + default_project_rules():
            print("%s  [%s]  %s" % (rule.rule_id, rule.severity, rule.description))
        return EXIT_CLEAN

    rules, project_rules = _pick_rules(args)
    findings, n_suppressed = analyze_paths(args.paths, rules,
                                           project_rules=project_rules)
    baseline = _resolve_baseline(args)

    if args.write_baseline:
        path = (baseline.path if baseline is not None
                else os.path.join(os.getcwd(), ".graftlint-baseline.json"))
        root = os.path.dirname(os.path.abspath(path))
        analyzed = {
            os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
            for p in iter_python_files(args.paths)
        }
        updated = Baseline.from_findings(
            findings, path, previous=baseline, analyzed_paths=analyzed,
            run_rules={r.rule_id for r in rules + project_rules})
        updated.save(path)
        print("wrote %d baseline entr%s to %s" % (
            len(updated.entries), "y" if len(updated.entries) == 1 else "ies",
            path))
        return EXIT_CLEAN

    if baseline is not None:
        new, baselined = baseline.filter(findings)
        stale = baseline.stale_entries(findings)
    else:
        new, baselined, stale = findings, [], []

    if args.format == "github":
        for f in new:
            print(_gh_annotation(f))
        print("%d finding%s, %d baselined, %d suppressed inline" % (
            len(new), "" if len(new) == 1 else "s", len(baselined),
            n_suppressed))
    elif args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "suppressed_inline": n_suppressed,
            "stale_baseline_entries": [list(k) for k in stale],
        }, indent=2))
    else:
        for f in new:
            print(f.format())
        if args.show_baselined and baselined:
            print("\nbaselined findings:")
            for f in baselined:
                print("  " + f.format(show_hint=False))
        if stale:
            print("\nnote: %d stale baseline entr%s (fixed findings — run "
                  "--write-baseline to prune):" % (
                      len(stale), "y" if len(stale) == 1 else "ies"))
            for rule, path, code in stale:
                print("  %s %s: %s" % (rule, path, code))
        summary = "%d finding%s" % (len(new), "" if len(new) == 1 else "s")
        if baselined:
            summary += ", %d baselined" % len(baselined)
        if n_suppressed:
            summary += ", %d suppressed inline" % n_suppressed
        print(summary)
    return EXIT_FINDINGS if new else EXIT_CLEAN


def main(argv=None):
    try:
        return run(argv)
    except KeyboardInterrupt:
        return 130  # conventional SIGINT code — NOT an internal error
    except BrokenPipeError:
        return 141  # downstream pager/head closed the pipe — not our bug
    except SystemExit:
        raise
    except Exception:  # noqa: BLE001 — exit 2 is the internal-error contract
        traceback.print_exc()
        return EXIT_INTERNAL_ERROR


if __name__ == "__main__":
    sys.exit(main())
