"""Whole-program analysis context (ISSUE 16).

The per-file rules see one module at a time, which is exactly why the PR 13
deadlock escaped them: ``_DONE`` was posted while holding ``_active_lock``
*through a helper call*, and the helper's blocking ``Queue.put()`` lived three
screens away from the ``with`` block. :class:`ProjectContext` gives rules the
project-wide view those bugs hide in:

- **one index over the already-parsed corpus** — the engine hands over the
  same :class:`~petastorm_tpu.analysis.engine.FileContext` objects the
  per-file phase used (no re-read, no re-parse), and this module indexes
  modules, classes, and methods over them;

- **lock identities** — every ``self.<attr>`` bound to a
  ``threading.Lock``/``RLock``/``Condition`` constructor is a tracked lock,
  keyed ``(class, attr)``. A lock *passed between constructors*
  (``self._b = Helper(self._lock)`` where ``Helper.__init__`` stores the
  parameter onto ``self``) is unified into ONE identity via union-find, so an
  acquisition through either name feeds the same lock-order node;

- **receiver typing project-style** — queues (with boundedness: ``put`` only
  blocks on a maxsize'd queue), Events, Threads, ``Connection``s, sockets and
  executor-built futures bound to ``self.<attr>`` anywhere in a class;

- **a conservative one-level intra-module call graph** — ``self.helper(...)``
  resolves to the same class's method, a bare ``helper(...)`` to a
  module-level ``def`` in the same file. One hop only, resolution must be
  unambiguous, and anything dynamic resolves to nothing: the goal is zero
  false edges, not completeness.

On top sit the project rules (``rules/project_concurrency.py``): GL-C005
(blocking call reached while a tracked lock is held, including through one
call hop — the PR 13 shape) and GL-C006 (lock-order cycles across the global
acquisition graph, reported with both witness paths).
"""
from __future__ import annotations

import ast
import os

from petastorm_tpu.analysis.rules._astutil import (
    attr_chain,
    call_kwarg,
    self_attr,
)

#: lock constructors → kind; Condition is a lock (acquired via ``with``) whose
#: ``wait()`` additionally RELEASES it while blocked — the rules special-case
#: that
_LOCK_KINDS = {
    "threading.Lock": "lock", "Lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "condition", "Condition": "condition",
}

#: queue constructors whose ``put`` can block when a maxsize is given
_QUEUE_CTORS = frozenset((
    "queue.Queue", "Queue", "queue.LifoQueue", "LifoQueue",
    "queue.PriorityQueue", "PriorityQueue", "queue.JoinableQueue",
    "JoinableQueue", "multiprocessing.Queue", "mp.Queue",
))
#: unbounded by construction: ``put`` never blocks, ``get`` still does
_SIMPLE_QUEUE_CTORS = frozenset(("queue.SimpleQueue", "SimpleQueue"))
_EVENT_CTORS = frozenset(("threading.Event", "Event"))
_THREAD_CTORS = frozenset(("threading.Thread", "Thread", "threading.Timer",
                           "Timer", "multiprocessing.Process", "Process",
                           "mp.Process"))
_CONN_CTORS = frozenset(("Client", "multiprocessing.connection.Client"))
_SOCK_CTORS = frozenset(("socket.socket", "socket.create_connection",
                         "create_connection"))

#: socket methods that block on a quiet peer (both directions: a full send
#: buffer against a stalled reader parks ``send``/``sendall`` too)
_SOCK_BLOCKING = frozenset(("recv", "recv_into", "recvfrom", "accept",
                            "connect", "send", "sendall"))


def _bounded_arg(node):
    """True when an explicit timeout argument actually bounds the call: any
    expression except the literal ``None`` (which is "block forever" spelled
    out). Dynamic timeouts are assumed real."""
    return node is not None and not (
        isinstance(node, ast.Constant) and node.value is None)


def _iter_methods(cls_node):
    for node in cls_node.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class ClassInfo:
    """One class's project-phase typing: methods by name plus the
    ``self.<attr>`` receiver types collected from every constructor
    assignment in the class body."""

    def __init__(self, module, node):
        self.module = module
        self.node = node
        self.name = node.name
        self.qualname = "%s.%s" % (module.name, node.name)
        self.methods = {m.name: m for m in _iter_methods(node)}
        self.lock_attrs = {}     # attr -> "lock" | "rlock" | "condition"
        self.queue_attrs = {}    # attr -> bool (True: put can block)
        self.event_attrs = set()
        self.thread_attrs = set()
        self.conn_attrs = set()
        self.sock_attrs = set()
        self.sock_bounded = set()
        self.future_attrs = set()
        #: __init__ parameter name -> self attr it is stored to (lock-identity
        #: unification input)
        self.init_param_attrs = {}

    def collect(self):
        init = self.methods.get("__init__")
        init_params = set()
        if init is not None:
            args = init.args
            init_params = {a.arg for a in (args.posonlyargs + args.args
                                           + args.kwonlyargs)} - {"self"}
        for method in self.methods.values():
            for node in ast.walk(method):
                if isinstance(node, ast.Assign):
                    self._collect_assign(node, method, init_params)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "settimeout" and node.args:
                    recv = self_attr(node.func.value)
                    if recv is None:
                        continue
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and arg.value is None:
                        self.sock_bounded.discard(recv)
                    else:
                        self.sock_bounded.add(recv)

    def _collect_assign(self, node, method, init_params):
        value = node.value
        chain = attr_chain(value.func) if isinstance(value, ast.Call) else None
        for target in node.targets:
            attr = self_attr(target)
            if attr is None:
                continue
            if chain in _LOCK_KINDS:
                self.lock_attrs[attr] = _LOCK_KINDS[chain]
            elif chain in _QUEUE_CTORS:
                self.queue_attrs[attr] = self._queue_possibly_bounded(value)
            elif chain in _SIMPLE_QUEUE_CTORS:
                self.queue_attrs[attr] = False
            elif chain in _EVENT_CTORS:
                self.event_attrs.add(attr)
            elif chain in _THREAD_CTORS:
                self.thread_attrs.add(attr)
            elif chain is not None and \
                    chain.split(".")[-1] in ("Client",) and \
                    (chain in _CONN_CTORS):
                self.conn_attrs.add(attr)
            elif chain in _SOCK_CTORS:
                self.sock_attrs.add(attr)
                if chain.endswith("create_connection") and \
                        _bounded_arg(call_kwarg(value, "timeout")):
                    self.sock_bounded.add(attr)
            elif isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Attribute) and \
                    value.func.attr == "submit":
                # an executor-built future: `self._fut = pool.submit(...)`
                self.future_attrs.add(attr)
            elif method.name == "__init__" and isinstance(value, ast.Name) \
                    and value.id in init_params:
                self.init_param_attrs[value.id] = attr

    @staticmethod
    def _queue_possibly_bounded(call):
        """Whether ``put`` on this queue can block. ``Queue()`` and
        ``Queue(0)`` are infinite (put never blocks); a literal positive
        maxsize is bounded; a DYNAMIC maxsize is treated as bounded — pipeline
        queues are bounded by design, and an unbounded one would not need the
        parameter."""
        maxsize = call.args[0] if call.args else call_kwarg(call, "maxsize")
        if maxsize is None:
            return False
        if isinstance(maxsize, ast.Constant):
            try:
                return maxsize.value is not None and int(maxsize.value) > 0
            except (TypeError, ValueError):
                return True
        return True


class ModuleInfo:
    """One parsed file: its FileContext plus class/function indexes."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.path = ctx.path
        self.name = os.path.splitext(os.path.basename(ctx.path))[0]
        self.tree = ctx.tree
        self.classes = {}    # class name -> ClassInfo (module-level classes)
        self.functions = {}  # module-level def name -> FunctionDef
        self.sleep_aliases = {"time.sleep"}
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassInfo(self, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        for node in ctx.by_type(ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        self.sleep_aliases.add(a.asname or "sleep")

    def rel_label(self):
        """Short path label for witness messages (basename keeps messages
        readable; the Finding itself carries the full path)."""
        return os.path.basename(self.path)


class BlockingSite:
    """One blocking call found by the classifier: where, why, and — for a
    ``Condition.wait`` — which lock identity the wait legitimately holds."""

    __slots__ = ("node", "reason", "cond_key")

    def __init__(self, node, reason, cond_key=None):
        self.node = node
        self.reason = reason
        self.cond_key = cond_key


class ProjectContext:
    """The whole-program index: built once per lint run from the parsed
    corpus, shared by every :class:`ProjectRule`."""

    def __init__(self, file_contexts):
        self.modules = [ModuleInfo(ctx) for ctx in file_contexts]
        self.modules_by_path = {m.path: m for m in self.modules}
        self._classes_by_name = {}
        for module in self.modules:
            for cls in module.classes.values():
                self._classes_by_name.setdefault(cls.name, []).append(cls)
        for module in self.modules:
            for cls in module.classes.values():
                cls.collect()
        self._alias_parent = {}  # union-find over (class_qualname, attr) keys
        self._lock_labels = {}   # representative key -> display label
        self._unify_ctor_passed_locks()
        self._summaries = {}

    # -- lock identity -----------------------------------------------------------------

    def _find(self, key):
        parent = self._alias_parent.get(key, key)
        if parent == key:
            return key
        root = self._find(parent)
        self._alias_parent[key] = root
        return root

    def _union(self, a, b):
        ra, rb = self._find(a), self._find(b)
        if ra == rb:
            return
        # deterministic representative: lexicographically smaller key wins
        lo, hi = (ra, rb) if ra <= rb else (rb, ra)
        self._alias_parent[hi] = lo

    def lock_id(self, cls, attr):
        """Canonical identity key for ``cls``'s lock attribute ``attr``."""
        return self._find((cls.qualname, attr))

    def lock_label(self, key):
        """``Class._attr`` display label for a canonical lock key."""
        qual, attr = key
        return "%s.%s" % (qual.split(".", 1)[1], attr)

    def _unify_ctor_passed_locks(self):
        """``self._b = Helper(self._lock)`` where ``Helper.__init__`` stores
        the parameter onto ``self`` makes the two attributes ONE lock. Only
        unambiguous targets unify: the callee's last dotted segment must name
        exactly one class in the project."""
        for module in self.modules:
            for cls in module.classes.values():
                for method in cls.methods.values():
                    for node in ast.walk(method):
                        if isinstance(node, ast.Call):
                            self._unify_call(cls, node)

    def _unify_call(self, caller, call):
        chain = attr_chain(call.func)
        if chain is None:
            return
        candidates = self._classes_by_name.get(chain.split(".")[-1])
        if candidates is None or len(candidates) != 1:
            return
        callee = candidates[0]
        if not callee.init_param_attrs:
            return
        init = callee.methods.get("__init__")
        if init is None:
            return
        params = [a.arg for a in (init.args.posonlyargs + init.args.args)]
        if params and params[0] == "self":
            params = params[1:]
        pairs = []
        for i, arg in enumerate(call.args):
            if i < len(params):
                pairs.append((params[i], arg))
        for kw in call.keywords:
            if kw.arg is not None:
                pairs.append((kw.arg, kw.value))
        for param, arg in pairs:
            stored_attr = callee.init_param_attrs.get(param)
            if stored_attr is None:
                continue
            passed_attr = self_attr(arg)
            if passed_attr is None or passed_attr not in caller.lock_attrs:
                continue
            callee.lock_attrs.setdefault(
                stored_attr, caller.lock_attrs[passed_attr])
            self._union((caller.qualname, passed_attr),
                        (callee.qualname, stored_attr))

    # -- call graph --------------------------------------------------------------------

    def resolve_call(self, module, cls, call):
        """One-level intra-module resolution: ``self.m(...)`` → the same
        class's method, bare ``f(...)`` → a module-level def of the same
        file. Returns ``(owner_cls_or_None, FunctionDef)`` or None."""
        func = call.func
        if isinstance(func, ast.Attribute) and cls is not None and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            target = cls.methods.get(func.attr)
            if target is not None:
                return cls, target
            return None
        if isinstance(func, ast.Name):
            target = module.functions.get(func.id)
            if target is not None:
                return None, target
        return None

    # -- blocking-call classification --------------------------------------------------

    def blocking_reason(self, module, cls, call):
        """Classify one Call as an unbounded blocking call under the typing
        env of ``cls``/``module``. Returns a :class:`BlockingSite` or None.

        Timed variants are clean everywhere here: a bounded wait under a lock
        is a latency bug at worst, not a deadlock."""
        func = call.func
        chain = attr_chain(func)
        if chain in module.sleep_aliases:
            return BlockingSite(call, "`%s(...)` sleeps" % chain)
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        attr = self_attr(func.value) if cls is not None else None
        if attr is None:
            return None
        if attr in cls.queue_attrs:
            if meth == "get" and not self._get_bounded(call):
                return BlockingSite(
                    call, "`self.%s.get()` has no timeout" % attr)
            if meth == "put" and cls.queue_attrs[attr] and \
                    not self._put_bounded(call):
                return BlockingSite(
                    call, "`self.%s.put()` has no timeout and the queue is "
                          "bounded" % attr)
        elif attr in cls.event_attrs:
            if meth == "wait" and not self._first_arg_bounded(call):
                return BlockingSite(
                    call, "`self.%s.wait()` has no timeout" % attr)
        elif attr in cls.thread_attrs:
            if meth == "join" and not self._first_arg_bounded(call):
                return BlockingSite(
                    call, "`self.%s.join()` has no timeout" % attr)
        elif attr in cls.conn_attrs:
            if meth in ("recv", "recv_bytes", "send", "send_bytes"):
                return BlockingSite(
                    call, "`self.%s.%s()` on a Connection blocks with no "
                          "timeout parameter" % (attr, meth))
        elif attr in cls.sock_attrs and attr not in cls.sock_bounded:
            if meth in _SOCK_BLOCKING:
                return BlockingSite(
                    call, "`self.%s.%s()` on a socket with no settimeout"
                          % (attr, meth))
        elif attr in cls.future_attrs:
            if meth == "result" and not self._first_arg_bounded(call):
                return BlockingSite(
                    call, "`self.%s.result()` has no timeout" % attr)
        elif attr in cls.lock_attrs and \
                cls.lock_attrs[attr] == "condition":
            if meth in ("wait", "wait_for"):
                timeout = call_kwarg(call, "timeout")
                pos = 1 if meth == "wait_for" else 0
                if len(call.args) > pos:
                    timeout = call.args[pos]
                if not _bounded_arg(timeout):
                    return BlockingSite(
                        call,
                        "`self.%s.%s()` has no timeout" % (attr, meth),
                        cond_key=self.lock_id(cls, attr))
        return None

    @staticmethod
    def _get_bounded(call):
        """``Queue.get(block, timeout)``: non-blocking or timed forms."""
        if _bounded_arg(call_kwarg(call, "timeout")):
            return True
        if len(call.args) >= 2:
            return _bounded_arg(call.args[1])
        if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
                and not call.args[0].value:
            return True
        block = call_kwarg(call, "block")
        return block is not None and isinstance(block, ast.Constant) \
            and not block.value

    @staticmethod
    def _put_bounded(call):
        """``Queue.put(item, block, timeout)``: non-blocking or timed forms."""
        if _bounded_arg(call_kwarg(call, "timeout")):
            return True
        if len(call.args) >= 3:
            return _bounded_arg(call.args[2])
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
                and not call.args[1].value:
            return True
        block = call_kwarg(call, "block")
        return block is not None and isinstance(block, ast.Constant) \
            and not block.value

    @staticmethod
    def _first_arg_bounded(call):
        """``join(timeout)`` / ``wait(timeout)`` / ``result(timeout)``."""
        if _bounded_arg(call_kwarg(call, "timeout")):
            return True
        return len(call.args) >= 1 and _bounded_arg(call.args[0])

    # -- function summaries (the one-hop seam) -----------------------------------------

    def summary(self, module, cls, func):
        """What calling ``func`` can do while the CALLER holds a lock:
        ``blocking`` — BlockingSites anywhere in its body (nested defs
        excluded: they run later, elsewhere); ``acquires`` — lock identities
        it takes via ``with self.<lock>``. Cached per function."""
        key = id(func)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached
        blocking, acquires = [], []
        stack = list(func.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # deferred execution: not part of this call
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if cls is not None and attr in cls.lock_attrs:
                        acquires.append(
                            (self.lock_id(cls, attr), item.context_expr))
            if isinstance(node, ast.Call):
                site = self.blocking_reason(module, cls, node)
                if site is not None:
                    blocking.append(site)
            stack.extend(ast.iter_child_nodes(node))
        result = {"blocking": blocking, "acquires": acquires}
        self._summaries[key] = result
        return result

    # -- lock-region walking -----------------------------------------------------------

    def lock_region_events(self, module, cls, method):
        """Walk one method yielding, in source order:

        - ``("acquire", lock_key, node, held_before)`` at each ``with
          self.<lock>`` entry;
        - ``("block", BlockingSite, held)`` at each unbounded blocking call;
        - ``("call", call_node, (owner, funcdef), held)`` at each resolvable
          one-hop call.

        ``held`` is the frozenset of lock identities lexically held. Nested
        function bodies are walked with an EMPTY held set — a closure runs
        later, usually on another thread, when the lock is no longer held
        (same principle as GL-C001's collector)."""
        events = []
        self._walk_region(module, cls, method.body, frozenset(), events)
        return events

    def _walk_region(self, module, cls, body, held, events):
        for node in body:
            self._visit_region(module, cls, node, held, events)

    def _visit_region(self, module, cls, node, held, events):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._walk_region(module, cls, node.body, frozenset(), events)
            return
        if isinstance(node, ast.Lambda):
            self._visit_region(module, cls, node.body, frozenset(), events)
            return
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                attr = self_attr(item.context_expr)
                if attr is not None and attr in cls.lock_attrs:
                    key = self.lock_id(cls, attr)
                    events.append(("acquire", key, item.context_expr, inner))
                    inner = inner | {key}
                else:
                    self._visit_region(module, cls, item.context_expr, held,
                                       events)
            self._walk_region(module, cls, node.body, inner, events)
            return
        if isinstance(node, ast.Call):
            site = self.blocking_reason(module, cls, node)
            if site is not None:
                events.append(("block", site, held))
            else:
                resolved = self.resolve_call(module, cls, node)
                if resolved is not None:
                    events.append(("call", node, resolved, held))
        for child in ast.iter_child_nodes(node):
            self._visit_region(module, cls, child, held, events)
